#!/usr/bin/env python
"""train.py — CLI entrypoint (SURVEY H1; BASELINE.json:5 "the same train.py
entrypoint").

Usage mirrors the reference harness:

    python train.py --config resnet18_cifar10
    python train.py --config llama2_7b --set optim.learning_rate=1e-4 \\
        --set mesh.fsdp=8 --set data.batch_size=64
    python train.py --config-json path/to/config.json --resume auto

Where the reference needed `torchrun --nproc-per-node=8 train.py` (SURVEY
§3.1), here the same script runs unmodified from 1 chip to a pod: bring-up is
jax.distributed.initialize (launch.py), and parallelism is the `mesh.*`
config, not a launcher topology.
"""

from __future__ import annotations

import argparse
import json
import sys


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--config", default="resnet18_cifar10",
                   help="preset name (see --list-configs)")
    p.add_argument("--config-json", default="",
                   help="path to a full TrainConfig JSON (overrides --config)")
    p.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                   help="dotted config override, e.g. optim.learning_rate=0.1")
    p.add_argument("--resume", default="",
                   help="shortcut for checkpoint.resume: auto | none | "
                        "/path/to/another/run's/checkpoint/dir")
    p.add_argument("--steps", type=int, default=0,
                   help="cap total steps (smoke runs)")
    p.add_argument("--list-configs", action="store_true")
    p.add_argument("--print-config", action="store_true",
                   help="print resolved config JSON and exit")
    p.add_argument("--eval-only", action="store_true",
                   help="restore the latest checkpoint (per --resume) and "
                        "run one validation pass, then exit — the "
                        "reference's validate() mode")
    p.add_argument("--compile-only", action="store_true",
                   help="AOT-compile the train step and print the "
                        "compiler's per-device memory report (one JSON "
                        "line), then exit without training — the "
                        "'will this config fit' probe")
    p.add_argument("--find-batch-size", action="store_true",
                   help="AOT-probe the largest fitting GLOBAL batch "
                        "(double then bisect on the compiler's per-device "
                        "memory accounting; no step executes) and print "
                        "one JSON line, then exit")
    p.add_argument("--hbm-gb", type=float, default=0.0,
                   help="with --find-batch-size: per-device memory budget "
                        "in GiB (default: the device's reported limit; "
                        "REQUIRED on CPU backends, whose temps are an "
                        "upper bound — see tools/memfit_7b.py)")
    p.add_argument("--export-safetensors", default="", metavar="PATH",
                   help="restore the latest checkpoint (or init) and write "
                        "a torch-layout safetensors file, then exit "
                        "(interop.py bridge)")
    p.add_argument("--import-safetensors", default="", metavar="PATH",
                   help="warm-start model params from a (torch-layout) "
                        "safetensors file before training")
    return p.parse_args(argv)


def build_config(args):
    from pytorch_distributed_train_tpu.config import TrainConfig, get_preset

    if args.config_json:
        with open(args.config_json) as f:
            cfg = TrainConfig.from_dict(json.load(f))
    else:
        cfg = get_preset(args.config)
    cfg.apply_overrides(args.set)
    if args.resume:
        cfg.checkpoint.resume = args.resume
    if args.steps:
        cfg.total_steps = args.steps
        cfg.epochs = 0
    return cfg


def main(argv=None) -> int:
    # PDTT_SANITIZE=1 (exported by `tools/chaos_soak.py --sanitize` to
    # elastic worker subprocesses): tsan-lite lock/thread wrappers on
    # from the first import — utils/syncdbg.py, docs/static_analysis.md
    from pytorch_distributed_train_tpu.utils import syncdbg

    syncdbg.maybe_activate()
    args = parse_args(argv)
    if args.list_configs:
        from pytorch_distributed_train_tpu.config import list_presets

        print("\n".join(list_presets()))
        return 0

    try:
        cfg = build_config(args)
    except (KeyError, ValueError, FileNotFoundError) as e:
        # Config mistakes (unknown preset, typo'd --set path, bad value)
        # are user errors, not crashes: one clear line, exit 2 — the
        # argparse convention — instead of a traceback.
        msg = e.args[0] if e.args else e
        print(f"train.py: error: {msg}", file=sys.stderr, flush=True)
        return 2
    if args.print_config:
        print(cfg.to_json())
        return 0

    if cfg.train.overlap_collectives:
        # Latency-hiding scheduler preset (config.py — jax-free, so this
        # runs BEFORE the jax-importing modules below initialize a
        # backend): without it the bucketed in-scan reductions compile
        # but serialize after compute, and the knob silently measures
        # nothing. TPU backends only — XLA:CPU/GPU reject unknown
        # --xla_tpu_* flags fatally (same gate as bench.py).
        import importlib.util
        import os

        plat = os.environ.get("JAX_PLATFORMS", "")
        if "tpu" in plat or (
                plat == "" and
                importlib.util.find_spec("libtpu") is not None):
            from pytorch_distributed_train_tpu.config import (
                ensure_latency_hiding_flags,
            )

            if ensure_latency_hiding_flags():
                print("[launch] overlap_collectives: appended the "
                      "latency-hiding scheduler preset to XLA_FLAGS",
                      flush=True)

    from pytorch_distributed_train_tpu.launch import initialize_distributed, runtime_info
    from pytorch_distributed_train_tpu.trainer import Trainer

    initialize_distributed()
    info = runtime_info()
    if info["process_index"] == 0:
        print(f"[launch] {info}", flush=True)
        print(f"[config] preset={cfg.preset}", flush=True)

    trainer = Trainer(cfg)
    if args.export_safetensors:
        from pytorch_distributed_train_tpu.interop import (
            save_torch_safetensors,
        )

        # Trainer construction already auto-resumed the latest checkpoint.
        params = trainer.state.params
        if cfg.lora.rank > 0:
            # Merge adapters into the base kernels: the exported file is a
            # plain base-model checkpoint (no lora_* tensors, which the
            # torch name mapping has no names for anyway).
            from pytorch_distributed_train_tpu import lora as lora_lib

            params = lora_lib.strip(params, cfg.lora)
        save_torch_safetensors(params, args.export_safetensors)
        print(f"[interop] exported params → {args.export_safetensors}",
              flush=True)
        trainer.close()
        return 0
    if args.import_safetensors:
        trainer.import_params(args.import_safetensors)
    if args.compile_only:
        report = trainer.compile_report()
        print(json.dumps({"compile_only": True, "preset": cfg.preset,
                          **report}), flush=True)
        trainer.close()
        return 0
    if args.find_batch_size:
        budget = int(args.hbm_gb * 1024**3) if args.hbm_gb else None
        report = trainer.find_batch_size(budget_bytes=budget)
        print(json.dumps({"find_batch_size": True, "preset": cfg.preset,
                          **report}), flush=True)
        trainer.close()
        return 0 if report["best_global"] else 4
    if args.eval_only:
        if not (trainer.resumed or args.import_safetensors):
            print("[eval-only] ERROR: no checkpoint restored and no "
                  "--import-safetensors — refusing to validate "
                  "randomly-initialized weights", file=sys.stderr, flush=True)
            trainer.close()
            return 2
        metrics = trainer.evaluate(int(trainer.state.step))
        trainer.close()
        return 0 if metrics else 1
    trainer.fit()
    trainer.close()
    if syncdbg.active():
        # Sanitized run (chaos_soak --sanitize exports PDTT_SANITIZE=1
        # to worker subprocesses): a concurrency finding in THIS
        # process must reach the supervising soak, and the exit code is
        # the only channel — rc 57, distinct from every fault-drill rc.
        # Checked BEFORE the preemption exit code: a preempted worker
        # with findings must not report the clean resume contract.
        syncdbg.check_teardown()
        summary = syncdbg.findings_summary()
        if summary:
            print(f"[sanitizer] findings: {summary} — failing the run",
                  file=sys.stderr, flush=True)
            return 57
    if trainer.preempted:
        # Graceful SIGTERM preemption: the loop already checkpointed and
        # the summary carries the `preempted` marker; the exit code is
        # the operator's contract with the supervisor (default 0 =
        # clean, so a whole-job reschedule resumes from the checkpoint).
        return cfg.faults.preempt_exit_code
    return 0


if __name__ == "__main__":
    sys.exit(main())
