#!/usr/bin/env python
"""chaos_soak — run a short training job under a randomized (seeded)
fault schedule and prove it absorbed the chaos.

The CI face of the faults/ layer (ISSUE 2 satellite): where the unit
tests script one fault each, the soak composes several — transient
checkpoint save I/O errors, flaky record decodes, a straggling step —
drawn from a seeded RNG so a failing schedule is exactly reproducible
by seed. Acceptance:

- training completes all steps;
- ``retries_total`` > 0 (the faults actually fired AND were absorbed
  by the retry policies, not skipped);
- the final checkpoint exists and passes manifest verification
  (faults/integrity.py) at the expected step.

Usage::

    python tools/chaos_soak.py [--seed 0] [--steps 8] [--out DIR]
    python tools/chaos_soak.py --shrink [--seed 0] [--steps 6]

``--shrink`` runs the elastic shrink drill instead (docs/elastic.md):
a 2-node tpurun gang (``min_nnodes=1``) where node 1 fires the
``elastic.shrink`` fault point mid-run and NEVER comes back (its agent
has no restart budget). Acceptance: the surviving node re-rendezvouses
degraded, restores the last checkpoint resharded onto the 1-host
world, finishes the horizon with a monotone per-generation step count,
the final checkpoint passes manifest verification at the horizon step,
and the event journal carries the ``elastic``/``reshard`` record.

Prints one JSON report line; exit 0 = pass. Registered as slow-marked
tests (tests/test_chaos_soak.py) so tier-1 stays fast.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_schedule(seed: int, steps: int, attempts: int) -> list[str]:
    """Randomized-but-reproducible schedule. Injected transient counts
    stay BELOW the retry budget (count < attempts) so every fault is
    absorbable — the soak proves recovery, not failure."""
    rng = random.Random(seed)
    specs = [
        # 1-2 transient ckpt save failures at a random cadence step
        f"ckpt.save_io@step={rng.randrange(2, max(3, steps))}"
        f":count={rng.randrange(1, attempts)}:gen=-1",
        # a flaky decode early in the run
        f"data.decode@call={rng.randrange(1, 4)}"
        f":count={rng.randrange(1, attempts)}:gen=-1",
        # one short straggle
        f"step.straggle@step={rng.randrange(1, steps + 1)}"
        f":count=1:delay=0.2:gen=-1",
    ]
    if rng.random() < 0.5:
        # probabilistic decode noise, seeded via faults.seed
        specs.append(f"data.decode@p=0.05:count={attempts - 1}:gen=-1")
    return specs


def run_soak(seed: int = 0, steps: int = 8, out_dir: str = "") -> dict:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from pytorch_distributed_train_tpu.checkpoint import CheckpointManager
    from pytorch_distributed_train_tpu.config import (
        CheckpointConfig,
        TrainConfig,
    )
    from pytorch_distributed_train_tpu.faults import integrity
    from pytorch_distributed_train_tpu.obs.registry import get_registry
    from pytorch_distributed_train_tpu.trainer import Trainer

    out_dir = out_dir or tempfile.mkdtemp(prefix="chaos-soak-")
    cfg = TrainConfig()
    cfg.model.name = "resnet18"
    cfg.model.num_classes = 10
    cfg.model.image_size = 8
    cfg.data.dataset = "synthetic_images"
    cfg.data.synthetic_size = 256
    cfg.data.batch_size = 32
    cfg.data.num_workers = 1
    cfg.optim.name = "momentum"
    cfg.optim.learning_rate = 0.05
    cfg.optim.schedule = "constant"
    cfg.optim.warmup_steps = 0
    cfg.total_steps = steps
    cfg.checkpoint.dir = os.path.join(out_dir, "ckpt")
    cfg.checkpoint.save_every_steps = 2
    cfg.checkpoint.async_save = False
    cfg.obs.log_every_steps = max(1, steps // 2)
    cfg.obs.jsonl_path = os.path.join(out_dir, "metrics.jsonl")
    cfg.faults.seed = seed
    schedule = build_schedule(seed, steps, cfg.faults.retry_max_attempts)
    cfg.faults.inject = tuple(schedule)

    trainer = Trainer(cfg)
    trainer.fit()
    trainer.close()

    reg = get_registry()
    injected = reg.family_total("faults_injected_total")
    retries = reg.family_total("retries_total")
    mgr = CheckpointManager(CheckpointConfig(dir=cfg.checkpoint.dir,
                                             async_save=False))
    final_step = mgr.latest_good_step()
    verified = (final_step is not None
                and integrity.verify_step(mgr.dir, final_step)[0] is True)
    mgr.close()
    report = {
        "seed": seed,
        "steps": steps,
        "schedule": schedule,
        "faults_injected_total": injected,
        "retries_total": retries,
        "records_skipped_total": reg.family_total("records_skipped_total"),
        "final_good_step": final_step,
        "final_manifest_verified": bool(verified),
        "out_dir": out_dir,
    }
    report["ok"] = bool(
        final_step == steps and verified and retries > 0 and injected > 0)
    return report


_SHRINK_WORKER = """
import os, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from pytorch_distributed_train_tpu.config import TrainConfig
from pytorch_distributed_train_tpu.trainer import Trainer

rank = int(os.environ["PROCESS_ID"])
out = {out!r}
cfg = TrainConfig()
cfg.model.name = "resnet18"; cfg.model.num_classes = 10
cfg.model.image_size = 8
cfg.data.dataset = "synthetic_images"; cfg.data.synthetic_size = 48
cfg.data.batch_size = 12; cfg.data.num_workers = 1
cfg.data.elastic_shards = True
cfg.optim.name = "momentum"; cfg.optim.learning_rate = 0.05
cfg.optim.schedule = "constant"; cfg.optim.warmup_steps = 0
cfg.total_steps = {steps}
cfg.checkpoint.dir = os.path.join(out, f"ckpt-{{rank}}")
cfg.checkpoint.save_every_steps = 2
cfg.checkpoint.tiered = True
cfg.obs.log_every_steps = 1
cfg.obs.jsonl_path = os.path.join(out, f"metrics-{{rank}}.jsonl")
if rank == 1:
    # generation 0 only (the default): node 1 is permanently lost
    cfg.faults.inject = ("elastic.shrink@step={shrink_step}",)
t = Trainer(cfg)
t.fit()
t.close()
"""


def run_shrink_drill(seed: int = 0, steps: int = 6,
                     out_dir: str = "") -> dict:
    """Seeded elastic shrink drill (docs/elastic.md): 2-node gang, node 1
    permanently lost mid-run, survivor resumes degraded at world 1."""
    import socket
    import threading

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    from pytorch_distributed_train_tpu.elastic import (
        ElasticAgent,
        LaunchConfig,
    )
    from pytorch_distributed_train_tpu.obs.events import load_events

    out_dir = out_dir or tempfile.mkdtemp(prefix="shrink-drill-")
    os.makedirs(out_dir, exist_ok=True)
    rng = random.Random(seed)
    shrink_step = rng.randrange(2, max(3, steps - 1))
    script = os.path.join(out_dir, "worker.py")
    with open(script, "w") as f:
        f.write(_SHRINK_WORKER.format(repo=repo, out=out_dir, steps=steps,
                                      shrink_step=shrink_step))
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    env = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    events_dir = os.path.join(out_dir, "events")
    rcs: dict[int, int] = {}

    def agent(node_rank: int, max_restarts: int) -> None:
        cfg = LaunchConfig(
            nprocs=1, max_restarts=max_restarts, monitor_interval_s=0.1,
            nnodes=2, node_rank=node_rank, master_addr="127.0.0.1",
            store_port=port, min_nnodes=1, rendezvous_window_s=2.0,
            backoff_base_s=0.05, backoff_max_s=0.1, env=env,
            events_dir=events_dir)
        rcs[node_rank] = ElasticAgent(
            cfg, [sys.executable, script]).run()

    # Node 1 gets no restart budget: once its worker exits 45 it leaves
    # for good — the "machine lost" simulation. Daemon threads: a
    # wedged agent past the join timeout must fail the report and let
    # the CLI exit, not block interpreter shutdown forever.
    threads = [threading.Thread(target=agent, args=(r, m), daemon=True)
               for r, m in ((0, 2), (1, 0))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)

    # Per-generation monotone step count from the survivor's metrics.
    steps_seen: list[int] = []
    metrics_path = os.path.join(out_dir, "metrics-0.jsonl")
    try:
        with open(metrics_path) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("tag") == "train":
                    steps_seen.append(int(rec["step"]))
    except OSError:
        pass
    # the resume rewinds to the last checkpoint — split into runs at the
    # rewind point and require each run strictly monotone
    monotone = bool(steps_seen)
    resumed_from = None
    for a, b in zip(steps_seen, steps_seen[1:]):
        if b <= a:
            if resumed_from is not None:  # more than one rewind: fail
                monotone = False
                break
            resumed_from = b
    completed = bool(steps_seen) and max(steps_seen, default=0) == steps

    from pytorch_distributed_train_tpu.checkpoint import CheckpointManager
    from pytorch_distributed_train_tpu.config import CheckpointConfig
    from pytorch_distributed_train_tpu.faults import integrity

    mgr = CheckpointManager(CheckpointConfig(
        dir=os.path.join(out_dir, "ckpt-0"), async_save=False))
    final_step = mgr.latest_good_step()
    verified = (final_step is not None
                and integrity.verify_step(mgr.dir, final_step)[0] is True)
    mgr.close()

    events = load_events(events_dir)
    resharded = any(e.get("category") == "elastic"
                    and e.get("name") == "reshard" for e in events)
    degraded = any(e.get("category") == "elastic"
                   and e.get("name") == "rendezvous_degraded"
                   for e in events)
    report = {
        "seed": seed, "steps": steps, "shrink_step": shrink_step,
        "rcs": {str(k): v for k, v in sorted(rcs.items())},
        "survivor_steps": steps_seen, "resumed_from": resumed_from,
        "monotone": monotone, "completed": completed,
        "final_good_step": final_step,
        "final_manifest_verified": bool(verified),
        "reshard_event": resharded, "rendezvous_degraded": degraded,
        "out_dir": out_dir,
    }
    report["ok"] = bool(
        rcs.get(0) == 0 and rcs.get(1) == 45 and completed and monotone
        and final_step == steps and verified and resharded and degraded)
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--steps", type=int, default=0,
                   help="horizon (default 8, or 6 with --shrink)")
    p.add_argument("--out", default="", help="run dir (default: tempdir)")
    p.add_argument("--shrink", action="store_true",
                   help="run the elastic shrink drill instead of the "
                        "multi-fault soak (docs/elastic.md)")
    p.add_argument("--store-outage", type=float, default=0.0,
                   metavar="SECONDS",
                   help="run the launcher-store blackout drill instead "
                        "(tools/store_outage_drill.py): a 2-node gang "
                        "trains through a store outage of this many "
                        "seconds with zero false hang blames")
    p.add_argument("--sanitize", action="store_true",
                   help="run under the tsan-lite concurrency sanitizer "
                        "(utils/syncdbg.py): agent threads in-process, "
                        "worker subprocesses via PDTT_SANITIZE=1; any "
                        "sanitizer finding fails the soak")
    args = p.parse_args(argv)
    if args.sanitize:
        # env first: the elastic agent's worker subprocesses inherit it
        # and train.py's maybe_activate() picks it up on their side
        os.environ["PDTT_SANITIZE"] = "1"
    from pytorch_distributed_train_tpu.utils import syncdbg

    syncdbg.maybe_activate()
    if args.store_outage > 0:
        import store_outage_drill

        report = store_outage_drill.run_training_drill(
            seed=args.seed, steps=args.steps or 18,
            outage_s=args.store_outage, out_dir=args.out)
    elif args.shrink:
        report = run_shrink_drill(seed=args.seed, steps=args.steps or 6,
                                  out_dir=args.out)
    else:
        report = run_soak(seed=args.seed, steps=args.steps or 8,
                          out_dir=args.out)
    if syncdbg.active():
        syncdbg.check_teardown()
        summary = syncdbg.findings_summary()
        report["sanitizer_findings"] = summary
        if summary:
            for f in syncdbg.findings():
                print(f"FAIL: sanitizer {f.kind}: {f.message}",
                      file=sys.stderr)
            report["ok"] = False
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
