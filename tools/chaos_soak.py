#!/usr/bin/env python
"""chaos_soak — run a short training job under a randomized (seeded)
fault schedule and prove it absorbed the chaos.

The CI face of the faults/ layer (ISSUE 2 satellite): where the unit
tests script one fault each, the soak composes several — transient
checkpoint save I/O errors, flaky record decodes, a straggling step —
drawn from a seeded RNG so a failing schedule is exactly reproducible
by seed. Acceptance:

- training completes all steps;
- ``retries_total`` > 0 (the faults actually fired AND were absorbed
  by the retry policies, not skipped);
- the final checkpoint exists and passes manifest verification
  (faults/integrity.py) at the expected step.

Usage::

    python tools/chaos_soak.py [--seed 0] [--steps 8] [--out DIR]

Prints one JSON report line; exit 0 = pass. Registered as a slow-marked
test (tests/test_chaos_soak.py) so tier-1 stays fast.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_schedule(seed: int, steps: int, attempts: int) -> list[str]:
    """Randomized-but-reproducible schedule. Injected transient counts
    stay BELOW the retry budget (count < attempts) so every fault is
    absorbable — the soak proves recovery, not failure."""
    rng = random.Random(seed)
    specs = [
        # 1-2 transient ckpt save failures at a random cadence step
        f"ckpt.save_io@step={rng.randrange(2, max(3, steps))}"
        f":count={rng.randrange(1, attempts)}:gen=-1",
        # a flaky decode early in the run
        f"data.decode@call={rng.randrange(1, 4)}"
        f":count={rng.randrange(1, attempts)}:gen=-1",
        # one short straggle
        f"step.straggle@step={rng.randrange(1, steps + 1)}"
        f":count=1:delay=0.2:gen=-1",
    ]
    if rng.random() < 0.5:
        # probabilistic decode noise, seeded via faults.seed
        specs.append(f"data.decode@p=0.05:count={attempts - 1}:gen=-1")
    return specs


def run_soak(seed: int = 0, steps: int = 8, out_dir: str = "") -> dict:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from pytorch_distributed_train_tpu.checkpoint import CheckpointManager
    from pytorch_distributed_train_tpu.config import (
        CheckpointConfig,
        TrainConfig,
    )
    from pytorch_distributed_train_tpu.faults import integrity
    from pytorch_distributed_train_tpu.obs.registry import get_registry
    from pytorch_distributed_train_tpu.trainer import Trainer

    out_dir = out_dir or tempfile.mkdtemp(prefix="chaos-soak-")
    cfg = TrainConfig()
    cfg.model.name = "resnet18"
    cfg.model.num_classes = 10
    cfg.model.image_size = 8
    cfg.data.dataset = "synthetic_images"
    cfg.data.synthetic_size = 256
    cfg.data.batch_size = 32
    cfg.data.num_workers = 1
    cfg.optim.name = "momentum"
    cfg.optim.learning_rate = 0.05
    cfg.optim.schedule = "constant"
    cfg.optim.warmup_steps = 0
    cfg.total_steps = steps
    cfg.checkpoint.dir = os.path.join(out_dir, "ckpt")
    cfg.checkpoint.save_every_steps = 2
    cfg.checkpoint.async_save = False
    cfg.obs.log_every_steps = max(1, steps // 2)
    cfg.obs.jsonl_path = os.path.join(out_dir, "metrics.jsonl")
    cfg.faults.seed = seed
    schedule = build_schedule(seed, steps, cfg.faults.retry_max_attempts)
    cfg.faults.inject = tuple(schedule)

    trainer = Trainer(cfg)
    trainer.fit()
    trainer.close()

    reg = get_registry()
    injected = reg.family_total("faults_injected_total")
    retries = reg.family_total("retries_total")
    mgr = CheckpointManager(CheckpointConfig(dir=cfg.checkpoint.dir,
                                             async_save=False))
    final_step = mgr.latest_good_step()
    verified = (final_step is not None
                and integrity.verify_step(mgr.dir, final_step)[0] is True)
    mgr.close()
    report = {
        "seed": seed,
        "steps": steps,
        "schedule": schedule,
        "faults_injected_total": injected,
        "retries_total": retries,
        "records_skipped_total": reg.family_total("records_skipped_total"),
        "final_good_step": final_step,
        "final_manifest_verified": bool(verified),
        "out_dir": out_dir,
    }
    report["ok"] = bool(
        final_step == steps and verified and retries > 0 and injected > 0)
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--out", default="", help="run dir (default: tempdir)")
    args = p.parse_args(argv)
    report = run_soak(seed=args.seed, steps=args.steps, out_dir=args.out)
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
