#!/usr/bin/env python
"""Live fleet health console over the store-discovered collector.

    python tools/fleet_console.py --store 127.0.0.1:7777 --watch
    python tools/fleet_console.py --store 127.0.0.1:7777 --snapshot
    python tools/fleet_console.py --snapshot --format json \
        --target serving=127.0.0.1:8000
    python tools/fleet_console.py --offline --run-dir checkpoints/

One screen answering "is the run healthy RIGHT NOW": every trainer
host and serving replica the fleet registered (elastic
``publish_obs_endpoint``; no static scrape config), scraped on a
cadence (obs/collector.py), evaluated against the closed alert-rule
catalog (obs/alerts.py), rendered as:

- the per-target table — role, generation, staleness state (never /
  ok / STALE on the collector's own clock), step + steps/s, MFU,
  goodput, serving TTFT/admission/queue, memory headroom;
- named rollups: the slowest trainer host and slowest serving replica;
- active alerts with their ages, values and baselines;
- the last rewind / restart / capture out of the event journal (when a
  run dir is at hand).

``--watch`` refreshes in place; ``--snapshot`` renders once (two
scrape passes so rates exist) — ``--format json`` for CI. ``--offline``
renders from journals + the perf ledger alone: the post-mortem view of
the same screen, no live fleet needed.

``--history-dir`` attaches the durable time-series store
(obs/tsdb.py): every scrape writes through to disk, the per-target
rows grow SPARKLINES over the recent trajectory, and an SLO-budget
panel (obs/slo_budget.py) shows each objective's remaining error
budget and fast/slow burn rates — the burn-rate alert rules
(kind ``burn_rate``) evaluate alongside the instant rules. With
``--since`` (+ optional ``--range``) the console instead renders a
RETROSPECTIVE of that window from the store alone — no live fleet,
no journal needed:

    python tools/fleet_console.py --history-dir run/tsdb \
        --since -30m --range 30m


Alert transitions journal under the ``alert`` event category (a
timeline_report landmark), and can additionally go to ``--alert-file``
(JSONL) / ``--alert-webhook`` (POST). ``--profile-on-alert`` lets a
firing anomaly rule open a managed-profiler capture on the offending
target via its own ``POST /profile`` route, cooldown-limited.

Pure stdlib + the repo's obs package; no jax import — safe on a login
host.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_train_tpu.obs import events as events_lib  # noqa: E402
from pytorch_distributed_train_tpu.obs.alerts import (  # noqa: E402
    RULES,
    AlertEngine,
)
from pytorch_distributed_train_tpu.obs.collector import FleetCollector  # noqa: E402


_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values, width: int = 24) -> str:
    """Unicode block sparkline of a value sequence (newest right).
    Empty input renders empty; a flat series renders flat-low."""
    vals = [float(v) for v in values if isinstance(v, (int, float))]
    if not vals:
        return ""
    vals = vals[-width:]
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vals)
    return "".join(
        _SPARK[min(len(_SPARK) - 1,
                   int((v - lo) / span * len(_SPARK)))] for v in vals)


# which stored series a row's sparkline follows, per role
_SPARK_SERIES = {"trainer": "steps_per_s", "serving": "ttft_p95_s"}


def _history_spark(history, row: dict, window_s: float = 300.0) -> str:
    series = _SPARK_SERIES.get(row["role"])
    if history is None or series is None:
        return ""
    now = time.time()
    try:
        pts = history.query(f"{row['role']}@{row['host']}", series,
                            now - window_s, now)
    except Exception:
        return ""
    if not pts:
        return ""
    return (f"{series} {sparkline([v for _ts, v in pts])} "
            f"[{min(v for _, v in pts):.3g}..{max(v for _, v in pts):.3g}]")


def slo_panel(slo_status: dict) -> list[str]:
    """The SLO-budget panel: per objective, worst-target remaining
    budget + the fast/slow actionable burn rates."""
    if not slo_status:
        return []
    out = ["  SLO budgets (worst target per objective):"]
    for name, st in sorted(slo_status.items()):
        rem = st.get("budget_remaining")
        burns = st.get("burn") or {}
        btxt = " ".join(
            f"{w}={burns[w]:.2f}x" for w in ("fast", "slow")
            if isinstance(burns.get(w), (int, float)))
        flag = ("OVERSPENT" if isinstance(rem, (int, float)) and rem < 0
                else "")
        out.append(
            f"    {name:<22} budget {_num(rem, '{:+.2f}'):>7} "
            f"burn {btxt or '-':<22} {st.get('worst_target') or ''} "
            f"{flag}".rstrip())
    return out


def _gb(n) -> str:
    return f"{n / 2**30:.1f}G" if isinstance(n, (int, float)) else "-"


def _num(v, fmt="{:.2f}") -> str:
    return fmt.format(v) if isinstance(v, (int, float)) else "-"


def _serving_cell(row: dict) -> str:
    if row["role"] != "serving":
        return "-"
    ttft = row.get("ttft_p95_s")
    if ttft is None:
        ttft = (row.get("ttft_rolling") or {}).get("p95")
    parts = []
    if ttft is not None:
        parts.append(f"ttft_p95 {1e3 * ttft:.0f}ms")
    if row.get("admission"):
        parts.append(str(row["admission"]))
    if row.get("queue_depth") is not None:
        parts.append(f"q={row['queue_depth']}")
    return " ".join(parts) or "-"


def store_panel(health: dict | None) -> list[str]:
    """One store-health line from the store_plane health snapshot:
    state, op p95, last-known-good cache ages. Empty for store-less
    consoles (no ops ever ran — nothing to report on)."""
    if not isinstance(health, dict) or not health.get("ops_total"):
        return []
    state = str(health.get("state", "ok"))
    parts = [f"  store: {state.upper() if state != 'ok' else 'ok'}"]
    p95 = health.get("op_p95_ms")
    if p95 is not None:
        parts.append(f"op p95 {p95:.1f}ms")
    if health.get("consecutive_failures"):
        parts.append(f"consec-fail {health['consecutive_failures']}")
    lkg = health.get("lkg_age_s") or {}
    if lkg:
        parts.append("lkg " + ",".join(
            f"{name}={age:.0f}s" for name, age in sorted(lkg.items())))
    if health.get("lkg_serves"):
        parts.append(f"served-from-cache {health['lkg_serves']}")
    if state != "ok" and health.get("last_error"):
        parts.append(f"err {health['last_error']}")
    return ["  ".join(parts)]


def render_snapshot(snap: dict, alerts: list[dict],
                    last_events: dict | None = None,
                    history=None,
                    slo_status: dict | None = None,
                    controller_lines: list[str] | None = None,
                    store_health: dict | None = None) -> str:
    rows = snap["targets"]
    states = [r["state"] for r in rows]
    head = (f"== fleet console: {len(rows)} target(s) "
            f"({states.count('ok')} ok, {states.count('stale')} stale, "
            f"{states.count('never')} never-scraped); "
            f"{len(alerts)} alert(s) firing ==")
    lines = [head,
             f"  {'host':<10} {'role':<8} {'gen':>3} {'state':<6} "
             f"{'age':>6} {'step':>7} {'steps/s':>8} {'mfu%':>6} "
             f"{'goodput%':>8}  serving"]
    for r in rows:
        state = r["state"].upper() if r["state"] != "ok" else "ok"
        age = f"{r['age_s']:.1f}s" if r["age_s"] is not None else "-"
        lines.append(
            f"  {r['host']:<10} {r['role']:<8} {r['gen']:>3} {state:<6} "
            f"{age:>6} {_num(r['step'], '{:.0f}'):>7} "
            f"{_num(r['steps_per_s']):>8} {_num(r['mfu_pct']):>6} "
            f"{_num(r['goodput_pct'], '{:.1f}'):>8}  {_serving_cell(r)}")
        mem = r.get("memory") or {}
        extras = []
        if "host_available_bytes" in mem:
            extras.append(f"avail {_gb(mem['host_available_bytes'])}")
        if "host_rss_bytes" in mem:
            extras.append(f"rss {_gb(mem['host_rss_bytes'])}")
        if mem.get("device_bytes_limit"):
            frac = mem.get("device_bytes_in_use", 0) / mem[
                "device_bytes_limit"]
            extras.append(f"dev {100 * frac:.0f}%")
        if r.get("restarts"):
            extras.append(f"restarts {r['restarts']}")
        split = r.get("input_split") or {}
        if split and sum(split.values()):
            top = max(split, key=split.get)
            extras.append(
                f"input {top} "
                f"{100 * split[top] / sum(split.values()):.0f}%")
        tiers = {k: v for k, v in (r.get("ckpt_tiers") or {}).items() if v}
        if tiers:
            extras.append("ckpt " + ",".join(
                f"{t}={int(n)}" for t, n in sorted(tiers.items())))
        if r.get("error") and r["state"] != "ok":
            extras.append(f"err {r['error']}")
        if extras:
            lines.append(" " * 13 + "· " + "  ".join(extras))
        health = r.get("model_health") or {}
        if health:
            # model-health panel (obs/model_health.py): the divergence
            # precursors per target — latest value + in-window
            # sparkline from the collector's own deques (no history
            # store needed)
            cells = []
            for name in ("grad_norm", "update_ratio", "reward_mean",
                         "kl_behavior"):
                vals = health.get(name)
                if vals:
                    cells.append(
                        f"{name} {_num(vals[-1], '{:.3g}')} "
                        f"{sparkline(vals)}")
            if cells:
                lines.append(" " * 13 + "♥ " + "  ".join(cells))
        spark = _history_spark(history, r)
        if spark:
            lines.append(" " * 13 + "~ " + spark)
    if snap.get("slowest_serving"):
        lines.append(f"  slowest serving replica: "
                     f"{snap['slowest_serving']}")
    if snap.get("slowest_trainer"):
        lines.append(f"  slowest trainer: {snap['slowest_trainer']}")
    if alerts:
        lines.append(f"  alerts ({len(alerts)} firing):")
        for a in alerts:
            val = (f" value={a['value']:.4g}"
                   if isinstance(a["value"], (int, float)) else "")
            base = (f" baseline={a['baseline']:.4g}"
                    if isinstance(a["baseline"], (int, float)) else "")
            lines.append(f"    FIRING {a['rule']:<22} {a['host']:<10} "
                         f"for {a['for_s']:.1f}s{val}{base}")
    else:
        lines.append("  alerts: none firing")
    lines.extend(store_panel(store_health))
    lines.extend(slo_panel(slo_status or {}))
    if controller_lines:
        lines.extend(controller_lines)
    if last_events:
        lines.append("  last: " + "  ".join(
            f"{k}={v}" for k, v in last_events.items()))
    return "\n".join(lines)


# ------------------------------------------------------------ journal bits
def controller_panel(events: list[dict], last: int = 5) -> list[str]:
    """Fleet-controller panel, replayed from the ``action`` journal
    category (fleet/controller.py): current mode, budget latches, and
    the last K actions with terminal outcomes. Empty when no
    controller wrote to this journal — the panel only appears on
    fleets that run the closed loop."""
    acts = [e for e in events if e.get("category") == "action"]
    if not acts:
        return []
    mode = "active"
    terminal: dict[str, dict] = {}
    order: list[str] = []
    for e in acts:
        d = e.get("detail") or {}
        if e.get("name") == "mode":
            mode = str(d.get("mode", mode))
            continue
        aid = d.get("id")
        if not aid:
            continue
        if aid not in order:
            order.append(aid)
        if e.get("name") in ("effective", "failed", "rolled_back",
                             "skipped"):
            terminal[aid] = e
    out = [f"  controller: mode={mode}  actions journaled="
           f"{len(order)}"]
    for aid in order[-last:]:
        t = terminal.get(aid)
        if t is None:
            out.append(f"    {aid}: no terminal outcome journaled")
            continue
        d = t.get("detail") or {}
        line = (f"    {d.get('action', '?'):<10} "
                f"{t.get('name'):<12} trigger={d.get('trigger', '?')}")
        if d.get("addr"):
            line += f" addr={d.get('addr')}"
        if d.get("alert_id"):
            line += f" alert={d.get('alert_id')}"
        if d.get("reason"):
            line += f" reason={d.get('reason')}"
        out.append(line)
    return out


def weights_panel(events: list[dict], last: int = 4) -> list[str]:
    """Online weight-sync panel, replayed from the ``weights`` journal
    category (online/, tools/serve_http.py): the newest published
    version, each replica's last applied swap (so a laggard is one
    glance away), recent rejects, and the rollout harvest rate. Empty
    when no online loop wrote to this journal."""
    recs = [e for e in events if e.get("category") == "weights"]
    if not recs:
        return []
    published = None
    swaps: dict[str, dict] = {}  # replica host -> last applied swap
    rejects: list[dict] = []
    batches = 0
    for e in recs:
        name = e.get("name")
        if name == "publish":
            published = e
        elif name == "swap":
            swaps[e.get("host", "?")] = e
        elif name == "swap_rejected":
            rejects.append(e)
        elif name == "rollout_batch":
            batches += 1
    out = ["  weight sync:"]
    if published is not None:
        d = published.get("detail") or {}
        out.append(f"    published v{d.get('version')} @ "
                   f"step {published.get('step')} "
                   f"({d.get('hosts')} host shard(s))")
    for host, e in sorted(swaps.items()):
        d = e.get("detail") or {}
        out.append(f"    {host:<10} serving v{d.get('version')} "
                   f"(from v{d.get('old_version')}, "
                   f"{d.get('dur_s', 0):.3f}s swap)")
    if rejects:
        d = (rejects[-1].get("detail") or {})
        out.append(f"    rejects: {len(rejects)} "
                   f"(last: v{d.get('version')} "
                   f"{d.get('reason', '?')} on "
                   f"{rejects[-1].get('host', '?')})")
    if batches:
        out.append(f"    rollout batches harvested: {batches}")
    return out


def _last_events(events: list[dict]) -> dict:
    """The operator's first three questions, from the journal."""
    out = {}
    for label, pred in (
            ("rewind", lambda e: e.get("category") == "sentinel"
             and e.get("name") == "rewind"),
            ("restart", lambda e: e.get("category") == "elastic"
             and e.get("name") in ("restart", "spawn")),
            ("capture", lambda e: e.get("category") == "profile"
             and e.get("name") == "capture_end"),
    ):
        hit = next((e for e in reversed(events) if pred(e)), None)
        out[label] = ("-" if hit is None else
                      f"{hit.get('name')}@step{hit.get('step')}"
                      f"[{hit.get('host')}]")
    return out


def offline_report(run_dir: str, events_dir: str = "",
                   ledger_path: str = "") -> str:
    """The same screen, from artifacts alone (journals + perf ledger):
    what was firing when the run died, which hosts wrote last."""
    from pytorch_distributed_train_tpu.obs.events import load_events

    events_dir = events_dir or os.path.join(run_dir, "events")
    events = load_events(events_dir) if os.path.isdir(events_dir) else []
    lines = [f"== fleet console (offline): {events_dir} "
             f"({len(events)} journaled events) =="]
    # per-writer last word
    writers: dict[str, dict] = {}
    for e in events:
        writers[e.get("host", "?")] = e
    for host, e in sorted(writers.items()):
        lines.append(f"  {host:<10} last: {e.get('category')}."
                     f"{e.get('name')} step={e.get('step')} "
                     f"g{e.get('gen')}")
    # alert replay: fired without a later resolved = was firing at EOJ
    active: dict[tuple, dict] = {}
    fired = 0
    for e in events:
        if e.get("category") != "alert":
            continue
        d = e.get("detail") or {}
        key = (d.get("rule"), d.get("host"))
        if e.get("name") == "fired":
            fired += 1
            active[key] = e
        elif e.get("name") == "resolved":
            active.pop(key, None)
    lines.append(f"  alerts: {fired} fired over the journal; "
                 f"{len(active)} still firing at end")
    for (rule, host), e in sorted(active.items(),
                                  key=lambda kv: kv[1].get("ts", 0.0)):
        d = e.get("detail") or {}
        lines.append(f"    UNRESOLVED {rule} on {host} "
                     f"value={d.get('value')} (gen {d.get('gen')})")
    lines.extend(controller_panel(events))
    lines.extend(weights_panel(events))
    # store-plane replay (the ``store`` journal category): the
    # degraded→ok arc and any liveness blame suspensions, so a store
    # outage reads as a control-plane incident, not N dead hosts
    srecs = [e for e in events if e.get("category") == "store"]
    if srecs:
        state = "ok"
        transitions = 0
        suspensions = 0
        for e in srecs:
            name = e.get("name")
            if name in ("degraded", "down"):
                state = name
                transitions += 1
            elif name == "recovered":
                state = "ok"
            elif name == "blame_suspended":
                suspensions += 1
        lines.append(f"  store: {state.upper() if state != 'ok' else 'ok'}"
                     f" at end  degraded-transitions={transitions}  "
                     f"blame-suspensions={suspensions}")
    lines.append("  " + "  ".join(
        f"last {k}: {v}" for k, v in _last_events(events).items()))
    ledger_path = ledger_path or os.path.join(run_dir, "perf_ledger.jsonl")
    if os.path.exists(ledger_path):
        last = None
        try:
            with open(ledger_path) as f:
                for line in f:
                    try:
                        last = json.loads(line)
                    except ValueError:
                        continue
        except OSError:
            last = None
        if last:
            lines.append(
                f"  perf ledger: last row {last.get('metric', '?')}="
                f"{last.get('value')} mfu={last.get('mfu_pct')} "
                f"({ledger_path})")
    return "\n".join(lines)


# ------------------------------------------------------- retrospective
def parse_since(spec: str, now: float | None = None) -> float:
    """``--since``: epoch seconds, ISO ``YYYY-mm-ddTHH:MM[:SS]``, or
    relative ``-30m`` / ``-2h`` / ``-90s`` (ago)."""
    now = time.time() if now is None else now
    spec = spec.strip()
    if spec.startswith("-"):
        return now - parse_duration(spec[1:])
    try:
        return float(spec)
    except ValueError:
        pass
    import datetime

    for fmt in ("%Y-%m-%dT%H:%M:%S", "%Y-%m-%dT%H:%M", "%Y-%m-%d"):
        try:
            return datetime.datetime.strptime(spec, fmt).timestamp()
        except ValueError:
            continue
    raise SystemExit(f"--since: cannot parse {spec!r}")


def parse_duration(spec: str) -> float:
    spec = spec.strip().lower()
    mult = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}.get(
        spec[-1:], None)
    if mult is not None:
        return float(spec[:-1]) * mult
    return float(spec)


def history_report(history_dir: str, since: float,
                   range_s: float) -> str:
    """The retrospective console: the window [since, since+range]
    rendered from the on-disk store ALONE — every target and series
    with data gets its stats + sparkline, then the SLO-budget panel as
    of the window's end. A dead fleet's last hour, readable after the
    fact."""
    from pytorch_distributed_train_tpu.obs.slo_budget import (
        SLOBudgetTracker,
    )
    from pytorch_distributed_train_tpu.obs.tsdb import TimeSeriesStore

    store = TimeSeriesStore(history_dir)
    end = since + range_s
    lines = [f"== fleet console (retrospective): {history_dir} "
             f"[{time.strftime('%Y-%m-%dT%H:%M:%S', time.localtime(since))}"
             f" +{range_s:.0f}s] =="]
    targets = store.targets()
    if not targets:
        lines.append("  (store is empty — no targets ever wrote "
                     "history here)")
        return "\n".join(lines)
    for target in targets:
        shown = []
        for series in store.series(target):
            try:
                pts = store.query(target, series, since, end)
            except Exception:
                continue
            if not pts:
                continue
            vals = [v for _ts, v in pts]
            shown.append(
                f"    {series:<24} n={len(vals):<5} "
                f"min={min(vals):.4g} mean={sum(vals) / len(vals):.4g} "
                f"max={max(vals):.4g}  {sparkline(vals)}")
        if shown:
            lines.append(f"  {target}:")
            lines.extend(shown)
    tracker = SLOBudgetTracker(store, clock=lambda: end)
    lines.extend(slo_panel(tracker.status()))
    return "\n".join(lines)


# ----------------------------------------------------------------- wiring
def _store_factory(addr: str):
    host, _, port = addr.rpartition(":")

    def factory():
        from pytorch_distributed_train_tpu.native.store import StoreClient

        return StoreClient(host or "127.0.0.1", int(port))

    return factory


def build(args) -> tuple[FleetCollector, AlertEngine]:
    endpoints = []
    for i, spec in enumerate(args.target or ()):
        role, _, addr = spec.partition("=")
        if not addr:
            raise SystemExit(f"--target wants role=host:port, got {spec!r}")
        endpoints.append({"role": role, "addr": addr,
                          "host": f"static{i}", "gen": "0", "idx": i})
    store_addr = args.store or os.environ.get("TPUSTORE_ADDR", "")
    factory = (_store_factory(store_addr) if store_addr
               else (lambda: None))
    history = None
    tracker = None
    history_dir = getattr(args, "history_dir", "")
    if history_dir:
        from pytorch_distributed_train_tpu.obs.slo_budget import (
            SLOBudgetTracker,
        )
        from pytorch_distributed_train_tpu.obs.tsdb import (
            TimeSeriesStore,
        )

        history = TimeSeriesStore(
            history_dir,
            disk_budget_bytes=int(
                getattr(args, "history_budget_mb", 64.0) * 2**20))
        tracker = SLOBudgetTracker(history)
    collector = FleetCollector(
        store_factory=factory, endpoints=endpoints,
        poll_s=args.interval, stale_after_s=args.stale_after,
        timeout_s=args.timeout, history=history)
    overrides = {}
    for spec in args.rule or ():
        key, _, value = spec.partition("=")
        if not value:
            raise SystemExit(f"--rule wants rule.field=value, got {spec!r}")
        overrides[key] = value
    engine = AlertEngine(
        sink_path=args.alert_file, webhook_url=args.alert_webhook,
        profile_on_alert=args.profile_on_alert,
        profile_cooldown_s=args.profile_cooldown,
        overrides=overrides, slo_tracker=tracker)
    return collector, engine


_EVENTS_CACHE: dict = {"sig": None, "events": []}


def _events_for_console(args) -> list[dict]:
    """Journal for the last-events line, cached by (path, size)
    signature: --watch calls this every refresh tick, and re-parsing a
    long multi-host run's whole journal several times a second would
    make each refresh slower than the interval."""
    events_dir = args.events or (os.path.join(args.run_dir, "events")
                                 if args.run_dir else
                                 os.environ.get(events_lib.ENV_VAR, ""))
    if not events_dir or not os.path.isdir(events_dir):
        return []
    import glob

    sig = tuple(sorted(
        (p, os.path.getsize(p))
        for p in glob.glob(os.path.join(events_dir, "events_*.jsonl"))))
    if sig != _EVENTS_CACHE["sig"]:
        from pytorch_distributed_train_tpu.obs.events import load_events

        _EVENTS_CACHE["sig"] = sig
        _EVENTS_CACHE["events"] = load_events(events_dir)
    return _EVENTS_CACHE["events"]


def tick(collector: FleetCollector, engine: AlertEngine) -> dict:
    """One console heartbeat: scrape, evaluate, snapshot."""
    collector.poll()
    engine.evaluate(collector)
    return collector.snapshot()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--store", default="",
                   help="launcher store host:port (default: "
                        "$TPUSTORE_ADDR) for endpoint discovery")
    p.add_argument("--target", action="append", metavar="ROLE=HOST:PORT",
                   help="static scrape target (repeatable; supplements "
                        "store discovery)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="scrape cadence seconds (--watch refresh)")
    p.add_argument("--stale-after", type=float, default=10.0,
                   help="seconds of scrape silence before a "
                        "previously-seen target counts stale")
    p.add_argument("--timeout", type=float, default=2.0,
                   help="per-scrape HTTP timeout")
    p.add_argument("--watch", action="store_true",
                   help="refresh the console in place until ^C")
    p.add_argument("--snapshot", action="store_true",
                   help="two scrape passes, render once, exit (CI)")
    p.add_argument("--rounds", type=int, default=2,
                   help="scrape passes for --snapshot (>=2 so "
                        "steps/s and rate series exist)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--offline", action="store_true",
                   help="render from journals + perf ledger alone "
                        "(no scraping; needs --run-dir)")
    p.add_argument("--run-dir", default="",
                   help="run directory (events/ + perf_ledger.jsonl "
                        "for --offline and the last-events line)")
    p.add_argument("--events", default="",
                   help="explicit events directory")
    p.add_argument("--alert-file", default="",
                   help="append alert transitions to this JSONL file")
    p.add_argument("--alert-webhook", default="",
                   help="POST alert transitions to this URL")
    p.add_argument("--profile-on-alert", action="store_true",
                   help="firing anomaly rules POST /profile on the "
                        "offending target (cooldown-limited)")
    p.add_argument("--profile-cooldown", type=float, default=300.0,
                   help="min seconds between alert-triggered captures")
    p.add_argument("--rule", action="append", metavar="RULE.FIELD=VALUE",
                   help="override a rule knob, e.g. "
                        "ttft_regression.min_samples=4 (repeatable)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the closed alert-rule catalog and exit")
    p.add_argument("--history-dir", default="",
                   help="attach the durable time-series store "
                        "(obs/tsdb.py) at this directory: scrapes "
                        "write through, sparklines + SLO budgets "
                        "render, burn-rate rules evaluate")
    p.add_argument("--history-budget-mb", type=float, default=64.0,
                   help="retention disk budget for --history-dir")
    p.add_argument("--since", default="",
                   help="retrospective mode: render [SINCE, "
                        "SINCE+RANGE] from the store alone (epoch, "
                        "ISO, or -30m style; needs --history-dir or "
                        "--run-dir with a tsdb/)")
    p.add_argument("--range", default="15m", dest="range_",
                   metavar="RANGE", help="retrospective window length")
    args = p.parse_args(argv)

    if args.list_rules:
        for name, r in sorted(RULES.items()):
            print(f"{name:<22} {r.kind:<10} roles={','.join(r.roles)}  "
                  f"{r.description}")
        return 0
    if args.since:
        history_dir = args.history_dir or (
            os.path.join(args.run_dir, "tsdb") if args.run_dir else "")
        if not history_dir or not os.path.isdir(history_dir):
            print("fleet_console: --since needs an existing store "
                  "(--history-dir, or --run-dir with tsdb/)",
                  file=sys.stderr)
            return 2
        print(history_report(history_dir, parse_since(args.since),
                             parse_duration(args.range_)))
        return 0
    if args.offline:
        if not args.run_dir and not args.events:
            print("fleet_console: --offline needs --run-dir or --events",
                  file=sys.stderr)
            return 2
        print(offline_report(args.run_dir, args.events))
        return 0
    if not (args.store or os.environ.get("TPUSTORE_ADDR")
            or args.target):
        print("fleet_console: no targets (--store, $TPUSTORE_ADDR or "
              "--target)", file=sys.stderr)
        return 2
    collector, engine = build(args)
    # alert events journal beside the run when a dir is at hand
    events_dir = args.events or (os.path.join(args.run_dir, "events")
                                 if args.run_dir else
                                 os.environ.get(events_lib.ENV_VAR))
    if events_dir:
        events_lib.configure(events_dir, who="fleet")
    try:
        def _slo_status():
            if engine.slo_tracker is None:
                return None
            try:
                return engine.slo_tracker.status()
            except Exception:
                return None

        if args.watch:
            while True:
                snap = tick(collector, engine)
                sys.stdout.write("\x1b[2J\x1b[H")  # clear, home
                evs = (_events_for_console(args)
                       if (args.run_dir or args.events) else [])
                print(render_snapshot(snap, engine.firing(),
                                      _last_events(evs) if evs
                                      else None,
                                      history=collector.history,
                                      slo_status=_slo_status(),
                                      controller_lines=(
                                          controller_panel(evs)
                                          + weights_panel(evs)),
                                      store_health=collector
                                      .store_health()))
                sys.stdout.flush()
                time.sleep(collector.poll_s)
        else:
            snap = None
            for i in range(max(1, args.rounds)):
                if i:
                    time.sleep(min(collector.poll_s, 0.5))
                snap = tick(collector, engine)
            if args.format == "json":
                out = json.dumps(dict(snap, alerts=engine.firing(),
                                      slo=_slo_status(),
                                      store_health=collector
                                      .store_health()),
                                 indent=2, sort_keys=True)
            else:
                evs = (_events_for_console(args)
                       if (args.run_dir or args.events) else [])
                out = render_snapshot(
                    snap, engine.firing(),
                    _last_events(evs) if evs else None,
                    history=collector.history,
                    slo_status=_slo_status(),
                    controller_lines=(controller_panel(evs)
                                      + weights_panel(evs)),
                    store_health=collector.store_health())
            print(out)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
