import os
import sys

# Runnable both as `python -m tools.analyze` (repo root on sys.path
# already) and as `python tools/analyze` from anywhere.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from tools.analyze.cli import main  # noqa: E402

sys.exit(main())
