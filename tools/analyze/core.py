"""pdtt-analyze core: the pass framework every plugin builds on.

The repo's correctness planes (serving, ckpt, sentinel, elastic, obs)
rest on conventions no interpreter enforces: no blocking work under a
service lock, monotonic clocks for deadline math, host-sync-free jitted
step functions, and code↔doc catalog sync. Each convention is a *pass*
here — an AST walk producing :class:`Finding`s — registered into one
runner so a new invariant is one new module, not one new script.

Contracts:

- a Finding's ``fingerprint`` (pass id, repo-relative path, key) is the
  baseline-suppression identity; the key defaults to the stripped source
  line so findings survive unrelated line-number drift;
- passes see the repo through a :class:`Context` (pre-parsed
  :class:`SourceFile`s + ``repo_root``) so tests can hand them a tmp
  tree or a single fixture file;
- ``include`` patterns scope a pass to the subsystems whose invariant it
  checks (a trailing ``/`` means prefix, otherwise fnmatch) — noise
  control is part of the pass contract, not the caller's job.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import os


# --------------------------------------------------------------- findings
@dataclasses.dataclass(frozen=True)
class Finding:
    pass_id: str
    path: str            # repo-relative, forward slashes
    line: int
    message: str
    severity: str = "error"   # "error" | "warning" (display only: any
    key: str = ""             # unsuppressed finding fails the run)

    @property
    def fingerprint(self) -> tuple[str, str, str]:
        return (self.pass_id, self.path, self.key)

    def as_dict(self) -> dict:
        return {"pass": self.pass_id, "path": self.path, "line": self.line,
                "severity": self.severity, "message": self.message,
                "key": self.key}

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.pass_id}] "
                f"{self.severity}: {self.message}")


# ------------------------------------------------------------ source files
class SourceFile:
    """One parsed python file; ``tree`` is None on syntax errors (the
    runner reports those once instead of every pass tripping over them).
    """

    def __init__(self, repo_root: str, relpath: str):
        self.path = relpath.replace(os.sep, "/")
        self.abspath = os.path.join(repo_root, relpath)
        try:
            with open(self.abspath, encoding="utf-8") as f:
                self.text = f.read()
        except UnicodeDecodeError:
            # One stray latin-1 byte must not kill the CI gate; the
            # replacement char at worst turns into a SyntaxError below,
            # which the runner reports as a skipped file.
            with open(self.abspath, encoding="utf-8",
                      errors="replace") as f:
                self.text = f.read()
        self.lines = self.text.splitlines()
        try:
            self.tree: ast.AST | None = ast.parse(self.text,
                                                  filename=self.path)
        except SyntaxError:
            self.tree = None

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


# Repo-relative roots the default discovery walks; tests/ is excluded on
# purpose (test code blocks and wall-clocks freely) and the analyzer's
# own fixtures are seeded violations, not findings.
DEFAULT_ROOTS = ("pytorch_distributed_train_tpu", "tools",
                 "train.py", "tpurun.py", "bench.py")
EXCLUDE_PARTS = ("__pycache__",)
EXCLUDE_PREFIXES = ("tools/analyze/fixtures/",)


def discover(repo_root: str, roots=DEFAULT_ROOTS) -> list[str]:
    out: list[str] = []
    for root in roots:
        top = os.path.join(repo_root, root)
        if os.path.isfile(top) and root.endswith(".py"):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d not in EXCLUDE_PARTS]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), repo_root)
                rel = rel.replace(os.sep, "/")
                if any(rel.startswith(p) for p in EXCLUDE_PREFIXES):
                    continue
                out.append(rel)
    return sorted(set(out))


class Context:
    """What a pass sees: the parsed files plus the repo root (catalog
    passes resolve ``docs/`` against it)."""

    def __init__(self, repo_root: str, relpaths: list[str] | None = None):
        self.repo_root = os.path.abspath(repo_root)
        # Explicit paths = a PARTIAL view: passes that check global
        # completeness ("every documented name has a site somewhere")
        # must skip the direction that needs the whole surface, or a
        # single-file run drowns in false phantom/unemitted findings.
        self.partial = relpaths is not None
        if relpaths is None:
            relpaths = discover(self.repo_root)
        self.files: list[SourceFile] = []
        for rel in relpaths:
            try:
                self.files.append(SourceFile(self.repo_root, rel))
            except OSError:
                continue
        self.by_path = {sf.path: sf for sf in self.files}

    def doc_path(self, *parts: str) -> str:
        return os.path.join(self.repo_root, *parts)


def build_context(repo_root: str, paths: list[str] | None = None) -> Context:
    return Context(repo_root, paths)


def path_matches(relpath: str, patterns) -> bool:
    for pat in patterns:
        if pat.endswith("/"):
            if relpath.startswith(pat):
                return True
        elif fnmatch.fnmatch(relpath, pat):
            return True
    return False


# ---------------------------------------------------------------- passes
class AnalysisPass:
    """Base class: subclass, set ``id``/``description``/``include``,
    implement ``run(ctx) -> list[Finding]``, and decorate with
    :func:`register`."""

    id: str = ""
    description: str = ""
    include: tuple = ("**",)   # every discovered file by default

    def files(self, ctx: Context):
        for sf in ctx.files:
            if sf.tree is not None and path_matches(sf.path, self.include):
                yield sf

    def finding(self, sf: SourceFile, node: ast.AST, message: str, *,
                severity: str = "error", key: str | None = None) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(self.id, sf.path, line, message, severity,
                       key if key is not None else sf.line_text(line))

    def run(self, ctx: Context) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


REGISTRY: dict[str, AnalysisPass] = {}


def register(cls):
    inst = cls()
    if not inst.id:
        raise ValueError(f"pass {cls.__name__} has no id")
    REGISTRY[inst.id] = inst
    return cls


def all_passes() -> dict[str, AnalysisPass]:
    # Importing the package registers the built-ins exactly once.
    from tools.analyze import passes  # noqa: F401

    return dict(REGISTRY)


# ------------------------------------------------------------ doc tables
def doc_table_names(doc_path: str, section: str, row_re) -> set:
    """First backticked column of every table row under the ``## ...``
    heading ``section`` (case-insensitive, that section only) — the one
    markdown contract parser all three catalog passes share."""
    names = set()
    in_section = False
    with open(doc_path, encoding="utf-8") as f:
        for line in f:
            if line.startswith("## "):
                in_section = line.strip().lower() == section
                continue
            if in_section:
                m = row_re.match(line)
                if m:
                    names.add(m.group(1))
    return names


# ------------------------------------------------------------ AST helpers
def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, None for anything dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_call_to(node: ast.AST, name: str) -> bool:
    return isinstance(node, ast.Call) and dotted(node.func) == name


def walk_no_nested_defs(body):
    """Yield nodes from ``body`` statements without descending into
    nested function/lambda/class bodies — for lexical "runs here, now"
    questions (a closure defined under a lock does not run under it)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


# Condition counts: `with self._cond:` acquires its lock, and
# Condition.wait is the one blocking call that correctly releases it.
LOCK_FACTORIES = ("threading.Lock", "threading.RLock",
                  "threading.Condition")


def class_lock_attrs(cls: ast.ClassDef) -> set[str]:
    """Names X for every ``self.X = threading.Lock()/RLock()`` in the
    class body (any method — locks made outside __init__ still count)."""
    out: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if dotted(node.value.func) in LOCK_FACTORIES:
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        out.add(tgt.attr)
    return out


def module_lock_names(tree: ast.AST) -> set[str]:
    """Module-global ``_LOCK = threading.Lock()`` style names."""
    out: set[str] = set()
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if dotted(node.value.func) in LOCK_FACTORIES:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
    return out


def withitem_lock_name(item: ast.withitem,
                       self_locks: set[str],
                       global_locks: set[str]) -> str | None:
    """'self._lock' / '_LOCK' when the withitem enters a known lock."""
    expr = item.context_expr
    # `with lock:` and `with lock_factory_result:`; also `lock.acquire()`
    # never appears as a withitem so Call forms are ignored.
    if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
            and expr.value.id == "self" and expr.attr in self_locks):
        return f"self.{expr.attr}"
    if isinstance(expr, ast.Name) and expr.id in global_locks:
        return expr.id
    return None
