"""trace-hygiene: span lifecycle + trace-context discipline.

Two invariants the distributed-tracing plane (obs/tracing.py,
docs/observability.md) rests on:

1. **Spans are context managers.** A span opened with a manual
   ``__enter__()`` whose ``__exit__()`` is not exception-safe corrupts
   the thread's nesting stack AND leaks the thread-local trace context
   — every later span on that thread parents to a ghost. So any
   ``.__enter__(``/``.__exit__(`` on a ``span(...)`` result (direct or
   through a variable), and any bare expression-statement ``span(...)``
   (a discarded context manager times nothing), is a finding; ``with``
   is the only sanctioned spelling. ``obs/spans.py`` itself is excused
   (its module-level ``span()`` helper returns the cm by design).

2. **No fresh trace ids where an inbound context exists.** Serving-path
   code minting with ``start_trace()``/``new_trace_id()`` instead of
   ``continue_or_start(inbound)`` splits one request into two trees —
   exactly the cross-process causality the plane exists to keep. The
   rule is scoped to the request-path surface (``MINT_SCOPE``), where
   an inbound ``traceparent`` can always exist.
"""

from __future__ import annotations

import ast

from tools.analyze.core import (AnalysisPass, Context, Finding, dotted,
                                path_matches, register)

# files where an inbound trace context can exist: minting is forbidden,
# continue_or_start() is the only door
MINT_SCOPE = (
    "pytorch_distributed_train_tpu/serving_plane/",
    "tools/serve_http.py",
    "tools/serve_router.py",
)

MINT_CALLS = ("start_trace", "new_trace_id")

# the cm-discipline rule skips the span machinery itself
CM_EXCUSED = ("pytorch_distributed_train_tpu/obs/spans.py",)


def _is_span_call(node: ast.AST) -> bool:
    """``span(...)`` or ``<recv>.span(...)`` — the recorder API."""
    if not isinstance(node, ast.Call):
        return False
    d = dotted(node.func)
    return d is not None and (d == "span" or d.endswith(".span"))


@register
class TraceHygienePass(AnalysisPass):
    id = "trace-hygiene"
    description = ("spans must be `with`-managed (no manual "
                   "__enter__/__exit__, no discarded span cm); serving "
                   "code must continue_or_start() instead of minting "
                   "trace ids")
    include = ("pytorch_distributed_train_tpu/", "tools/",
               "train.py", "tpurun.py", "bench.py")
    mint_scope = MINT_SCOPE

    def run(self, ctx: Context) -> list[Finding]:
        out: list[Finding] = []
        for sf in self.files(ctx):
            if sf.path.startswith("tools/analyze/"):
                continue  # the linter's own sources name these in text
            if sf.path not in CM_EXCUSED:
                out.extend(self._check_cm_discipline(sf))
            if path_matches(sf.path, self.mint_scope):
                out.extend(self._check_minting(sf))
        return out

    # ------------------------------------------------- rule 1: with-only
    def _check_cm_discipline(self, sf) -> list[Finding]:
        out: list[Finding] = []
        # names assigned from a span(...) call anywhere in the file —
        # manual __enter__/__exit__ on them is the unbalanced pattern
        span_names: set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign) and _is_span_call(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        span_names.add(tgt.id)
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("__enter__", "__exit__")):
                recv = node.func.value
                manual = _is_span_call(recv) or (
                    isinstance(recv, ast.Name) and recv.id in span_names)
                if manual:
                    out.append(self.finding(
                        sf, node,
                        f"manual `{node.func.attr}()` on a span context "
                        f"manager — open spans with `with span(...):` "
                        f"(unbalanced begin/end corrupts the nesting "
                        f"stack and leaks the trace context)"))
            elif isinstance(node, ast.Expr) and _is_span_call(node.value):
                out.append(self.finding(
                    sf, node.value,
                    "span context manager created and discarded — it "
                    "times nothing; use `with span(...):` around the "
                    "region"))
        return out

    # ---------------------------------------------- rule 2: no minting
    def _check_minting(self, sf) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d is None:
                continue
            tail = d.rsplit(".", 1)[-1]
            if tail in MINT_CALLS:
                out.append(self.finding(
                    sf, node,
                    f"`{d}(...)` mints a fresh trace id on the serving "
                    f"surface, where an inbound context can exist — use "
                    f"`tracing.continue_or_start(inbound)` so the "
                    f"cross-process tree stays one trace"))
        return out
