"""raw-store: launcher-store ops must go through the resilience plane.

Every control-plane subsystem (liveness, discovery, peer ckpt, profiler
triggers) rides the ONE launcher KV store. A raw ``StoreClient`` /
``elastic.worker_store()`` handle gives each op the native client's
defaults — a 60s blocking ``get``, no retry, no health scoring, no
fault points — so one slow store stalls a step loop for a minute and
the outage is invisible to the ``store_degraded`` alert.
``store_plane.ResilientStore`` exists to close exactly that hole:
bounded per-op deadline, bounded retry, last-known-good discovery
cache, and the ok→degraded→down health machine the console, alerts and
controller hold on (docs/fault_tolerance.md degraded-mode matrix).

The pass taints names bound from a raw-handle constructor —
``worker_store()`` (NOT ``resilient_worker_store``) or
``StoreClient(...)`` — including ``self.x`` attribute bindings
class-wide, and flags any store op (``get``/``set``/``add``/``wait``/
``delete``/``num_keys``/``barrier``) invoked on a tainted handle.

Deliberately NOT flagged:

- a store received as a *parameter* (``def f(store): store.get(...)``)
  — elastic helpers and ckpt/peer.py take the caller's handle, and the
  resilient wrapper IS that handle at every production call site;
- the plumbing that builds the plane itself: ``elastic.py`` (the
  launcher/agent side pre-dates workers and owns rendezvous),
  ``store_plane.py`` (the wrapper's own raw calls are the point),
  ``native/`` (the client), and ``sentinel/liveness.py``'s factory
  plumbing (it builds ResilientStore from a raw probe).
"""

from __future__ import annotations

import ast

from tools.analyze.core import AnalysisPass, Context, Finding, dotted, register

# Final dotted segment of a call that yields a RAW handle. Matched
# exactly: ``resilient_worker_store`` must not taint.
RAW_FACTORIES = {"worker_store", "StoreClient"}
STORE_OPS = {"get", "set", "add", "wait", "delete", "num_keys", "barrier"}
EXEMPT = (
    "pytorch_distributed_train_tpu/elastic.py",
    "pytorch_distributed_train_tpu/store_plane.py",
    "pytorch_distributed_train_tpu/native/",
    "pytorch_distributed_train_tpu/sentinel/liveness.py",
)


def _is_raw_factory(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted(node.func)
    return bool(d) and d.split(".")[-1] in RAW_FACTORIES


def _assign_names(tgt: ast.AST):
    if isinstance(tgt, ast.Name):
        yield ("name", tgt.id)
    elif isinstance(tgt, ast.Attribute):
        d = dotted(tgt)
        if d and d.startswith("self."):
            yield ("attr", d)
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for elt in tgt.elts:
            yield from _assign_names(elt)


def _scope_nodes(body):
    """Statements of this scope only — nested defs are their own world
    (a parameter-taking closure must not inherit outer taint rules)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


@register
class RawStorePass(AnalysisPass):
    id = "raw-store"
    description = ("launcher-store get/set/add on a raw StoreClient/"
                   "worker_store handle instead of "
                   "store_plane.ResilientStore")
    include = ("**",)

    def run(self, ctx: Context) -> list[Finding]:
        out: list[Finding] = []
        for sf in self.files(ctx):
            if any(sf.path == e or sf.path.startswith(e) for e in EXEMPT):
                continue
            # class-wide attr taint: self._store = StoreClient(...) in
            # any method taints self._store ops in every method
            attr_taint: dict[int, set[str]] = {}
            for cls in [n for n in ast.walk(sf.tree)
                        if isinstance(n, ast.ClassDef)]:
                attrs: set[str] = set()
                for node in ast.walk(cls):
                    if isinstance(node, ast.Assign) and \
                            _is_raw_factory(node.value):
                        for t in node.targets:
                            for kind, name in _assign_names(t):
                                if kind == "attr":
                                    attrs.add(name)
                for fn in ast.walk(cls):
                    if isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        attr_taint[id(fn)] = attrs
            funcs = [n for n in ast.walk(sf.tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
            for fn in funcs:
                out.extend(self._check_scope(
                    sf, fn.body, attr_taint.get(id(fn), set())))
            top = [n for n in sf.tree.body
                   if not isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))]
            out.extend(self._check_scope(sf, top, set()))
        return out

    def _check_scope(self, sf, body, tainted_attrs) -> list[Finding]:
        tainted: set[str] = set()
        for node in _scope_nodes(body):
            tgts = None
            if isinstance(node, ast.Assign):
                tgts, value = node.targets, node.value
            elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
                tgts, value = [node.target], node.value
            elif isinstance(node, ast.withitem):
                tgts = [node.optional_vars] if node.optional_vars else []
                value = node.context_expr
            if tgts and value is not None and _is_raw_factory(value):
                for t in tgts:
                    for kind, name in _assign_names(t):
                        if kind == "name":
                            tainted.add(name)

        out: list[Finding] = []
        seen: set[int] = set()
        for node in _scope_nodes(body):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in STORE_OPS):
                continue
            base = node.func.value
            hit = None
            if isinstance(base, ast.Name) and base.id in tainted:
                hit = base.id
            elif isinstance(base, ast.Attribute):
                d = dotted(base)
                if d in tainted_attrs:
                    hit = d
            elif _is_raw_factory(base):
                hit = dotted(base.func) or "StoreClient(...)"
            if hit is not None and node.lineno not in seen:
                seen.add(node.lineno)
                out.append(self.finding(
                    sf, node,
                    f"raw store op `{hit}.{node.func.attr}(...)` outside "
                    "the resilience plane — build the handle with "
                    "store_plane.resilient_worker_store()/ResilientStore "
                    "for bounded timeout, retry, LKG cache and health "
                    "scoring (docs/fault_tolerance.md)"))
        return out
