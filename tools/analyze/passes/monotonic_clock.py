"""monotonic-clock: deadline/timeout/backoff math must not use wall time.

``time.time()`` jumps (NTP step, leap smear, operator clock set); a
deadline computed from it can expire hours early or never. The serving
SLO plane, hedging, elastic restart backoff and liveness blame all do
"now vs deadline" comparisons — those must run on ``time.monotonic()``.
Wall-clock is *correct* for journaled/event timestamps (humans and
cross-host merges read those), so the pass only fires when a wall-clock
reading flows into arithmetic that decides behavior:

- a comparison whose either side contains ``time.time()`` or a value
  derived from it (per-function + per-class ``self.x`` taint);
- ``deadline_ish = time.time() + ...`` (names matching
  deadline/until/expir/_by);
- a wall-derived value passed to a ``timeout``-named argument.

Comparisons against ``0``/``None`` are existence checks, not duration
math, and are ignored.
"""

from __future__ import annotations

import ast
import re

from tools.analyze.core import (AnalysisPass, Context, Finding, dotted,
                                register)

WALL_CALLS = {"time.time"}
_DEADLINEISH = re.compile(r"(deadline|until|expir|_by$)", re.I)
_TIMEOUTISH = re.compile(r"timeout", re.I)


def _contains_wall(node: ast.AST, tainted: set[str],
                   tainted_attrs: set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and dotted(sub.func) in WALL_CALLS:
            return True
        if isinstance(sub, ast.Name) and sub.id in tainted:
            return True
        if isinstance(sub, ast.Attribute):
            d = dotted(sub)
            if d in tainted_attrs:
                return True
    return False


def _is_null_check(comp: ast.Compare) -> bool:
    """`x > 0` / `x is None` style: existence, not duration math."""
    sides = [comp.left] + list(comp.comparators)
    for s in sides:
        if isinstance(s, ast.Constant) and s.value in (0, 0.0, None):
            return True
    return False


def _assign_names(node: ast.AST):
    if isinstance(node, ast.Name):
        yield ("name", node.id)
    elif isinstance(node, ast.Attribute):
        d = dotted(node)
        if d and d.startswith("self."):
            yield ("attr", d)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            yield from _assign_names(elt)


class _Scope:
    """One taint scope: a function body, or module top-level code."""

    def __init__(self, body, tainted_attrs: set[str]):
        self.body = body
        self.tainted: set[str] = set()
        self.tainted_attrs = tainted_attrs

    def collect(self):
        # Two passes so `a = time.time(); b = a - t0` taints b even when
        # helper ordering is odd; fixpoint beyond that is overkill.
        for _ in range(2):
            for node in self._own_nodes():
                tgts = None
                if isinstance(node, ast.Assign):
                    tgts, value = node.targets, node.value
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    tgts, value = [node.target], node.value
                if not tgts or value is None:
                    continue
                if isinstance(value, (ast.Dict, ast.List, ast.Set,
                                      ast.Tuple)):
                    # A timestamp stored in a record/container literal is
                    # journaling; comparisons on the container's OTHER
                    # members are unrelated to the wall clock.
                    continue
                if _contains_wall(value, self.tainted, self.tainted_attrs):
                    for t in tgts:
                        for kind, name in _assign_names(t):
                            if kind == "name":
                                self.tainted.add(name)

    def _own_nodes(self):
        stack = list(self.body)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # nested scopes are their own taint world
            stack.extend(ast.iter_child_nodes(node))


@register
class MonotonicClockPass(AnalysisPass):
    id = "monotonic-clock"
    description = ("wall-clock time.time() flowing into deadline/"
                   "timeout/backoff/staleness arithmetic")
    # Whole production surface; tests are excluded by discovery.
    include = ("**",)

    def run(self, ctx: Context) -> list[Finding]:
        out: list[Finding] = []
        for sf in self.files(ctx):
            # Class-wide attr taint: self._t0 = time.time() in any
            # method taints self._t0 reads in every method.
            attr_taint: dict[int, set[str]] = {}
            for cls in [n for n in ast.walk(sf.tree)
                        if isinstance(n, ast.ClassDef)]:
                attrs: set[str] = set()
                for node in ast.walk(cls):
                    if isinstance(node, ast.Assign) and _contains_wall(
                            node.value, set(), set()):
                        for t in node.targets:
                            for kind, name in _assign_names(t):
                                if kind == "attr":
                                    attrs.add(name)
                for fn in ast.walk(cls):
                    if isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                        attr_taint[id(fn)] = attrs
            funcs = [n for n in ast.walk(sf.tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
            for fn in funcs:
                out.extend(self._check_scope(
                    sf, fn.body, attr_taint.get(id(fn), set())))
            # Module top-level statements (scripts).
            top = [n for n in sf.tree.body
                   if not isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef,
                                         ast.ClassDef))]
            out.extend(self._check_scope(sf, top, set()))
        return out

    def _check_scope(self, sf, body, tainted_attrs) -> list[Finding]:
        scope = _Scope(body, tainted_attrs)
        scope.collect()
        out: list[Finding] = []
        seen_lines: set[int] = set()

        def emit(node, msg):
            if node.lineno not in seen_lines:
                seen_lines.add(node.lineno)
                out.append(self.finding(sf, node, msg))

        for node in scope._own_nodes():
            if isinstance(node, ast.Compare) and not _is_null_check(node):
                if any(_contains_wall(s, scope.tainted, tainted_attrs)
                       for s in [node.left] + list(node.comparators)):
                    emit(node, "wall-clock value in a deadline/staleness "
                               "comparison — use time.monotonic() "
                               "(wall jumps misfire deadlines)")
            elif isinstance(node, ast.Assign):
                if not isinstance(node.value, ast.BinOp):
                    continue
                if not _contains_wall(node.value, set(), set()):
                    continue  # direct time.time() arithmetic only here
                for t in node.targets:
                    for kind, name in _assign_names(t):
                        if _DEADLINEISH.search(name):
                            emit(node, f"deadline `{name}` computed from "
                                       "time.time() — use "
                                       "time.monotonic()")
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg and _TIMEOUTISH.search(kw.arg) and \
                            _contains_wall(kw.value, scope.tainted,
                                           tainted_attrs):
                        emit(node, f"wall-clock-derived value passed as "
                                   f"`{kw.arg}=` — compute remaining "
                                   "time from time.monotonic()")
        return out
