"""slo-catalog: obs/slo_budget.py SLO_CATALOG ↔ docs table.

The fifth catalog: every declared service-level objective must appear
in docs/observability.md's '## SLO catalog' table and vice versa — an
SLO nobody can look up has no owner, and a documented objective the
budget tracker never accounts is a promise nothing measures. Also
lints the declarations themselves (the closed-field contract the burn
-rate rules are generated from): ``good`` comes from GOOD_SIDES, every
SLO names at least one role, the objective is a proper fraction, and
the accounting window is positive.
"""

from __future__ import annotations

import os
import re

from tools.analyze.core import AnalysisPass, Context, Finding, register

_ROW = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|")
DOC_REL = os.path.join("docs", "observability.md")
SECTION = "## slo catalog"
CODE_REL = "pytorch_distributed_train_tpu/obs/slo_budget.py"


def documented_slos(doc_path: str) -> set[str]:
    from tools.analyze.core import doc_table_names

    return doc_table_names(doc_path, SECTION, _ROW)


def declared_slos() -> dict:
    from pytorch_distributed_train_tpu.obs.slo_budget import SLO_CATALOG

    return dict(SLO_CATALOG)


@register
class SloCatalogPass(AnalysisPass):
    id = "slo-catalog"
    description = ("service-level objectives: obs/slo_budget.py "
                   "SLO_CATALOG ↔ the doc's '## SLO catalog' table, "
                   "both ways, plus closed-field lint")
    include = (CODE_REL,)

    def run(self, ctx: Context) -> list[Finding]:
        from pytorch_distributed_train_tpu.obs.slo_budget import GOOD_SIDES

        doc_path = ctx.doc_path(DOC_REL)
        doc_rel = DOC_REL.replace(os.sep, "/")
        code = declared_slos()
        try:
            doc = documented_slos(doc_path)
        except OSError:
            return [Finding(self.id, doc_rel, 1,
                            "docs/observability.md is unreadable",
                            key="doc-missing")]
        if not doc:
            return [Finding(self.id, doc_rel, 1,
                            "no rows under '## SLO catalog' — was the "
                            "table renamed?", key="catalog-empty")]
        out: list[Finding] = []
        for name, slo in sorted(code.items()):
            if slo.good not in GOOD_SIDES:
                out.append(Finding(
                    self.id, CODE_REL, 1,
                    f"SLO `{name}` has good={slo.good!r} outside the "
                    f"closed set {sorted(GOOD_SIDES)}",
                    key=f"good:{name}"))
            if not slo.roles:
                out.append(Finding(
                    self.id, CODE_REL, 1,
                    f"SLO `{name}` applies to no role — its budget can "
                    f"never be accounted", key=f"roles:{name}"))
            if not 0.0 < slo.objective < 1.0:
                out.append(Finding(
                    self.id, CODE_REL, 1,
                    f"SLO `{name}` objective {slo.objective} is not a "
                    f"proper fraction (0 < objective < 1)",
                    key=f"objective:{name}"))
            if slo.window_s <= 0:
                out.append(Finding(
                    self.id, CODE_REL, 1,
                    f"SLO `{name}` has non-positive accounting window "
                    f"{slo.window_s}s", key=f"window:{name}"))
        for name in sorted(set(code) - doc):
            out.append(Finding(
                self.id, doc_rel, 1,
                f"SLO `{name}` declared in obs/slo_budget.py but "
                f"missing from the doc's SLO catalog",
                key=f"undocumented:{name}"))
        for name in sorted(doc - set(code)):
            out.append(Finding(
                self.id, doc_rel, 1,
                f"SLO `{name}` documented but absent from "
                f"obs/slo_budget.py SLO_CATALOG", key=f"phantom:{name}"))
        return out
