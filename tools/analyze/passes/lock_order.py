"""lock-order: inter-procedural lock-acquisition graph + cycle report.

`lock-scope` polices what runs *inside* one lock; this pass polices the
relationship *between* locks: which lock objects are acquired while
which others are held, across method and module-function calls. Two
code paths that take the same pair of locks in opposite orders are a
deadlock waiting for the right interleaving — c10d keeps its reducer
honest with exactly this discipline (plus TSAN); here the rule becomes
a gate.

How the graph is built:

- lock identity is the *creation site*: ``self.X = threading.Lock()``
  (or RLock/Condition — entering a Condition acquires its lock) keyed
  per class, and module-global ``_LOCK = threading.Lock()`` keyed per
  module. Instances of the same class share a node — two instances
  locked in both orders is the classic AB/BA hazard this pass exists
  to name, though a *self*-edge (two instances of one class nested) is
  skipped: direction is meaningless on a single node.
- within a function the walk is lexical: ``with self._lock:`` bodies
  extend the held set (nested defs are skipped — closures run later,
  not here); an explicit ``.acquire()`` on a known lock records an
  acquisition at that point but does not extend the held set (its
  matching release is not lexically findable).
- calls are resolved inter-procedurally: ``self`` methods, same-module
  and imported-module functions, constructor calls (``Cls()`` runs
  ``Cls.__init__``), and method calls through typed expressions —
  ``self.<attr>`` chains assigned a constructor or factory-function
  result, module-global singletons (``_X = Cls()``, including
  ``global``-statement assigns), and factory returns resolved from
  ``return Cls(...)`` / ``return <global>`` / ``return self.<attr>``.
  Everything a callee transitively acquires becomes an edge from every
  lock held at the call site.
- parameters and dynamically-injected collaborators are *not* resolved
  — that blind spot is exactly what ``--compare-runtime`` (diffing this
  static graph against a ``utils/syncdbg.py`` runtime recording) turns
  into a named pass-gap report instead of silence.

Findings: one per strongly-connected component of the edge graph with
≥ 2 locks, naming the cycle and one concrete acquisition path for each
direction.
"""

from __future__ import annotations

import ast
import dataclasses

from tools.analyze.core import (AnalysisPass, Context, Finding,
                                LOCK_FACTORIES, dotted, register)

SCOPE = (
    "pytorch_distributed_train_tpu/serving_plane/",
    "pytorch_distributed_train_tpu/ckpt/",
    "pytorch_distributed_train_tpu/obs/",
    "pytorch_distributed_train_tpu/faults/",
    "pytorch_distributed_train_tpu/elastic.py",
    "pytorch_distributed_train_tpu/data/workers.py",
    "pytorch_distributed_train_tpu/fleet/",
    "pytorch_distributed_train_tpu/online/",
    "tools/serve_http.py",
    "tools/serve_router.py",
    "tools/fleet_controller.py",
    "tools/online_loop.py",
)


# ------------------------------------------------------------- symbol table
@dataclasses.dataclass
class ClassInfo:
    key: str                     # "path::ClassName"
    name: str
    path: str
    node: ast.ClassDef
    locks: dict = dataclasses.field(default_factory=dict)   # attr -> [lines]
    methods: dict = dataclasses.field(default_factory=dict)  # name -> node
    attr_values: dict = dataclasses.field(default_factory=dict)  # attr->expr
    attr_types: dict = dataclasses.field(default_factory=dict)  # attr -> key


@dataclasses.dataclass
class FuncInfo:
    key: str                     # "path::Class.m" / "path::f"
    short: str                   # "Class.m" / "f"
    path: str
    node: ast.AST
    cls: ClassInfo | None
    # (held_lock, held_line, acquired_lock, line) — lexical nesting
    nested: list = dataclasses.field(default_factory=list)
    # (lock, line) — every acquisition, for reachability
    acqs: list = dataclasses.field(default_factory=list)
    # (callee_key, line, held_tuple) — held_tuple: ((lock, line), ...)
    calls: list = dataclasses.field(default_factory=list)


def _candidate_values(value: ast.AST):
    """The sub-expressions a ``x = ...`` value may evaluate to:
    unwraps ``a if c else b`` and ``a or b``."""
    stack, out = [value], []
    while stack:
        v = stack.pop()
        if isinstance(v, ast.IfExp):
            stack.extend((v.body, v.orelse))
        elif isinstance(v, ast.BoolOp):
            stack.extend(v.values)
        else:
            out.append(v)
    return out


class _AnnMarker:
    """A module global declared by annotation only: carries the
    annotation expression (``Cls | None``) instead of a value."""

    __slots__ = ("ann",)

    def __init__(self, ann: ast.AST):
        self.ann = ann


def _self_assigns(cls: ast.ClassDef):
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                yield tgt.attr, node.value, node.lineno


class _Table:
    """Symbol + type tables over the analyzed surface."""

    def __init__(self, files):
        self.by_path = {sf.path: sf for sf in files}
        self.classes: dict[str, ClassInfo] = {}
        self.by_name: dict[str, list[str]] = {}
        self.mod_funcs: dict[str, ast.AST] = {}
        self.mod_locks: dict[str, dict[str, list[int]]] = {}
        self.mod_globals: dict[str, dict[str, ast.AST]] = {}
        self.mod_imports: dict[str, dict[str, str]] = {}      # alias -> path
        self.from_funcs: dict[str, dict[str, str]] = {}       # name -> fkey
        self.from_classes: dict[str, dict[str, str]] = {}     # name -> ckey
        self._ret_memo: dict[str, str | None] = {}
        for sf in files:
            self._collect_module(sf)
        self._collect_imports(files)
        # attr types need every other table; a few rounds reach the
        # fixpoint for chained attr -> factory -> class resolution
        self._attr_fixpoint()
        # injected collaborators: `self.X = <param>` in __init__, bound
        # from the argument types at resolvable constructor call sites
        # (the serve plane wires its monitor/profiler/replica-set this
        # way — without this layer those subgraphs are invisible)
        self._bind_ctor_params(files)
        self._attr_fixpoint()

    def _attr_fixpoint(self) -> None:
        for _ in range(4):
            changed = False
            for ci in self.classes.values():
                for attr, value in ci.attr_values.items():
                    if attr in ci.attr_types:
                        continue
                    t = self.expr_type(value, ci.path, ci)
                    if t is not None:
                        ci.attr_types[attr] = t
                        changed = True
            if not changed:
                break

    # --------------------------------------------------------- collection
    def _collect_module(self, sf) -> None:
        locks: dict[str, list[int]] = {}
        # every candidate value a module global is ever assigned —
        # `_X = None` at module scope then `global _X; _X = Cls()` in a
        # lazy builder means BOTH exprs are candidates; annotation-only
        # declarations (`_X: Cls | None = None`) contribute their
        # annotation's class names
        mod_globals: dict[str, list[ast.AST]] = {}
        for node in sf.tree.body:
            if isinstance(node, ast.Assign):
                is_lock = (isinstance(node.value, ast.Call)
                           and dotted(node.value.func) in LOCK_FACTORIES)
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    if is_lock:
                        locks.setdefault(tgt.id, []).append(node.lineno)
                    else:
                        mod_globals.setdefault(tgt.id, []).append(
                            node.value)
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                anns = mod_globals.setdefault(node.target.id, [])
                anns.append(_AnnMarker(node.annotation))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.mod_funcs[f"{sf.path}::{node.name}"] = node
        # `global X; X = Cls()` inside functions is how the repo's
        # lazily-built singletons (tracer, recorder, registry) appear
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            gnames = {n for sub in ast.walk(node)
                      if isinstance(sub, ast.Global) for n in sub.names}
            if not gnames:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name) and tgt.id in gnames:
                        mod_globals.setdefault(tgt.id, []).append(
                            sub.value)
        self.mod_locks[sf.path] = locks
        self.mod_globals[sf.path] = mod_globals
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            ci = ClassInfo(f"{sf.path}::{node.name}", node.name,
                           sf.path, node)
            for attr, value, line in _self_assigns(node):
                if isinstance(value, ast.Call) and \
                        dotted(value.func) in LOCK_FACTORIES:
                    ci.locks.setdefault(attr, []).append(line)
                else:
                    ci.attr_values.setdefault(attr, value)
            for m in node.body:
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[m.name] = m
            self.classes[ci.key] = ci
            self.by_name.setdefault(node.name, []).append(ci.key)

    def _module_path(self, dotted_mod: str) -> str | None:
        rel = dotted_mod.replace(".", "/")
        for cand in (rel + ".py", rel + "/__init__.py"):
            if cand in self.by_path:
                return cand
        return None

    def _rel_module(self, sf_path: str, level: int,
                    module: str | None) -> str | None:
        """Resolve a relative ``from ...x import y`` base module.
        Level 1 is the containing package — the file's directory, for
        plain modules and ``__init__.py`` alike."""
        parts = sf_path.split("/")[:-1]
        for _ in range(level - 1):
            if not parts:
                return None
            parts = parts[:-1]
        dotted_mod = ".".join(parts + (module.split(".") if module else []))
        return self._module_path(dotted_mod) if dotted_mod else None

    def _collect_imports(self, files) -> None:
        # phase 1: module aliases + pending from-imports (target may be
        # a re-export through a package __init__, resolved in phase 2)
        pending: dict[str, list[tuple[str, str, str]]] = {}
        for sf in files:
            aliases: dict[str, str] = {}
            todo: list[tuple[str, str, str]] = []
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        p = self._module_path(a.name)
                        if p is not None:
                            aliases[a.asname or a.name.split(".")[0]] = p
                elif isinstance(node, ast.ImportFrom):
                    if node.level == 0:
                        base = self._module_path(node.module or "")
                        subfmt = (node.module or "") + ".{}"
                    else:
                        base = self._rel_module(sf.path, node.level,
                                                node.module)
                        subfmt = None
                    for a in node.names:
                        local = a.asname or a.name
                        sub = None
                        if subfmt is not None:
                            sub = self._module_path(subfmt.format(a.name))
                        elif base is not None and \
                                base.endswith("/__init__.py"):
                            sub = self._module_path(
                                base[:-len("/__init__.py")].replace("/", ".")
                                + "." + a.name)
                        if sub is not None:     # `from pkg import module`
                            aliases[local] = sub
                            continue
                        if base is not None:
                            todo.append((local, base, a.name))
            self.mod_imports[sf.path] = aliases
            self.from_funcs[sf.path] = {}
            self.from_classes[sf.path] = {}
            pending[sf.path] = todo
        # phase 2: resolve names, following re-export chains (a few
        # rounds cover __init__ -> module -> definition)
        for _ in range(4):
            changed = False
            for path, todo in pending.items():
                for local, base, name in todo:
                    if local in self.from_funcs[path] or \
                            local in self.from_classes[path]:
                        continue
                    if f"{base}::{name}" in self.mod_funcs:
                        self.from_funcs[path][local] = f"{base}::{name}"
                    elif f"{base}::{name}" in self.classes:
                        self.from_classes[path][local] = f"{base}::{name}"
                    elif name in self.from_funcs.get(base, {}):
                        self.from_funcs[path][local] = \
                            self.from_funcs[base][name]
                    elif name in self.from_classes.get(base, {}):
                        self.from_classes[path][local] = \
                            self.from_classes[base][name]
                    else:
                        continue
                    changed = True
            if not changed:
                break

    def _param_attr_map(self, ci: ClassInfo) -> dict[str, str]:
        """param name -> self attr for ``self.X = <param>`` assigns in
        ``__init__`` (through ``a if c else b`` / ``a or b``)."""
        init = ci.methods.get("__init__")
        if init is None:
            return {}
        params = {a.arg for a in list(init.args.args)
                  + list(init.args.kwonlyargs)} - {"self"}
        out: dict[str, str] = {}
        for node in ast.walk(init):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                for v in _candidate_values(node.value):
                    if isinstance(v, ast.Name) and v.id in params:
                        out[v.id] = tgt.attr
        return out

    def _bind_ctor_params(self, files) -> None:
        for sf in files:
            # innermost class per node, for `self` at the call site
            cls_of: dict[int, ClassInfo] = {}
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef):
                    ci = self.classes.get(f"{sf.path}::{node.name}")
                    if ci is None:
                        continue
                    for sub in ast.walk(node):
                        cls_of[id(sub)] = ci
            # one-level local-variable types per function (module main()
            # builds monitor/plane/router in locals before wiring them)
            funcs = [n for n in ast.walk(sf.tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
            inner: dict[int, ast.AST] = {}
            for fn in funcs:
                for sub in ast.walk(fn):
                    inner[id(sub)] = fn
            local_types: dict[int, dict[str, str]] = {}
            for fn in funcs:
                env: dict[str, str] = {}
                ci = cls_of.get(id(fn))
                for sub in ast.walk(fn):
                    if inner[id(sub)] is not fn or \
                            not isinstance(sub, ast.Assign):
                        continue
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            t = self.expr_type(sub.value, sf.path, ci)
                            if t is not None:
                                env.setdefault(tgt.id, t)
                local_types[id(fn)] = env
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if d is None:
                    continue
                ck = None
                if "." not in d:
                    ck = self.resolve_class(d, sf.path)
                else:
                    head, tail = d.split(".", 1)
                    mod = self.mod_imports.get(sf.path, {}).get(head)
                    if mod is not None and "." not in tail \
                            and f"{mod}::{tail}" in self.classes:
                        ck = f"{mod}::{tail}"
                if ck is None:
                    continue
                tci = self.classes[ck]
                pmap = self._param_attr_map(tci)
                if not pmap:
                    continue
                init = tci.methods["__init__"]
                pos = [a.arg for a in init.args.args[1:]]
                ci = cls_of.get(id(node))
                env = local_types.get(id(inner.get(id(node))), {})

                def _argtype(expr):
                    if isinstance(expr, ast.Name) and expr.id in env:
                        return env[expr.id]
                    return self.expr_type(expr, sf.path, ci)

                for i, arg in enumerate(node.args):
                    if i < len(pos) and pos[i] in pmap:
                        t = _argtype(arg)
                        if t is not None:
                            tci.attr_types.setdefault(pmap[pos[i]], t)
                for kw in node.keywords:
                    if kw.arg in pmap:
                        t = _argtype(kw.value)
                        if t is not None:
                            tci.attr_types.setdefault(pmap[kw.arg], t)

    # --------------------------------------------------------- resolution
    def resolve_class(self, name: str, path: str) -> str | None:
        """A bare class name at a use site → class key: same module,
        explicit from-import, else unique across the surface."""
        key = f"{path}::{name}"
        if key in self.classes:
            return key
        k = self.from_classes.get(path, {}).get(name)
        if k is not None:
            return k
        keys = self.by_name.get(name, ())
        return keys[0] if len(keys) == 1 else None

    def resolve_func(self, name: str, path: str) -> str | None:
        key = f"{path}::{name}"
        if key in self.mod_funcs:
            return key
        return self.from_funcs.get(path, {}).get(name)

    def resolve_call_target(self, call: ast.Call, path: str,
                            ci: ClassInfo | None) -> str | None:
        """Call expression → function/method key (``Cls()`` resolves to
        ``Cls.__init__`` when one is defined)."""
        func = call.func
        if isinstance(func, ast.Name):
            fk = self.resolve_func(func.id, path)
            if fk is not None:
                return fk
            ck = self.resolve_class(func.id, path)
            if ck is not None and "__init__" in self.classes[ck].methods:
                tci = self.classes[ck]
                return f"{tci.path}::{tci.name}.__init__"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        recv = func.value
        # imported-module function: events_lib.emit(...)
        if isinstance(recv, ast.Name):
            mod = self.mod_imports.get(path, {}).get(recv.id)
            if mod is not None:
                fk = f"{mod}::{func.attr}"
                if fk in self.mod_funcs:
                    return fk
                # module.Class(...) constructor
                ck = f"{mod}::{func.attr}"
                if ck in self.classes and \
                        "__init__" in self.classes[ck].methods:
                    return f"{ck}.__init__"
                return None
        # typed receiver: self.m(), self.a.b.m(), get_x().m(), _GLOBAL.m()
        t = self.expr_type(recv, path, ci)
        if t is not None:
            tci = self.classes.get(t)
            if tci is not None and func.attr in tci.methods:
                return f"{tci.path}::{tci.name}.{func.attr}"
        return None

    def expr_type(self, expr: ast.AST, path: str,
                  ci: ClassInfo | None, depth: int = 0) -> str | None:
        """Best-effort class key an expression evaluates to."""
        if depth > 6:
            return None
        for v in _candidate_values(expr):
            t = self._expr_type_one(v, path, ci, depth)
            if t is not None:
                return t
        return None

    def _global_type(self, mod: str, name: str, depth: int) -> str | None:
        """Type of a module global: first resolvable candidate value,
        else a class named in its annotation."""
        for g in self.mod_globals.get(mod, {}).get(name, ()):
            if isinstance(g, _AnnMarker):
                for sub in ast.walk(g.ann):
                    d = dotted(sub)
                    if d is None:
                        continue
                    ck = self.resolve_class(d.rsplit(".", 1)[-1], mod)
                    if ck is not None:
                        return ck
                continue
            t = self.expr_type(g, mod, None, depth + 1)
            if t is not None:
                return t
        return None

    def _expr_type_one(self, v, path, ci, depth) -> str | None:
        if isinstance(v, ast.Name):
            if v.id == "self" and ci is not None:
                return ci.key
            return self._global_type(path, v.id, depth)
        if isinstance(v, ast.Attribute):
            if isinstance(v.value, ast.Name):
                mod = self.mod_imports.get(path, {}).get(v.value.id)
                if mod is not None:     # module-global singleton use
                    return self._global_type(mod, v.attr, depth)
            base = self.expr_type(v.value, path, ci, depth + 1)
            if base is not None:
                bci = self.classes.get(base)
                if bci is not None:
                    return bci.attr_types.get(v.attr)
            return None
        if isinstance(v, ast.Call):
            d = dotted(v.func)
            if d is not None:
                ck = self.resolve_class(d.rsplit(".", 1)[-1], path) \
                    if "." not in d else None
                if "." not in d:
                    if ck is not None:
                        return ck
                    fk = self.resolve_func(d, path)
                    if fk is not None:
                        return self.return_type(fk, depth + 1)
                else:
                    head, tail = d.split(".", 1)
                    mod = self.mod_imports.get(path, {}).get(head)
                    if mod is not None and "." not in tail:
                        if f"{mod}::{tail}" in self.classes:
                            return f"{mod}::{tail}"
                        if f"{mod}::{tail}" in self.mod_funcs:
                            return self.return_type(f"{mod}::{tail}",
                                                    depth + 1)
            return None
        return None

    def return_type(self, func_key: str, depth: int = 0) -> str | None:
        if func_key in self._ret_memo:
            return self._ret_memo[func_key]
        self._ret_memo[func_key] = None     # cycle guard
        node = self.mod_funcs.get(func_key)
        path = func_key.split("::", 1)[0]
        ci = None
        if node is None:
            cls_part, mname = func_key.rsplit(".", 1)
            ci = self.classes.get(cls_part)
            if ci is None:
                return None
            node = ci.methods.get(mname)
            if node is None:
                return None
        types = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Return) and sub.value is not None:
                t = self.expr_type(sub.value, path, ci, depth + 1)
                if t is not None:
                    types.add(t)
        out = types.pop() if len(types) == 1 else None
        self._ret_memo[func_key] = out
        return out


# ----------------------------------------------------------- per-function
def _lock_of_withitem(item, ci: ClassInfo | None, mod_locks, path):
    expr = item.context_expr
    if (isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name)
            and expr.value.id == "self" and ci is not None
            and expr.attr in ci.locks):
        return f"{path}::{ci.name}.{expr.attr}"
    if isinstance(expr, ast.Name) and expr.id in mod_locks:
        return f"{path}::{expr.id}"
    return None


def _lock_of_receiver(func: ast.Attribute, ci, mod_locks, path):
    """`self._lock.acquire()` / `_LOCK.acquire()` receivers."""
    recv = func.value
    if (isinstance(recv, ast.Attribute) and isinstance(recv.value, ast.Name)
            and recv.value.id == "self" and ci is not None
            and recv.attr in ci.locks):
        return f"{path}::{ci.name}.{recv.attr}"
    if isinstance(recv, ast.Name) and recv.id in mod_locks:
        return f"{path}::{recv.id}"
    return None


def _scan_function(fi: FuncInfo, table: _Table) -> None:
    ci = fi.cls
    mod_locks = table.mod_locks.get(fi.path, {})
    # DFS with lexical held set: (node, held) where held is a tuple of
    # (lock_id, acquired_at_line).
    stack: list[tuple[ast.AST, tuple]] = [
        (n, ()) for n in reversed(fi.node.body)]
    while stack:
        node, held = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue  # separate execution context
        if isinstance(node, ast.With):
            inner = held
            for item in node.items:
                # a later withitem's context expr evaluates with the
                # earlier locks already held
                for sub in ast.walk(item.context_expr):
                    if isinstance(sub, ast.Call):
                        _note_call(fi, sub, inner, ci, mod_locks, table)
                lock = _lock_of_withitem(item, ci, mod_locks, fi.path)
                if lock is not None:
                    fi.acqs.append((lock, node.lineno))
                    for h, hline in inner:
                        if h != lock:
                            fi.nested.append((h, hline, lock, node.lineno))
                    inner = inner + ((lock, node.lineno),)
            for child in reversed(node.body):
                stack.append((child, inner))
            continue
        if isinstance(node, ast.Call):
            _note_call(fi, node, held, ci, mod_locks, table)
        for child in reversed(list(ast.iter_child_nodes(node))):
            stack.append((child, held))


def _note_call(fi: FuncInfo, call: ast.Call, held, ci, mod_locks, table):
    func = call.func
    if isinstance(func, ast.Attribute) and \
            func.attr in ("acquire", "__enter__"):
        lock = _lock_of_receiver(func, ci, mod_locks, fi.path)
        if lock is not None:
            fi.acqs.append((lock, call.lineno))
            for h, hline in held:
                if h != lock:
                    fi.nested.append((h, hline, lock, call.lineno))
            return
    callee = table.resolve_call_target(call, fi.path, ci)
    if callee is not None and callee != fi.key:
        fi.calls.append((callee, call.lineno, held))


# ----------------------------------------------------------------- graph
class LockGraph:
    """Static result: ``nodes`` (lock id -> creation sites) and
    ``edges`` ((a, b) -> one concrete acquisition path, as text steps);
    a→b means "b acquired while a is held" somewhere."""

    def __init__(self):
        self.nodes: dict[str, list[tuple[str, int]]] = {}
        self.edges: dict[tuple[str, str], list[str]] = {}

    def add_edge(self, a: str, b: str, chain: list[str]) -> None:
        if a == b:
            return
        cur = self.edges.get((a, b))
        if cur is None or len(chain) < len(cur):
            self.edges[(a, b)] = chain

    def sccs(self) -> list[list[str]]:
        """Tarjan strongly-connected components with ≥ 2 nodes."""
        adj: dict[str, list[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on: set[str] = set()
        order: list[str] = []
        out: list[list[str]] = []
        counter = [0]

        def strongconnect(v: str):
            # iterative Tarjan (the call graph can be deep)
            work = [(v, 0)]
            while work:
                node, pi = work[-1]
                if pi == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    order.append(node)
                    on.add(node)
                recurse = False
                for w in adj[node][pi:]:
                    work[-1] = (node, work[-1][1] + 1)
                    if w not in index:
                        work.append((w, 0))
                        recurse = True
                        break
                    if w in on:
                        low[node] = min(low[node], index[w])
                if recurse:
                    continue
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = order.pop()
                        on.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        out.append(sorted(comp))
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        return sorted(out)

    def cycle_in(self, comp: list[str]) -> list[str]:
        """One concrete cycle inside an SCC: BFS from its first node
        back to itself, restricted to the component."""
        comp_set = set(comp)
        start = comp[0]
        prev: dict[str, str] = {}
        frontier = [start]
        seen = {start}
        while frontier:
            nxt = []
            for v in frontier:
                for (a, b) in self.edges:
                    if a != v or b not in comp_set:
                        continue
                    if b == start:
                        path = [v]
                        node = v
                        while node != start:
                            node = prev[node]
                            path.append(node)
                        path.reverse()
                        return path + [start]
                    if b not in seen:
                        seen.add(b)
                        prev[b] = v
                        nxt.append(b)
            frontier = nxt
        return [start, start]  # unreachable for a true SCC


def build_graph(ctx: Context, include=SCOPE) -> LockGraph:
    from tools.analyze.core import path_matches

    files = [sf for sf in ctx.files
             if sf.tree is not None and path_matches(sf.path, include)]
    table = _Table(files)
    graph = LockGraph()
    for ci in table.classes.values():
        for attr, lines in ci.locks.items():
            graph.nodes[f"{ci.path}::{ci.name}.{attr}"] = [
                (ci.path, ln) for ln in lines]
    for path, locks in table.mod_locks.items():
        for name, lines in locks.items():
            graph.nodes[f"{path}::{name}"] = [(path, ln) for ln in lines]

    funcs: dict[str, FuncInfo] = {}
    for ci in table.classes.values():
        for name, node in ci.methods.items():
            key = f"{ci.path}::{ci.name}.{name}"
            funcs[key] = FuncInfo(key, f"{ci.name}.{name}", ci.path,
                                  node, ci)
    for key, node in table.mod_funcs.items():
        path, name = key.split("::", 1)
        funcs.setdefault(key, FuncInfo(key, name, path, node, None))
    for fi in funcs.values():
        _scan_function(fi, table)

    # reachable acquisitions per function (fixpoint over the call graph)
    reach: dict[str, dict[str, list[str]]] = {}
    for key, fi in funcs.items():
        reach[key] = {}
        for lock, line in fi.acqs:
            if lock not in reach[key]:
                reach[key][lock] = [
                    f"{fi.path}:{line} {fi.short} acquires "
                    f"`{_short(lock)}`"]
    changed = True
    while changed:
        changed = False
        for key, fi in funcs.items():
            mine = reach[key]
            for callee, line, _held in fi.calls:
                if callee == key:
                    continue
                for lock, chain in reach.get(callee, {}).items():
                    if lock in mine:
                        continue
                    mine[lock] = [f"{fi.path}:{line} {fi.short} calls "
                                  f"{funcs[callee].short}"] + chain
                    changed = True

    # edges: lexical nesting + (held at a call site) x (callee reach)
    for key in sorted(funcs):
        fi = funcs[key]
        for held, hline, lock, line in fi.nested:
            graph.add_edge(held, lock, [
                f"{fi.path}:{line} {fi.short} acquires `{_short(lock)}` "
                f"while holding `{_short(held)}` (since line {hline})"])
        for callee, line, held_tuple in fi.calls:
            if not held_tuple:
                continue
            callee_reach = reach.get(callee, {})
            for held, hline in held_tuple:
                for lock, chain in callee_reach.items():
                    if lock == held:
                        continue
                    graph.add_edge(held, lock, [
                        f"{fi.path}:{line} {fi.short} (holding "
                        f"`{_short(held)}`, since line {hline}) calls "
                        f"{funcs[callee].short}"] + chain)
    return graph


def _short(lock_id: str) -> str:
    path, name = lock_id.split("::", 1)
    return f"{path.rsplit('/', 1)[-1]}::{name}"


def _fmt_chain(chain: list[str]) -> str:
    return " -> ".join(chain)


@register
class LockOrderPass(AnalysisPass):
    id = "lock-order"
    description = ("inter-procedural lock-acquisition graph: a cycle "
                   "(locks taken in both orders on different paths) is "
                   "a deadlock hazard")
    include = SCOPE

    def run(self, ctx: Context) -> list[Finding]:
        graph = build_graph(ctx, self.include)
        out: list[Finding] = []
        for comp in graph.sccs():
            cycle = graph.cycle_in(comp)
            legs = []
            for a, b in zip(cycle, cycle[1:]):
                chain = graph.edges.get((a, b), ["<edge>"])
                legs.append(f"`{_short(a)}` -> `{_short(b)}` via: "
                            f"{_fmt_chain(chain)}")
            anchor_path, anchor_line = _anchor(graph, cycle)
            names = " -> ".join(_short(n) for n in cycle)
            out.append(Finding(
                self.id, anchor_path, anchor_line,
                f"lock-order cycle (deadlock hazard): {names}. "
                + " ; ".join(legs)
                + ". Pick one global order for these locks or drop one "
                  "acquisition out of the overlap.",
                key="cycle:" + "->".join(sorted(set(comp)))))
        return out


def _anchor(graph: LockGraph, cycle: list[str]):
    """(path, line) to pin the finding on: the head lock's creation
    site (stable, survives call-site drift)."""
    sites = graph.nodes.get(cycle[0])
    if sites:
        return sites[0]
    path = cycle[0].split("::", 1)[0]
    return path, 1
