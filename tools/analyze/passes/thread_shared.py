"""thread-shared-state: attributes a spawned thread writes unlocked.

A class that does ``threading.Thread(target=self._worker)`` has two
execution contexts; an attribute the worker (or anything it calls
through ``self``) *writes* outside the class lock, and another method
also touches outside the lock, is a data race the GIL only papers over
for single-opcode accesses. Findings are per (class, attribute) and
carry warning severity: some of these are deliberately GIL-atomic
flags — those belong in the baseline with a reason saying so, which is
itself the documentation the next reader needs.

Skips: ``__init__`` writes (pre-start), attributes that *are*
synchronization primitives or thread handles (Lock/Event/Queue/
deque/Thread — their methods are the synchronization), and classes
with no spawned thread.
"""

from __future__ import annotations

import ast

from tools.analyze.core import (AnalysisPass, Context, Finding,
                                class_lock_attrs, dotted,
                                module_lock_names, register,
                                withitem_lock_name)

# self.X = <factory>() where the factory yields a thread-safe object or
# a handle whose cross-thread use is the point.
SAFE_FACTORIES = ("threading.", "queue.", "collections.deque")


def _thread_target_methods(cls: ast.ClassDef) -> set[str]:
    """Methods named as Thread(target=self.X) anywhere in the class,
    closed transitively over self-method calls (the worker's helpers
    run on the worker thread too)."""
    targets: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and \
                (dotted(node.func) or "").endswith("Thread"):
            for kw in node.keywords:
                if kw.arg == "target" and \
                        isinstance(kw.value, ast.Attribute) and \
                        isinstance(kw.value.value, ast.Name) and \
                        kw.value.value.id == "self":
                    targets.add(kw.value.attr)
    if not targets:
        return targets
    calls: dict[str, set[str]] = {}
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            callees: set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id == "self":
                    callees.add(sub.func.attr)
            calls[node.name] = callees
    changed = True
    while changed:
        changed = False
        for m in list(targets):
            for callee in calls.get(m, ()):
                if callee in calls and callee not in targets:
                    targets.add(callee)
                    changed = True
    return targets


def _safe_attrs(cls: ast.ClassDef) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            d = dotted(node.value.func) or ""
            if any(d.startswith(p) or d == p.rstrip(".")
                   for p in SAFE_FACTORIES):
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        out.add(tgt.attr)
    return out


class _AttrAccess:
    __slots__ = ("writes_thread_unlocked", "other_unlocked", "first_line")

    def __init__(self):
        self.writes_thread_unlocked: list[int] = []
        self.other_unlocked: list[tuple[str, int]] = []
        self.first_line = 0


@register
class ThreadSharedStatePass(AnalysisPass):
    id = "thread-shared-state"
    description = ("attributes written by a spawned-thread method and "
                   "accessed elsewhere, both outside the class lock")
    include = (
        "pytorch_distributed_train_tpu/serving_plane/",
        "pytorch_distributed_train_tpu/ckpt/",
        "pytorch_distributed_train_tpu/sentinel/",
        "pytorch_distributed_train_tpu/elastic.py",
        # shared-memory decode plane: worker processes + a submitter
        # thread against ring state — in scope from day one (ISSUE 12)
        "pytorch_distributed_train_tpu/data/workers.py",
        "tools/serve_*.py",
    )

    def run(self, ctx: Context) -> list[Finding]:
        out: list[Finding] = []
        for sf in self.files(ctx):
            global_locks = module_lock_names(sf.tree)
            for cls in [n for n in ast.walk(sf.tree)
                        if isinstance(n, ast.ClassDef)]:
                out.extend(self._check_class(sf, cls, global_locks))
        return out

    def _check_class(self, sf, cls, global_locks) -> list[Finding]:
        thread_methods = _thread_target_methods(cls)
        if not thread_methods:
            return []
        locks = class_lock_attrs(cls)
        skip = _safe_attrs(cls) | locks
        acc: dict[str, _AttrAccess] = {}
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            if method.name == "__init__":
                continue
            on_thread = method.name in thread_methods
            self._scan(method, on_thread, method.name, locks,
                       global_locks, skip, acc)
        out = []
        for attr, a in sorted(acc.items()):
            if a.writes_thread_unlocked and a.other_unlocked:
                other = a.other_unlocked[0]
                out.append(Finding(
                    self.id, sf.path, a.writes_thread_unlocked[0],
                    f"`self.{attr}` is written on the spawned thread "
                    f"(line {a.writes_thread_unlocked[0]}) and accessed "
                    f"in `{other[0]}` (line {other[1]}), neither under "
                    f"the class lock — guard both or baseline with the "
                    f"reason it is safe", severity="warning",
                    key=f"{cls.name}.{attr}"))
        return out

    def _scan(self, method, on_thread, name, locks, global_locks, skip,
              acc) -> None:
        # Lexical lock tracking: (node, locked?) DFS.
        stack: list[tuple[ast.AST, bool]] = [(n, False)
                                             for n in method.body]
        while stack:
            node, locked = stack.pop()
            if isinstance(node, ast.With):
                inner = locked or any(
                    withitem_lock_name(i, locks, global_locks)
                    for i in node.items)
                for child in node.body:
                    stack.append((child, inner))
                for item in node.items:
                    stack.append((item, locked))
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # closures: separate execution context
            for child in ast.iter_child_nodes(node):
                stack.append((child, locked))
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr not in skip):
                continue
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            if not is_write and not isinstance(node.ctx, ast.Load):
                continue
            a = acc.setdefault(node.attr, _AttrAccess())
            if locked:
                continue
            if on_thread and is_write:
                a.writes_thread_unlocked.append(node.lineno)
            elif not on_thread:
                a.other_unlocked.append((name, node.lineno))
