"""event-catalog: docs ↔ obs/events.py CATEGORIES ↔ emit() call sites.

The analyzer-plugin port of ``tools/check_events.py`` (now a thin shim
over this module). Three-way: every declared category is documented,
every documented category is declared, every ``emit("<cat>", ...)``
literal names a declared category (an undeclared one raises at
runtime — catch it in CI instead), and every declared category has at
least one emitter (a category nothing can produce is a dead doc row).
"""

from __future__ import annotations

import ast
import os
import re

from tools.analyze.core import (AnalysisPass, Context, Finding, dotted,
                                register)

_ROW = re.compile(r"^\|\s*`([a-z_]+)`\s*\|")
DOC_REL = os.path.join("docs", "observability.md")
SECTION = "## event categories"
# The definition site and the shim's own docstring are not emitters.
SKIP_SUFFIXES = (os.path.join("obs", "events.py").replace(os.sep, "/"),
                 "check_events.py")


def documented_categories(doc_path: str) -> set[str]:
    """Category names from the first column of the '## Event categories'
    table (only that section)."""
    from tools.analyze.core import doc_table_names

    return doc_table_names(doc_path, SECTION, _ROW)


def declared_categories() -> set[str]:
    from pytorch_distributed_train_tpu.obs.events import CATEGORIES

    return set(CATEGORIES)


def emit_sites(tree: ast.AST) -> list[tuple[str, int]]:
    """(category, lineno) for every ``emit("<literal>", ...)`` call —
    func named exactly ``emit`` (bare or attribute), so wrappers like
    ``self._emit`` with a different first-arg contract don't count."""
    out: list[tuple[str, int]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if name != "emit" or not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, node.lineno))
    return out


@register
class EventCatalogPass(AnalysisPass):
    id = "event-catalog"
    description = ("event categories: docs table ↔ obs/events.py "
                   "CATEGORIES ↔ emit() call sites, three-way")
    include = ("pytorch_distributed_train_tpu/", "tools/",
               "train.py", "tpurun.py")

    def run(self, ctx: Context) -> list[Finding]:
        doc_path = ctx.doc_path(DOC_REL)
        doc_rel = DOC_REL.replace(os.sep, "/")
        code = declared_categories()
        try:
            doc = documented_categories(doc_path)
        except OSError:
            return [Finding(self.id, doc_rel, 1,
                            "docs/observability.md is unreadable",
                            key="doc-missing")]
        if not doc:
            return [Finding(self.id, doc_rel, 1,
                            "no rows under '## Event categories' — was "
                            "the table renamed?", key="catalog-empty")]
        used: dict[str, tuple[str, int]] = {}
        undeclared: list[Finding] = []
        for sf in self.files(ctx):
            if sf.path.endswith(SKIP_SUFFIXES):
                continue
            for cat, line in emit_sites(sf.tree):
                used.setdefault(cat, (sf.path, line))
                if cat not in code:
                    undeclared.append(Finding(
                        self.id, sf.path, line,
                        f"emit() uses undeclared category `{cat}` "
                        f"(would raise at runtime)",
                        key=f"undeclared:{cat}"))
        out: list[Finding] = undeclared
        for c in sorted(code - doc):
            out.append(Finding(
                self.id, doc_rel, 1,
                f"category `{c}` declared in obs/events.py but missing "
                f"from the doc table", key=f"undocumented:{c}"))
        for c in sorted(doc - code):
            out.append(Finding(
                self.id, doc_rel, 1,
                f"category `{c}` documented but absent from "
                f"obs/events.py", key=f"phantom:{c}"))
        if not ctx.partial:
            # "No emitter anywhere" needs the whole surface — a
            # path-scoped run must not report every category dead.
            for c in sorted(code - set(used)):
                out.append(Finding(
                    self.id, doc_rel, 1,
                    f"category `{c}` has no emitter call site (dead doc "
                    f"row)", key=f"unemitted:{c}"))
        return out
