"""action-catalog: fleet/controller.py ACTIONS ↔ docs/autoscaler.md.

The controller's action vocabulary is closed, like the fault points,
event categories, metrics and alert rules before it: every declared
action must appear in docs/autoscaler.md's '## Action catalog' table
and vice versa — an actuation an operator cannot look up in the
runbook is exactly the kind of surprise a self-healing loop must
never produce. Also lints the declarations themselves: outcomes come
from the controller's closed OUTCOMES set (and always include the
``requested``/journaled lifecycle root plus at least one terminal),
and triggers name real alert rules (obs/alerts.py RULES) or one of
the policy sentinels.
"""

from __future__ import annotations

import os
import re

from tools.analyze.core import AnalysisPass, Context, Finding, register

_ROW = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|")
DOC_REL = os.path.join("docs", "autoscaler.md")
SECTION = "## action catalog"
CODE_REL = "pytorch_distributed_train_tpu/fleet/controller.py"
TERMINALS = {"effective", "failed", "rolled_back", "skipped"}


def documented_actions(doc_path: str) -> set[str]:
    from tools.analyze.core import doc_table_names

    return doc_table_names(doc_path, SECTION, _ROW)


def declared_actions() -> dict:
    from pytorch_distributed_train_tpu.fleet.controller import ACTIONS

    return dict(ACTIONS)


@register
class ActionCatalogPass(AnalysisPass):
    id = "action-catalog"
    description = ("fleet-controller actions: fleet/controller.py "
                   "ACTIONS ↔ docs/autoscaler.md '## Action catalog', "
                   "both ways, plus closed-outcome/trigger lint")
    include = (CODE_REL,)

    def run(self, ctx: Context) -> list[Finding]:
        from pytorch_distributed_train_tpu.fleet.controller import (
            OUTCOMES,
            POLICY_TRIGGERS,
        )
        from pytorch_distributed_train_tpu.obs.alerts import RULES

        doc_path = ctx.doc_path(DOC_REL)
        doc_rel = DOC_REL.replace(os.sep, "/")
        code = declared_actions()
        try:
            doc = documented_actions(doc_path)
        except OSError:
            return [Finding(self.id, doc_rel, 1,
                            "docs/autoscaler.md is unreadable",
                            key="doc-missing")]
        if not doc:
            return [Finding(self.id, doc_rel, 1,
                            "no rows under '## Action catalog' — was "
                            "the table renamed?", key="catalog-empty")]
        out: list[Finding] = []
        valid_triggers = set(RULES) | set(POLICY_TRIGGERS)
        for name, spec in sorted(code.items()):
            bad = sorted(set(spec.outcomes) - set(OUTCOMES))
            if bad:
                out.append(Finding(
                    self.id, CODE_REL, 1,
                    f"action `{name}` declares outcomes {bad} outside "
                    f"the closed set {sorted(OUTCOMES)}",
                    key=f"outcome:{name}"))
            if "requested" not in spec.outcomes or not (
                    set(spec.outcomes) & TERMINALS):
                out.append(Finding(
                    self.id, CODE_REL, 1,
                    f"action `{name}` must declare the `requested` "
                    f"lifecycle root and at least one terminal outcome "
                    f"({sorted(TERMINALS)})", key=f"lifecycle:{name}"))
            for t in sorted(set(spec.triggers) - valid_triggers):
                out.append(Finding(
                    self.id, CODE_REL, 1,
                    f"action `{name}` trigger `{t}` names neither an "
                    f"alert rule (obs/alerts.py RULES) nor a policy "
                    f"sentinel {sorted(POLICY_TRIGGERS)}",
                    key=f"trigger:{name}:{t}"))
        for name in sorted(set(code) - doc):
            out.append(Finding(
                self.id, doc_rel, 1,
                f"controller action `{name}` declared in "
                f"fleet/controller.py but missing from the doc's "
                f"action catalog", key=f"undocumented:{name}"))
        for name in sorted(doc - set(code)):
            out.append(Finding(
                self.id, doc_rel, 1,
                f"controller action `{name}` documented but absent "
                f"from fleet/controller.py ACTIONS",
                key=f"phantom:{name}"))
        return out
