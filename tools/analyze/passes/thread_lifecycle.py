"""thread-lifecycle: every spawned thread must be daemon or joined.

A non-daemon ``threading.Thread`` nobody joins outlives its owner: it
blocks interpreter shutdown (the silent-hang twin of the tier-1 suite's
wedges), and its writes race teardown. The rule: every
``threading.Thread(target=...)`` is either ``daemon=True`` or provably
joined — stored somewhere (``self.X`` / a local / a list of threads)
that a reachable ``.join()`` call drains. The companion hazard is the
inverse: a ``.join()`` (or any thread-wait) executed *while a lock is
held* turns "slow worker" into "everyone blocked behind the lock" — the
runtime sanitizer (utils/syncdbg.py) times the same pattern live.

Conservative by design:

- ``daemon=<non-constant>`` is accepted (can't prove it false), as is a
  post-construction ``<name>.daemon = True`` on the same stored name;
- a join anywhere in the owning class (for ``self.X``) or function (for
  locals) counts — we don't prove the shutdown path runs, only that one
  exists;
- list-of-threads patterns count when the list's elements are joined in
  a loop over the list.
"""

from __future__ import annotations

import ast

from tools.analyze.core import (AnalysisPass, Context, Finding,
                                class_lock_attrs, dotted,
                                module_lock_names, register,
                                walk_no_nested_defs, withitem_lock_name)

SCOPE = (
    "pytorch_distributed_train_tpu/serving_plane/",
    "pytorch_distributed_train_tpu/ckpt/",
    "pytorch_distributed_train_tpu/obs/",
    "pytorch_distributed_train_tpu/faults/",
    "pytorch_distributed_train_tpu/elastic.py",
    "pytorch_distributed_train_tpu/data/workers.py",
    "pytorch_distributed_train_tpu/fleet/",
    "pytorch_distributed_train_tpu/online/",
    "tools/serve_http.py",
    "tools/serve_router.py",
    "tools/fleet_controller.py",
    "tools/online_loop.py",
)


def _is_thread_ctor(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and (dotted(node.func) or "").endswith("Thread"))


def _daemon_status(call: ast.Call) -> str:
    """'daemon' | 'non_daemon' | 'unknown' from the constructor kwargs."""
    for kw in call.keywords:
        if kw.arg == "daemon":
            if isinstance(kw.value, ast.Constant):
                return "daemon" if kw.value.value else "non_daemon"
            return "unknown"  # dynamic: can't prove it false
    return "non_daemon"  # threading's default


def _joined_names(tree: ast.AST) -> set[str]:
    """Names X with an ``X.join(...)`` / ``self.X.join(...)`` call
    anywhere under ``tree`` (nested defs included: shutdown paths are
    often closures/handlers)."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"):
            continue
        recv = node.func.value
        if isinstance(recv, ast.Name):
            out.add(recv.id)
        elif (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"):
            out.add(f"self.{recv.attr}")
    return out


def _loop_joined_lists(tree: ast.AST) -> set[str]:
    """Names L for ``for t in L: ... t.join(...)`` patterns (self.L
    included) — the joined-thread-list idiom."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.For):
            continue
        if not isinstance(node.target, ast.Name):
            continue
        it = node.iter
        name = None
        if isinstance(it, ast.Name):
            name = it.id
        elif (isinstance(it, ast.Attribute)
                and isinstance(it.value, ast.Name)
                and it.value.id == "self"):
            name = f"self.{it.attr}"
        if name is None:
            continue
        tvar = node.target.id
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "join"
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == tvar):
                out.add(name)
                break
    return out


def _daemon_assigned_names(tree: ast.AST) -> set[str]:
    """Names X with a ``X.daemon = True`` / ``self.X.daemon = True``
    assignment after construction."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and node.value.value is True):
            continue
        for tgt in node.targets:
            if not (isinstance(tgt, ast.Attribute)
                    and tgt.attr == "daemon"):
                continue
            recv = tgt.value
            if isinstance(recv, ast.Name):
                out.add(recv.id)
            elif (isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"):
                out.add(f"self.{recv.attr}")
    return out


def _storage_name(ctor: ast.Call, parents: dict) -> str | None:
    """Where the Thread object lands: 'x' / 'self.x' for a direct
    assignment, the comprehension's / appended-to list's name, else
    None (constructed and dropped, e.g. ``Thread(...).start()``)."""
    node = ctor
    while True:
        parent = parents.get(id(node))
        if parent is None:
            return None
        if isinstance(parent, ast.Assign):
            for tgt in parent.targets:
                if isinstance(tgt, ast.Name):
                    return tgt.id
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    return f"self.{tgt.attr}"
            return None
        if isinstance(parent, (ast.ListComp, ast.List, ast.Tuple)):
            node = parent
            continue
        if isinstance(parent, ast.Call):
            # L.append(Thread(...)) — storage is L
            f = parent.func
            if (isinstance(f, ast.Attribute) and f.attr == "append"
                    and node in parent.args):
                if isinstance(f.value, ast.Name):
                    return f.value.id
                if (isinstance(f.value, ast.Attribute)
                        and isinstance(f.value.value, ast.Name)
                        and f.value.value.id == "self"):
                    return f"self.{f.value.attr}"
            return None
        if isinstance(parent, (ast.Expr, ast.Attribute)):
            # Thread(...).start() or a bare expression: keep climbing
            # one level to see if anything captures it (it won't).
            node = parent
            continue
        return None


def _parent_map(root: ast.AST) -> dict:
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


@register
class ThreadLifecyclePass(AnalysisPass):
    id = "thread-lifecycle"
    description = ("threads must be daemon or provably joined; no "
                   "blocking .join() while a lock is held")
    include = SCOPE

    def run(self, ctx: Context) -> list[Finding]:
        out: list[Finding] = []
        for sf in self.files(ctx):
            out.extend(self._check_file(sf))
        return out

    def _check_file(self, sf) -> list[Finding]:
        out: list[Finding] = []
        global_locks = module_lock_names(sf.tree)
        # scope attribution: every node belongs to its INNERMOST
        # enclosing function (ast.walk is breadth-first, parents before
        # children, so later overwrites win), and each function to its
        # innermost class — a ctor in a closure is checked against the
        # closure, once, not against every enclosing def too.
        funcs = [n for n in ast.walk(sf.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        innermost: dict[int, ast.AST] = {}
        for func in funcs:
            for sub in ast.walk(func):
                innermost[id(sub)] = func
        class_of_func: dict[int, ast.ClassDef] = {}
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        class_of_func.setdefault(id(sub), node)

        # module/class-body scope first: a thread spawned at import time
        # (not inside any def) is bound by the same rule — its joins can
        # live anywhere in the module (atexit hooks, shutdown helpers)
        mod_ctors = [n for n in ast.walk(sf.tree)
                     if _is_thread_ctor(n) and id(n) not in innermost]
        if mod_ctors:
            parents = _parent_map(sf.tree)
            joined = _joined_names(sf.tree) | _loop_joined_lists(sf.tree)
            daemoned = _daemon_assigned_names(sf.tree)
            for ctor in mod_ctors:
                if _daemon_status(ctor) != "non_daemon":
                    continue
                name = _storage_name(ctor, parents)
                if name is None:
                    out.append(self.finding(
                        sf, ctor,
                        "non-daemon thread is constructed and dropped "
                        "at module scope — nothing can ever join it; "
                        "pass daemon=True or store and join it on a "
                        "shutdown path"))
                elif name not in joined and name not in daemoned:
                    out.append(self.finding(
                        sf, ctor,
                        f"non-daemon module-scope thread stored in "
                        f"`{name}` is never joined (no `{name}.join(...)`"
                        f" anywhere in the module) — pass daemon=True "
                        f"or join it"))

        for func in funcs:
            cls = class_of_func.get(id(func))
            parents = _parent_map(func)
            ctors = [n for n in ast.walk(func)
                     if _is_thread_ctor(n) and innermost[id(n)] is func]
            if ctors:
                local_joined = _joined_names(func) | _loop_joined_lists(func)
                local_daemoned = _daemon_assigned_names(func)
                if cls is not None:
                    cls_joined = _joined_names(cls) | _loop_joined_lists(cls)
                    cls_daemoned = _daemon_assigned_names(cls)
                else:
                    cls_joined = cls_daemoned = set()
                for ctor in ctors:
                    status = _daemon_status(ctor)
                    if status != "non_daemon":
                        continue
                    name = _storage_name(ctor, parents)
                    if name is None:
                        out.append(self.finding(
                            sf, ctor,
                            "non-daemon thread is constructed and "
                            "dropped — nothing can ever join it; pass "
                            "daemon=True or store and join it on a "
                            "shutdown path"))
                        continue
                    joined = local_joined | (
                        cls_joined if name.startswith("self.") else set())
                    daemoned = local_daemoned | (
                        cls_daemoned if name.startswith("self.") else set())
                    if name in joined or name in daemoned:
                        continue
                    out.append(self.finding(
                        sf, ctor,
                        f"non-daemon thread stored in `{name}` is never "
                        f"joined (no `{name}.join(...)` on any shutdown "
                        f"path) — pass daemon=True or join it"))

            # .join() under a held lock: lexical, same stance as
            # lock-scope but with thread-wait-specific wording, and
            # over THIS pass's scope (which includes obs/).
            self_locks = class_lock_attrs(cls) if cls is not None else set()
            for node in ast.walk(func):
                if not isinstance(node, ast.With) \
                        or innermost[id(node)] is not func:
                    continue
                held = None
                for item in node.items:
                    held = withitem_lock_name(item, self_locks,
                                              global_locks)
                    if held:
                        break
                if not held:
                    continue
                for sub in walk_no_nested_defs(node.body):
                    if not (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "join"):
                        continue
                    # thread-ish receivers only: a bare name or a
                    # self attribute — `", ".join(...)` (Constant) and
                    # `os.path.join(...)` (module attr chain) are
                    # string/path joins, not thread waits
                    recv = sub.func.value
                    threadish = isinstance(recv, ast.Name) or (
                        isinstance(recv, ast.Attribute)
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self")
                    if not threadish:
                        continue
                    out.append(self.finding(
                        sf, sub,
                        f"blocking `.join()` while holding `{held}` "
                        f"— a slow or wedged thread stalls every "
                        f"thread behind this lock; join outside "
                        f"the lock"))
        return out
