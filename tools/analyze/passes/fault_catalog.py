"""fault-catalog: docs/fault_tolerance.md ↔ faults/registry.py POINTS.

The analyzer-plugin port of ``tools/check_fault_points.py`` (now a thin
shim over this module): an operator writes injection schedules from the
doc's catalog table, so a point in code but not the doc — or vice
versa — is exactly the "schedule that silently does nothing" the fault
layer forbids.
"""

from __future__ import annotations

import os
import re

from tools.analyze.core import AnalysisPass, Context, Finding, register

_ROW = re.compile(r"^\|\s*`([a-z_]+\.[a-z_]+)`\s*\|")
DOC_REL = os.path.join("docs", "fault_tolerance.md")
SECTION = "## fault-point catalog"


def documented_points(doc_path: str) -> set[str]:
    """Point names from the first column of the '## Fault-point catalog'
    table (only that section: the grammar examples and recovery matrix
    mention points too, but the catalog is the contract)."""
    from tools.analyze.core import doc_table_names

    return doc_table_names(doc_path, SECTION, _ROW)


def registry_points() -> set[str]:
    from pytorch_distributed_train_tpu.faults.registry import POINTS

    return set(POINTS)


def sync_sets(doc_path: str) -> tuple[set[str], set[str]]:
    """(code, doc) point-name sets — the shim and the pass share this."""
    return registry_points(), documented_points(doc_path)


def _section_line(doc_path: str) -> int:
    try:
        with open(doc_path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                if line.strip().lower() == SECTION:
                    return i
    except OSError:
        pass
    return 1


@register
class FaultCatalogPass(AnalysisPass):
    id = "fault-catalog"
    description = ("fault-point names in docs/fault_tolerance.md's "
                   "catalog ↔ faults/registry.py POINTS, both ways")
    include = ("pytorch_distributed_train_tpu/faults/",)

    def run(self, ctx: Context) -> list[Finding]:
        doc_path = ctx.doc_path(DOC_REL)
        doc_rel = DOC_REL.replace(os.sep, "/")
        try:
            code, doc = sync_sets(doc_path)
        except OSError:
            return [Finding(self.id, doc_rel, 1,
                            "docs/fault_tolerance.md is unreadable",
                            key="doc-missing")]
        if not doc:
            return [Finding(self.id, doc_rel, 1,
                            "no catalog rows under '## Fault-point "
                            "catalog' — was the table renamed?",
                            key="catalog-empty")]
        line = _section_line(doc_path)
        out: list[Finding] = []
        for p in sorted(code - doc):
            out.append(Finding(
                self.id, doc_rel, line,
                f"fault point `{p}` exists in faults/registry.py but is "
                f"missing from the doc catalog", key=f"undocumented:{p}"))
        for p in sorted(doc - code):
            out.append(Finding(
                self.id, doc_rel, line,
                f"fault point `{p}` is documented in the catalog but "
                f"absent from faults/registry.py", key=f"phantom:{p}"))
        return out
