"""Built-in pdtt-analyze passes; importing this package registers them.

To add a pass: drop a module here that subclasses
``tools.analyze.core.AnalysisPass``, decorate it with ``@register``,
and import it below — the runner, ``--only`` selection, baseline and
JSON output all pick it up from the registry. docs/static_analysis.md
documents the contract.
"""

from tools.analyze.passes import (  # noqa: F401
    action_catalog,
    alert_catalog,
    event_catalog,
    fault_catalog,
    jit_purity,
    lock_order,
    lock_scope,
    metric_catalog,
    monotonic_clock,
    raw_store,
    slo_catalog,
    thread_lifecycle,
    thread_shared,
    trace_hygiene,
)
