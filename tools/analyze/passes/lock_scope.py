"""lock-scope: blocking work must not run under a service lock.

PR 7 fixed (by hand, in review) a starvation where the serve scheduler
held the service lock across its busy quantum; this pass makes the rule
mechanical: lexically inside ``with self._lock:`` (for any
``threading.Lock/RLock`` attribute of the class, or a module-global
lock) no call may sleep, talk to the network, fork a process, do file
I/O, or block on another synchronization primitive. Closures defined
under the lock are skipped — they run later, not here.

Scoped to the concurrency planes whose locks sit on request/step/save
hot paths; a lock held across ``time.sleep`` there is a cross-thread
stall of intake, shed, scrape or save.
"""

from __future__ import annotations

import ast
import re

from tools.analyze.core import (AnalysisPass, Context, Finding,
                                class_lock_attrs, dotted,
                                module_lock_names, register,
                                walk_no_nested_defs, withitem_lock_name)

# Exact dotted calls that always block.
BLOCKING_DOTTED = {
    "time.sleep",
    "socket.create_connection",
    "urllib.request.urlopen",
}
# Module prefixes whose calls block (spawn/IO heavy).
BLOCKING_PREFIXES = ("subprocess.", "requests.", "http.client.")
# Builtins that hit the filesystem.
BLOCKING_BUILTINS = {"open"}
# Method names that block on *some* receiver; conservative set — `.get`
# only counts on queue-ish receivers (a store get with timeout_ms is a
# different protocol) and `.wait` is excused on condition variables
# (Condition.wait releases the lock; that's the one correct pattern).
BLOCKING_METHODS = {"wait", "join", "acquire", "recv", "accept",
                    "connect", "communicate", "check_output", "urlopen"}
_QUEUEISH = re.compile(r"(^|_)(q|queue)\d*$")
_CONDISH = re.compile(r"(cond|cv|condition)", re.I)


def _receiver_name(func: ast.Attribute) -> str:
    d = dotted(func.value)
    return (d or "").rsplit(".", 1)[-1]


def _blocking_reason(call: ast.Call) -> str | None:
    func = call.func
    d = dotted(func)
    if d is not None:
        if d in BLOCKING_DOTTED:
            return f"`{d}(...)`"
        for pfx in BLOCKING_PREFIXES:
            if d.startswith(pfx):
                return f"`{d}(...)`"
        if d in BLOCKING_BUILTINS:
            return f"`{d}(...)` (file I/O)"
    if isinstance(func, ast.Attribute):
        recv = _receiver_name(func)
        if func.attr == "get" and _QUEUEISH.search(recv):
            return f"`{recv}.get(...)` (queue get)"
        if func.attr in BLOCKING_METHODS:
            if func.attr == "wait" and _CONDISH.search(recv):
                return None  # Condition.wait releases the lock
            return f"`{recv or '<expr>'}.{func.attr}(...)`"
    # (bare `open(...)` is already caught above: dotted() on an ast.Name
    # returns its id, so it hits the BLOCKING_BUILTINS check.)
    return None


@register
class LockScopePass(AnalysisPass):
    id = "lock-scope"
    description = ("blocking calls (sleep/net/file/subprocess/wait) "
                   "lexically inside `with <lock>:` bodies")
    include = (
        "pytorch_distributed_train_tpu/serving_plane/",
        "pytorch_distributed_train_tpu/ckpt/",
        "pytorch_distributed_train_tpu/sentinel/",
        "pytorch_distributed_train_tpu/elastic.py",
        # shared-memory decode plane (ISSUE 12): its queues sit on the
        # input hot path — no blocking work under any lock here
        "pytorch_distributed_train_tpu/data/workers.py",
        # online weight plane (ISSUE 19): WeightState sits between the
        # swap handler and the serving scheduler — a blocking call
        # under its lock stalls every decode quantum
        "pytorch_distributed_train_tpu/online/",
        "tools/serve_*.py",
    )

    def run(self, ctx: Context) -> list[Finding]:
        out: list[Finding] = []
        for sf in self.files(ctx):
            global_locks = module_lock_names(sf.tree)
            # Map every With node to the lock it takes, per class (for
            # self.X locks) and module-wide (for globals).
            classes = [n for n in ast.walk(sf.tree)
                       if isinstance(n, ast.ClassDef)]
            covered: set[int] = set()
            for cls in classes:
                self_locks = class_lock_attrs(cls)
                for node in ast.walk(cls):
                    if isinstance(node, ast.With):
                        covered.add(id(node))
                        out.extend(self._check_with(
                            sf, node, self_locks, global_locks))
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.With) and id(node) not in covered:
                    out.extend(self._check_with(
                        sf, node, set(), global_locks))
        return out

    def _check_with(self, sf, node: ast.With, self_locks: set[str],
                    global_locks: set[str]) -> list[Finding]:
        held = None
        lock_idx = -1
        for i, item in enumerate(node.items):
            held = withitem_lock_name(item, self_locks, global_locks)
            if held:
                lock_idx = i
                break
        if not held:
            return []
        # Items AFTER the lock item evaluate with the lock already held
        # (`with self._lock, open(p) as f:` smuggles the I/O in), so
        # scan their context expressions along with the body.
        later_items = [n for item in node.items[lock_idx + 1:]
                       for n in ast.walk(item.context_expr)]
        out = []
        for sub in list(walk_no_nested_defs(node.body)) + later_items:
            if not isinstance(sub, ast.Call):
                continue
            reason = _blocking_reason(sub)
            if reason:
                out.append(self.finding(
                    sf, sub,
                    f"blocking call {reason} while holding `{held}` — "
                    f"move the blocking work outside the lock"))
        return out
