"""jit-purity: jitted step functions must stay host-sync-free.

A ``float()``/``.item()``/``np.asarray`` inside a jitted function
forces a device→host transfer at trace time (or a tracer error at
best); ``print``/``time.*`` run once at trace and never again, which is
how "debug" output silently lies; a Python ``if`` on a traced value is
a concretization error waiting for the first shape change. The trainer
hot path depends on steps staying async — one hidden sync serializes
the pipeline.

Jitted functions are found two ways, both lexical and conservative:

- decorated with ``jax.jit``/``pjit``/``shard_map`` (bare or via
  ``partial(jax.jit, ...)``);
- defined in the module and later *wrapped*: ``jax.jit(f, ...)`` /
  ``shard_map(f, ...)`` with ``f`` (or ``partial(f, ...)``) naming the
  local def. A function arriving through a parameter is not resolvable
  and is skipped — no guessing.

The traced-branch heuristic only fires on an ``if``/``while`` test that
references a *parameter* of the jitted function directly, excluding
``.shape``/``.ndim``/``.dtype``/``.size``/``len(...)`` (static at
trace time) — config flags closed over from outside never trip it.
"""

from __future__ import annotations

import ast

from tools.analyze.core import (AnalysisPass, Context, Finding, dotted,
                                register)

JIT_WRAPPERS = {"jit", "pjit", "shard_map"}
STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}
NP_HOST_FUNCS = {"asarray", "array", "save", "load", "frombuffer"}
NP_MODULES = {"np", "numpy", "onp"}


def _is_jit_dotted(d: str | None) -> bool:
    return d is not None and (d in JIT_WRAPPERS
                              or d.split(".")[-1] in JIT_WRAPPERS)


def _wrapped_name(call: ast.Call) -> str | None:
    """f in jax.jit(f, ...) / shard_map(partial(f, ...), ...)."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Call) and \
            (dotted(arg.func) or "").split(".")[-1] == "partial" and arg.args:
        arg = arg.args[0]
    if isinstance(arg, ast.Name):
        return arg.id
    return None


def _jitted_functions(tree: ast.AST) -> list[ast.FunctionDef]:
    by_name: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    jitted: dict[int, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                d = dotted(target)
                if _is_jit_dotted(d):
                    jitted[id(node)] = node
                elif (isinstance(dec, ast.Call)
                      and (dotted(dec.func) or "").endswith("partial")
                      and dec.args and _is_jit_dotted(dotted(dec.args[0]))):
                    jitted[id(node)] = node
        if isinstance(node, ast.Call) and _is_jit_dotted(dotted(node.func)):
            name = _wrapped_name(node)
            if name:
                for fn in by_name.get(name, []):
                    jitted[id(fn)] = fn
    return list(jitted.values())


def _in_debug_call(parents: list[ast.AST]) -> bool:
    for p in parents:
        if isinstance(p, ast.Call):
            d = dotted(p.func) or ""
            if d.startswith("jax.debug.") or d.endswith("io_callback") \
                    or d.endswith("pure_callback"):
                return True
    return False


def _param_rooted(node: ast.AST, params: set[str]) -> bool:
    """Does `node` reference a parameter as a (possibly attributed)
    value, excluding static metadata like .shape/.ndim and len()?"""
    if isinstance(node, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
        return False  # `x is (not) None`: pytree structure is static
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in STATIC_ATTRS:
            return False  # treat the whole test as static metadata
        if isinstance(sub, ast.Call):
            d = dotted(sub.func)
            if d == "len" or d == "isinstance":
                return False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in params:
            return True
    return False


@register
class JitPurityPass(AnalysisPass):
    id = "jit-purity"
    description = ("host syncs (float/.item/np.asarray/print/time.*) and "
                   "traced-value branches inside jitted functions")
    include = (
        "pytorch_distributed_train_tpu/steps.py",
        "pytorch_distributed_train_tpu/trainer.py",
        "pytorch_distributed_train_tpu/models/",
        "pytorch_distributed_train_tpu/parallel/",
        # device-side augmentation runs inside the jitted step (ISSUE
        # 12c) — host syncs here would serialize the train pipeline
        "pytorch_distributed_train_tpu/ops/device_augment.py",
        # fused optimizer/block epilogues execute inside the jitted
        # step (ISSUE 14) — same purity contract as steps.py
        "pytorch_distributed_train_tpu/ops/fused_update.py",
        # in-graph model-health stats (ISSUE 20) run inside the jitted
        # step at every step — same purity contract as steps.py
        "pytorch_distributed_train_tpu/ops/model_health.py",
    )

    def run(self, ctx: Context) -> list[Finding]:
        out: list[Finding] = []
        for sf in self.files(ctx):
            for fn in _jitted_functions(sf.tree):
                out.extend(self._check_fn(sf, fn))
        return out

    def _check_fn(self, sf, fn) -> list[Finding]:
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)} - {"self"}
        out: list[Finding] = []
        # Walk with a parent stack so jax.debug.print(...) args are
        # excused (that's the *correct* spelling of print-under-jit).
        stack: list[tuple[ast.AST, list[ast.AST]]] = [
            (n, []) for n in fn.body]
        while stack:
            node, parents = stack.pop()
            for child in ast.iter_child_nodes(node):
                stack.append((child, parents + [node]))
            if isinstance(node, ast.Call) and not _in_debug_call(parents):
                d = dotted(node.func)
                if d == "print":
                    out.append(self.finding(
                        sf, node, f"print() inside jitted `{fn.name}` — "
                        "runs once at trace; use jax.debug.print"))
                elif d == "float" and node.args and not self._static_arg(
                        node.args[0]):
                    out.append(self.finding(
                        sf, node, f"float() on a traced value inside "
                        f"jitted `{fn.name}` forces a host sync"))
                elif d is not None and d.startswith("time."):
                    out.append(self.finding(
                        sf, node, f"{d}() inside jitted `{fn.name}` runs "
                        "at trace time only"))
                elif d is not None and "." in d and \
                        d.split(".")[0] in NP_MODULES and \
                        d.split(".")[-1] in NP_HOST_FUNCS:
                    out.append(self.finding(
                        sf, node, f"{d}() inside jitted `{fn.name}` "
                        "materializes on host — use jnp"))
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "item":
                    out.append(self.finding(
                        sf, node, f".item() inside jitted `{fn.name}` "
                        "forces a host sync"))
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "block_until_ready":
                    out.append(self.finding(
                        sf, node, f".block_until_ready() inside jitted "
                        f"`{fn.name}` is a host sync"))
            elif isinstance(node, (ast.If, ast.While)):
                if _param_rooted(node.test, params):
                    out.append(self.finding(
                        sf, node, f"Python `{type(node).__name__.lower()}`"
                        f" on a traced parameter of jitted `{fn.name}` — "
                        "use jax.lax.cond/select (concretization)",
                        severity="warning"))
        return out

    @staticmethod
    def _static_arg(arg: ast.AST) -> bool:
        """float(1), float(x.shape[0]), float(len(x)) are static."""
        if isinstance(arg, ast.Constant):
            return True
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Attribute) and sub.attr in STATIC_ATTRS:
                return True
            if isinstance(sub, ast.Call) and dotted(sub.func) == "len":
                return True
        return False
