"""alert-catalog: obs/alerts.py RULES ↔ docs/observability.md table.

The fourth catalog the planes grew (after fault points, event
categories and metrics): every declared fleet alert rule must appear
in the doc's '## Alert catalog' table and vice versa — an alert an
operator cannot look up is noise; a documented rule nothing evaluates
is a silent gap. Also lints the declarations themselves: kinds come
from the closed set the engine implements, and every rule names at
least one role.
"""

from __future__ import annotations

import os
import re

from tools.analyze.core import AnalysisPass, Context, Finding, register

_ROW = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|")
DOC_REL = os.path.join("docs", "observability.md")
SECTION = "## alert catalog"
KINDS = {"threshold", "absence", "rate", "anomaly", "burn_rate"}
CODE_REL = "pytorch_distributed_train_tpu/obs/alerts.py"


def documented_rules(doc_path: str) -> set[str]:
    from tools.analyze.core import doc_table_names

    return doc_table_names(doc_path, SECTION, _ROW)


def declared_rules() -> dict:
    from pytorch_distributed_train_tpu.obs.alerts import RULES

    return dict(RULES)


@register
class AlertCatalogPass(AnalysisPass):
    id = "alert-catalog"
    description = ("fleet alert rules: obs/alerts.py RULES ↔ the doc's "
                   "'## Alert catalog' table, both ways, plus "
                   "closed-kind/role lint")
    include = (CODE_REL,)

    def run(self, ctx: Context) -> list[Finding]:
        doc_path = ctx.doc_path(DOC_REL)
        doc_rel = DOC_REL.replace(os.sep, "/")
        code = declared_rules()
        try:
            doc = documented_rules(doc_path)
        except OSError:
            return [Finding(self.id, doc_rel, 1,
                            "docs/observability.md is unreadable",
                            key="doc-missing")]
        if not doc:
            return [Finding(self.id, doc_rel, 1,
                            "no rows under '## Alert catalog' — was the "
                            "table renamed?", key="catalog-empty")]
        out: list[Finding] = []
        for name, rule in sorted(code.items()):
            if rule.kind not in KINDS:
                out.append(Finding(
                    self.id, CODE_REL, 1,
                    f"rule `{name}` has kind {rule.kind!r} outside the "
                    f"closed set {sorted(KINDS)}", key=f"kind:{name}"))
            if not rule.roles:
                out.append(Finding(
                    self.id, CODE_REL, 1,
                    f"rule `{name}` applies to no role — it can never "
                    f"evaluate", key=f"roles:{name}"))
        for name in sorted(set(code) - doc):
            out.append(Finding(
                self.id, doc_rel, 1,
                f"alert rule `{name}` declared in obs/alerts.py but "
                f"missing from the doc's alert catalog",
                key=f"undocumented:{name}"))
        for name in sorted(doc - set(code)):
            out.append(Finding(
                self.id, doc_rel, 1,
                f"alert rule `{name}` documented but absent from "
                f"obs/alerts.py RULES", key=f"phantom:{name}"))
        return out
