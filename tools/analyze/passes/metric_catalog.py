"""metric-catalog: registered metric names ↔ docs/observability.md,
plus a label-cardinality lint.

Every literal name passed to ``registry.counter/gauge/histogram`` must
appear in the doc's '## Metric catalog' table and vice versa — the
third catalog the planes grew (after fault points and event
categories), previously unenforced. Dynamic names (the MetricLogger
mirror gauges like ``train_loss``) are variables at the call site and
are out of scope by construction; the doc table says so.

The cardinality lint rejects label *values* that are unbounded by
construction: identifiers that look like per-request/per-user ids
(uid/request_id/session/trace...), f-strings, and ``str(...)`` calls.
A label value must come from a closed vocabulary or the registry's
per-series storage grows without bound.
"""

from __future__ import annotations

import ast
import os
import re

from tools.analyze.core import (AnalysisPass, Context, Finding, dotted,
                                register)

_ROW = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|")
DOC_REL = os.path.join("docs", "observability.md")
SECTION = "## metric catalog"
METRIC_METHODS = {"counter", "gauge", "histogram"}
UNBOUNDED_ID = re.compile(
    r"(^|_)(uid|user|userid|user_id|request_id|req_id|session|"
    r"session_id|trace_id|token)(_|$)", re.I)


def documented_metrics(doc_path: str) -> set[str]:
    from tools.analyze.core import doc_table_names

    return doc_table_names(doc_path, SECTION, _ROW)


def metric_sites(tree: ast.AST) -> list[tuple[str, ast.Call]]:
    """(name, call) for every literal-named counter/gauge/histogram
    registration."""
    out: list[tuple[str, ast.Call]] = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in METRIC_METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.append((node.args[0].value, node))
    return out


def _unbounded_label_value(value: ast.AST) -> str | None:
    """A human-readable reason when the label value is unbounded."""
    if isinstance(value, ast.JoinedStr):
        return "f-string label value"
    if isinstance(value, ast.Call) and dotted(value.func) == "str":
        return "str(...) label value"
    d = dotted(value)
    if d is not None and UNBOUNDED_ID.search(d.rsplit(".", 1)[-1]):
        return f"identifier `{d}` looks like a per-request/user id"
    return None


@register
class MetricCatalogPass(AnalysisPass):
    id = "metric-catalog"
    description = ("registry.counter/gauge/histogram names ↔ the doc's "
                   "metric catalog, plus unbounded-label-value lint")
    include = ("pytorch_distributed_train_tpu/", "tools/",
               "train.py", "tpurun.py", "bench.py")

    def run(self, ctx: Context) -> list[Finding]:
        doc_path = ctx.doc_path(DOC_REL)
        doc_rel = DOC_REL.replace(os.sep, "/")
        try:
            doc = documented_metrics(doc_path)
        except OSError:
            return [Finding(self.id, doc_rel, 1,
                            "docs/observability.md is unreadable",
                            key="doc-missing")]
        if not doc:
            return [Finding(self.id, doc_rel, 1,
                            "no rows under '## Metric catalog' — was the "
                            "table renamed?", key="catalog-empty")]
        out: list[Finding] = []
        seen: dict[str, tuple[str, int]] = {}
        for sf in self.files(ctx):
            if sf.path.startswith("tools/analyze/"):
                continue  # the linter's own sources name metrics in text
            for name, call in metric_sites(sf.tree):
                seen.setdefault(name, (sf.path, call.lineno))
                if name not in doc:
                    out.append(Finding(
                        self.id, sf.path, call.lineno,
                        f"metric `{name}` is registered here but missing "
                        f"from the doc's metric catalog",
                        key=f"undocumented:{name}"))
                # labels= is the registry's SECOND positional parameter
                # (counter(name, labels=None, help="")) — lint both
                # spellings.
                label_dicts = [kw.value for kw in call.keywords
                               if kw.arg == "labels"
                               and isinstance(kw.value, ast.Dict)]
                if len(call.args) >= 2 and isinstance(call.args[1],
                                                      ast.Dict):
                    label_dicts.append(call.args[1])
                for ld in label_dicts:
                    for k, v in zip(ld.keys, ld.values):
                        reason = _unbounded_label_value(v)
                        if reason:
                            label = (k.value if isinstance(
                                k, ast.Constant) else "?")
                            out.append(Finding(
                                self.id, sf.path, call.lineno,
                                f"unbounded label `{label}` on "
                                f"`{name}`: {reason} — label values "
                                f"must be a closed vocabulary",
                                key=f"label:{name}:{label}"))
        if not ctx.partial:
            # "No registration site anywhere" needs the whole surface —
            # a path-scoped run must not report every metric phantom.
            for name in sorted(doc - set(seen)):
                out.append(Finding(
                    self.id, doc_rel, 1,
                    f"metric `{name}` is documented in the catalog but "
                    f"has no literal registration site in code",
                    key=f"phantom:{name}"))
        return out
