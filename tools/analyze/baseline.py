"""Baseline suppressions: pre-existing / intentionally-accepted findings.

The baseline file is JSON::

    {"suppressions": [
        {"pass": "monotonic-clock",
         "path": "pytorch_distributed_train_tpu/obs/events.py",
         "key": "rec = {\"ts\": time.time(),",
         "reason": "journal timestamps are wall-clock on purpose"}]}

Identity is the finding fingerprint (pass, path, key) — the key is the
stripped source line, so entries survive line-number drift but expire
the moment the flagged code changes. An entry that matches no current
finding is *stale*: reported (so fixed violations lose their
suppression promptly) and dropped by the next ``--write-baseline``.
Every entry carries a human ``reason`` — a suppression without a why is
just drift with extra steps.
"""

from __future__ import annotations

import json
import os

from tools.analyze.core import Finding

DEFAULT_BASELINE = os.path.join("tools", "analyze", "baseline.json")


class Baseline:
    def __init__(self, entries: list[dict] | None = None,
                 path: str | None = None):
        self.path = path
        self.entries = list(entries or [])

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        entries = data.get("suppressions", [])
        for e in entries:
            if not {"pass", "path", "key"} <= set(e):
                raise ValueError(
                    f"baseline entry missing pass/path/key: {e!r}")
        return cls(entries, path=path)

    def apply(self, findings: list[Finding]
              ) -> tuple[list[Finding], list[Finding], list[dict]]:
        """Split into (unsuppressed, suppressed, stale_entries)."""
        by_fp: dict[tuple, dict] = {
            (e["pass"], e["path"], e["key"]): e for e in self.entries}
        used: set[tuple] = set()
        unsuppressed: list[Finding] = []
        suppressed: list[Finding] = []
        for f in findings:
            if f.fingerprint in by_fp:
                used.add(f.fingerprint)
                suppressed.append(f)
            else:
                unsuppressed.append(f)
        stale = [e for fp, e in by_fp.items() if fp not in used]
        return unsuppressed, suppressed, stale

    @staticmethod
    def write(path: str, findings: list[Finding],
              previous: "Baseline | None" = None,
              keep: list[dict] | None = None) -> int:
        """Rewrite ``path`` to suppress exactly ``findings`` plus the
        out-of-scope ``keep`` entries, carrying reasons forward from
        ``previous`` where fingerprints still match (expiry: stale
        in-scope entries simply aren't rewritten). ``keep`` is how a
        scoped run (``--only``/explicit paths) avoids silently deleting
        suppressions it never re-evaluated."""
        old_reasons: dict[tuple, str] = {}
        if previous is not None:
            for e in previous.entries:
                old_reasons[(e["pass"], e["path"], e["key"])] = \
                    e.get("reason", "")
        entries = []
        seen: set[tuple] = set()
        for e in sorted(keep or [],
                        key=lambda e: (e["pass"], e["path"], e["key"])):
            fp = (e["pass"], e["path"], e["key"])
            if fp not in seen:
                seen.add(fp)
                entries.append(dict(e))
        for f in sorted(findings, key=lambda f: (f.pass_id, f.path, f.key)):
            if f.fingerprint in seen:
                continue
            seen.add(f.fingerprint)
            entries.append({
                "pass": f.pass_id, "path": f.path, "key": f.key,
                "reason": old_reasons.get(f.fingerprint,
                                          "TODO: justify or fix"),
            })
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"suppressions": entries}, f, indent=2,
                      ensure_ascii=False)
            f.write("\n")
        return len(entries)
