"""pdtt-analyze: pluggable AST-based correctness linter for this repo's
concurrency, clock, tracing and contract invariants.

Run ``python -m tools.analyze`` from the repo root; see
docs/static_analysis.md for the pass catalog and baseline workflow.
"""

from tools.analyze.baseline import DEFAULT_BASELINE, Baseline  # noqa: F401
from tools.analyze.core import (AnalysisPass, Context,  # noqa: F401
                                Finding, REGISTRY, all_passes,
                                build_context, register)
