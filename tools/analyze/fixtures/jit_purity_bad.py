"""Seeded jit-purity violations: host syncs inside jitted functions."""
import time

import jax
import numpy as np


@jax.jit
def decorated_step(state, batch):
    print("loss:", state)                 # VIOLATION: trace-time print
    lr = float(state.lr)                  # VIOLATION: host sync
    t0 = time.time()                      # VIOLATION: trace-time clock
    host = np.asarray(batch)              # VIOLATION: host materialization
    s = state.loss.item()                 # VIOLATION: host sync
    if batch:                             # VIOLATION: traced-value branch
        s = s + 1
    return lr, t0, host, s


def wrapped_step(state, batch):
    print("wrapped")                      # VIOLATION: found via jax.jit(f)
    return state


jitted = jax.jit(wrapped_step, donate_argnums=(0,))
