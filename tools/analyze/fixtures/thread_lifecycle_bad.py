"""Seeded thread-lifecycle violations: a stored non-daemon thread
nobody joins, a constructed-and-dropped non-daemon thread, and a
``.join()`` executed while a lock is held."""

import threading

# module-scope spawn: same rule, no enclosing def to hide in
_POLLER = threading.Thread(target=print)
_POLLER.start()


class Spawner:
    def __init__(self):
        self._lock = threading.Lock()
        self._worker = threading.Thread(target=self._run)  # never joined
        self._worker.start()

    def _run(self):
        pass

    def fire_and_forget(self):
        threading.Thread(target=self._run).start()  # dropped: unjoinable

    def stop_wrong(self):
        other = threading.Thread(target=self._run)
        other.start()
        with self._lock:
            other.join()  # joined, but while holding the class lock
