"""Seeded monotonic-clock violations: wall time in deadline math."""
import time


def drain(grace_s: float):
    deadline = time.time() + grace_s          # VIOLATION: wall deadline
    while time.time() < deadline:             # VIOLATION: wall compare
        pass


def backoff(last_attempt, retry_after_s):
    elapsed = time.time() - last_attempt
    if elapsed > retry_after_s:               # VIOLATION: tainted compare
        return True
    return False


def remaining(store, deadline):
    left = deadline - time.time()
    store.get("key", timeout_ms=int(left * 1000))   # VIOLATION: timeout kw


class Prober:
    def __init__(self):
        self._last_ok = time.time()

    def stale(self, timeout_s):
        return time.time() - self._last_ok > timeout_s  # VIOLATION: attr taint
