"""Seeded raw-store violations (tools/analyze/passes/raw_store.py)."""
import time

from pytorch_distributed_train_tpu.elastic import worker_store
from pytorch_distributed_train_tpu.native.store import StoreClient


def poll_once():
    store = worker_store()
    return store.get("fleet/epoch")  # finding: raw worker_store handle


def publish(addr):
    client = StoreClient("127.0.0.1", 29400)
    idx = client.add("replicas/count", 1)  # finding: raw StoreClient
    client.set(f"replicas/{idx}", addr.encode())  # finding
    return idx


def inline_chain():
    return StoreClient("127.0.0.1", 29400).get("k")  # finding: no binding


class BeatLoop:
    def __init__(self):
        self._store = StoreClient("127.0.0.1", 29400)

    def tick(self, step):
        # finding: attr tainted class-wide from __init__
        self._store.set("beat", str(step).encode())
        time.sleep(0.1)
