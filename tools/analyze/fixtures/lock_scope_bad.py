"""Seeded lock-scope violations: blocking work under a held lock."""
import queue
import subprocess
import threading
import time

_LOCK = threading.Lock()


class Service:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._done = threading.Event()

    def quantum(self):
        with self._lock:
            time.sleep(0.1)              # VIOLATION: sleep under lock
            item = self._q.get()         # VIOLATION: queue get under lock
            self._done.wait(1.0)         # VIOLATION: event wait under lock
            subprocess.run(["true"])     # VIOLATION: subprocess under lock
            with open("/tmp/x") as f:    # VIOLATION: file I/O under lock
                f.read()
        return item


def module_level():
    with _LOCK:
        time.sleep(0.5)                  # VIOLATION: global lock held


def smuggled_in_withitem(svc):
    with svc._lock, open("/tmp/y") as f:  # noqa — parse-only fixture
        return f.name


class Smuggler:
    def __init__(self):
        self._lock = threading.Lock()

    def read(self):
        # VIOLATION: the second withitem evaluates with the lock held
        with self._lock, open("/tmp/y") as f:
            return f.name
