"""Seeded trace-hygiene violations (tools/analyze/passes/trace_hygiene).

Lines matter to the test: manual __enter__/__exit__ on span context
managers, a discarded span cm, and fresh trace-id minting where an
inbound context exists.
"""

from pytorch_distributed_train_tpu.obs import tracing
from pytorch_distributed_train_tpu.obs.spans import span


def manual_begin_end(rec):
    cm = rec.span("work")
    cm.__enter__()          # finding: manual begin
    do_work()
    cm.__exit__(None, None, None)   # finding: manual end


def direct_enter():
    span("request").__enter__()     # finding: manual begin, no exit


def discarded():
    span("quantum")         # finding: cm created and discarded


def handler(headers):
    ctx = tracing.start_trace()          # finding: mint over inbound
    sid = tracing.new_trace_id()         # finding: mint over inbound
    return ctx, sid


def do_work():
    pass
