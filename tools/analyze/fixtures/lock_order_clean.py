"""Near-miss patterns the lock-order pass must NOT flag: consistent
ordering (edges, no cycle), re-entry of the same lock, closures
defined under a lock, and an injected collaborator used one-way."""

import threading

_MOD_LOCK = threading.Lock()


class Inner:
    def __init__(self):
        self._lock = threading.Lock()

    def poke(self):
        with self._lock:
            pass


class Outer:
    """Always module -> outer -> inner: a chain, never a cycle."""

    def __init__(self, inner=None):
        self._lock = threading.Lock()
        self.inner = inner if inner is not None else Inner()

    def fwd(self):
        with self._lock:
            self.inner.poke()

    def fwd_top(self):
        with _MOD_LOCK:
            self.fwd()

    def reenter(self):
        with self._lock:
            self._again()

    def _again(self):
        # same lock through a call: re-entry/self-edge, not a cycle
        with self._lock:
            pass

    def deferred(self):
        with self._lock:
            def later():
                # closure body runs on another thread, later — its
                # acquisitions are not edges from the enclosing hold
                with _MOD_LOCK:
                    self.inner.poke()
            return later
