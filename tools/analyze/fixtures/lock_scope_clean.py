"""Clean lock-scope patterns the pass must NOT flag."""
import threading
import time


class Service:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._stop = threading.Event()

    def quantum(self):
        with self._lock:
            pending = list(range(3))     # pure compute under lock: fine
        time.sleep(0.1)                  # blocking OUTSIDE the lock
        with self._lock:
            def later():
                time.sleep(1.0)          # closure body: runs later
            self._cb = later
        return pending

    def waiter(self):
        with self._cond:
            self._cond.wait(1.0)         # Condition.wait releases the lock

    def other(self):
        with self._stop:                 # not a known lock attr
            pass
