"""Near-miss patterns the thread-lifecycle pass must NOT flag."""

import threading

# module-scope spawns are fine when daemon or joined somewhere
_BG = threading.Thread(target=print, daemon=True)
_SVC = threading.Thread(target=print)


def _shutdown():
    _SVC.join(timeout=1.0)


class Clean:
    def __init__(self):
        self._lock = threading.Lock()
        self._bg = threading.Thread(target=self._run, daemon=True)
        self._svc = threading.Thread(target=self._run)  # joined in stop()

    def _run(self):
        pass

    def stop(self):
        self._svc.join(timeout=2.0)

    def fan_out(self):
        threads = [threading.Thread(target=self._run) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def dynamic_daemon(self, flag):
        # daemon=<non-constant>: can't prove it false — accepted
        t = threading.Thread(target=self._run, daemon=flag)
        t.start()

    def late_daemon(self):
        t = threading.Thread(target=self._run)
        t.daemon = True
        t.start()

    def join_outside(self):
        t = threading.Thread(target=self._run)
        t.start()
        with self._lock:
            pass
        t.join()
