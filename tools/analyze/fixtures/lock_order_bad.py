"""Seeded lock-order violations for the lock-order pass tests.

``Pool`` takes A then B on the submit path, but B then (via a helper)
A on the reclaim path — the classic AB/BA cycle, closed only
inter-procedurally. ``Mixer`` closes a second cycle through a module
lock and a cross-class call.
"""

import threading

_MOD_LOCK = threading.Lock()


class Pool:
    def __init__(self):
        self._slots = threading.Lock()
        self._stats = threading.Lock()

    def submit(self):
        with self._slots:
            with self._stats:
                pass

    def reclaim(self):
        with self._stats:
            self._count()

    def _count(self):
        with self._slots:
            pass


class Mixer:
    def __init__(self):
        self._lock = threading.Lock()
        self.pool = Pool()

    def tick(self):
        with _MOD_LOCK:
            with self._lock:
                pass

    def tock(self):
        with self._lock:
            self.grab()

    def grab(self):
        with _MOD_LOCK:
            pass
