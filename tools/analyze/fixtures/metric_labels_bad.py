"""Seeded metric-catalog violations: undocumented name + unbounded labels."""


def handler(registry, request_id, outcome):
    # VIOLATION: name not in any doc catalog (when run against a doc
    # without this row) + unbounded per-request id label value.
    registry.counter("fixture_requests_total",
                     labels={"rid": request_id}).inc()
    # VIOLATION: f-string label value is unbounded by construction.
    registry.counter("fixture_errors_total",
                     labels={"who": f"user-{outcome}"}).inc()
    # VIOLATION: str(...) label value.
    registry.gauge("fixture_depth",
                   labels={"shard": str(outcome)}).set(1)
    # VIOLATION: labels passed POSITIONALLY (the registry's second
    # parameter) must be linted the same as labels=.
    registry.counter("fixture_requests_total",
                     {"uid": request_id}).inc()
