"""Clean jit patterns the purity pass must NOT flag."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def good_step(state, batch):
    jax.debug.print("loss {}", state)     # the correct print-under-jit
    if batch.ndim == 3:                   # static shape metadata: fine
        batch = batch.reshape(len(batch), -1)
    if state.dynamic_scale is not None:   # pytree structure: static
        batch = batch * 2
    return jnp.sum(batch)


def helper_not_jitted(batch):
    # Never jitted (only referenced by name, never wrapped): host work
    # is allowed here.
    print("host side")
    return np.asarray(batch)


def outer(config):
    flag = config.use_extra

    @jax.jit
    def inner(x):
        if flag:                          # closure var, not a param: fine
            x = x + 1
        return x

    return inner
