"""Clean store usage the raw-store pass must NOT flag."""
from pytorch_distributed_train_tpu import store_plane


def poll_once():
    # the resilient wrapper IS the sanctioned handle
    store = store_plane.resilient_worker_store(name="clean")
    if store is None:
        return None
    return store.get("fleet/epoch")


def drain(store):
    # parameter-taking helpers inherit the CALLER's handle (which is the
    # wrapper at production call sites) — not tainted
    store.set("drained", b"1")
    return store.add("drain/count", 1)


class CachedReader:
    def __init__(self, factory):
        self._store = store_plane.ResilientStore(factory, name="reader")

    def read(self):
        return self._store.get("k", timeout_ms=200)
