"""Clean clock patterns the monotonic pass must NOT flag."""
import json
import time


def drain(grace_s: float):
    deadline = time.monotonic() + grace_s     # monotonic: correct
    while time.monotonic() < deadline:
        pass


def journal(step: int):
    # Wall-clock TIMESTAMPS are correct — humans and cross-host merges
    # read them; they feed no arithmetic.
    return json.dumps({"step": step, "ts": time.time()})


def record_wall_duration(t0):
    # Elapsed-for-reporting: subtraction lands in a record, not a
    # comparison — journaling, not behavior.
    return {"wall_s": round(time.time() - t0, 3)}


def existence_check(self_t0=None):
    started = time.time() if self_t0 is None else self_t0
    if started is None:                       # null check: not duration math
        return False
    return True
