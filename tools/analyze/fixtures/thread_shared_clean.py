"""Clean cross-thread patterns the thread-shared pass must NOT flag."""
import queue
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._stop = threading.Event()
        self.progress = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        for i in range(10):
            with self._lock:
                self.progress = i     # guarded write
            self._q.put(i)            # queue: its methods ARE the sync

    def status(self):
        with self._lock:
            return self.progress      # guarded read

    def stop(self):
        self._stop.set()              # Event attr: excluded primitive


class NoThreads:
    def __init__(self):
        self.value = 0

    def bump(self):
        self.value += 1               # no spawned thread: out of scope
