"""Clean trace-hygiene patterns: with-managed spans, inbound contexts
continued instead of minted."""

from pytorch_distributed_train_tpu.obs import tracing
from pytorch_distributed_train_tpu.obs.spans import span


def with_managed(rec, step):
    with span("http.completions", path="/v1/completions"):
        with rec.span("checkpoint.save", step=step):
            do_work()


def handler(headers):
    # the sanctioned door: honor inbound, mint only when none exists
    ctx = tracing.continue_or_start(headers.get("traceparent"))
    with tracing.activate(ctx):
        with span("router.request"):
            do_work()
    tracing.get_tracer().finish(ctx.trace_id, dur_s=0.1)


def explicit_record(rec, t0):
    # explicit-time recording is not a context manager at all
    rec.record("serve.decode", t0, 0.01, tokens=3)


def do_work():
    pass
