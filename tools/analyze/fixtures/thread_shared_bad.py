"""Seeded thread-shared-state violation: unlocked cross-thread write."""
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.result = None
        self.progress = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        for i in range(10):
            self.progress = i         # VIOLATION: unlocked thread write
        self._finish()

    def _finish(self):
        self.result = "done"          # VIOLATION: transitive thread write

    def status(self):
        return self.progress, self.result   # unlocked main-thread read
