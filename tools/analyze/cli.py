"""pdtt-analyze runner: ``python -m tools.analyze``.

Exit codes: 0 = no unsuppressed findings; 1 = findings; 2 = usage
error (unknown pass, unreadable baseline). Stale baseline entries are
reported but don't fail the run — a fixed violation keeping its
suppression one run too long is safe; the next ``--write-baseline``
drops it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.analyze import baseline as baseline_lib
from tools.analyze import core


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="AST-based correctness linter for the repo's "
                    "concurrency/clock/tracing/contract invariants")
    p.add_argument("paths", nargs="*",
                   help="repo-relative files to analyze (default: the "
                        "whole production surface)")
    p.add_argument("--only", default=None, metavar="PASS[,PASS...]",
                   help="run only these passes")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline suppressions file (default: "
                        f"{baseline_lib.DEFAULT_BASELINE} when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline to suppress every current "
                        "finding (stale entries expire)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--root", default=None, help=argparse.SUPPRESS)
    p.add_argument("--list-passes", action="store_true",
                   help="list registered passes and exit")
    return p


def main(argv: list[str] | None = None, out=None) -> int:
    out = out if out is not None else sys.stdout
    args = build_parser().parse_args(argv)
    passes = core.all_passes()

    if args.list_passes:
        for pid in sorted(passes):
            print(f"{pid:22s} {passes[pid].description}", file=out)
        return 0

    if args.only:
        wanted = [p.strip() for p in args.only.split(",") if p.strip()]
        unknown = [p for p in wanted if p not in passes]
        if unknown:
            print(f"analyze: unknown pass(es): {', '.join(unknown)} "
                  f"(have: {', '.join(sorted(passes))})", file=sys.stderr)
            return 2
        passes = {pid: passes[pid] for pid in wanted}

    root = os.path.abspath(args.root) if args.root else _repo_root()
    paths = list(args.paths) or None
    if paths:
        missing = [p for p in paths
                   if not os.path.isfile(os.path.join(root, p))]
        if missing:
            # A typo'd CI path must not stay green having analyzed
            # nothing — same class of mistake as an unknown pass.
            print(f"analyze: no such file(s): {', '.join(missing)}",
                  file=sys.stderr)
            return 2
    ctx = core.build_context(root, paths)

    findings: list[core.Finding] = []
    for pid in sorted(passes):
        findings.extend(passes[pid].run(ctx))
    # A file no pass could parse is unenforced, not clean — surface it
    # as a finding so the gate fails (baselinable like any other, with
    # a reason, if someone truly ships unparseable python).
    for sf in ctx.files:
        if sf.tree is None:
            findings.append(core.Finding(
                "parse-error", sf.path, 1,
                "file does not parse — every invariant pass skipped it",
                key="parse-error"))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id, f.key))

    bl = None
    bl_path = args.baseline
    if not args.no_baseline:
        if bl_path is None:
            default = os.path.join(root, baseline_lib.DEFAULT_BASELINE)
            bl_path = default if os.path.exists(default) else None
        if bl_path is not None:
            if not os.path.exists(bl_path) and args.write_baseline:
                bl = None  # --write-baseline creates it below
            else:
                try:
                    bl = baseline_lib.Baseline.load(bl_path)
                except (OSError, ValueError, json.JSONDecodeError) as e:
                    print(f"analyze: cannot read baseline {bl_path}: {e}",
                          file=sys.stderr)
                    return 2

    if args.write_baseline:
        target = bl_path or os.path.join(root, baseline_lib.DEFAULT_BASELINE)
        keep: list[dict] = []
        if bl is not None and (args.only or args.paths):
            # A scoped run only re-evaluated (selected passes ×
            # analyzed files): entries outside that product were not
            # looked at and must survive the rewrite.
            analyzed = {sf.path for sf in ctx.files}
            keep = [e for e in bl.entries
                    if e["pass"] not in passes
                    or e["path"] not in analyzed]
        n = baseline_lib.Baseline.write(target, findings, previous=bl,
                                        keep=keep)
        print(f"analyze: wrote {n} suppression(s) to "
              f"{os.path.relpath(target, root)}", file=out)
        return 0

    if bl is not None:
        unsuppressed, suppressed, stale = bl.apply(findings)
    else:
        unsuppressed, suppressed, stale = findings, [], []

    syntax_errors = [sf.path for sf in ctx.files if sf.tree is None]

    if args.format == "json":
        json.dump({
            "passes": sorted(passes),
            "findings": [f.as_dict() for f in unsuppressed],
            "suppressed": [f.as_dict() for f in suppressed],
            "stale_baseline": stale,
            "syntax_errors": syntax_errors,
            "counts": {"findings": len(unsuppressed),
                       "suppressed": len(suppressed),
                       "stale_baseline": len(stale)},
        }, out, indent=2, ensure_ascii=False)
        out.write("\n")
    else:
        for f in unsuppressed:
            print(f.render(), file=out)
        for e in stale:
            print(f"analyze: stale baseline entry (nothing matches it "
                  f"anymore): {e['pass']} {e['path']} {e['key']!r}"
                  + (f" — {e['reason']}" if e.get("reason") else ""),
                  file=out)
        summary = (f"analyze: {len(unsuppressed)} finding(s), "
                   f"{len(suppressed)} suppressed, {len(stale)} stale "
                   f"baseline entr{'y' if len(stale) == 1 else 'ies'}, "
                   f"{len(passes)} pass(es) over {len(ctx.files)} files")
        print(summary, file=out)

    return 1 if unsuppressed else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
