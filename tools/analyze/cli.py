"""pdtt-analyze runner: ``python -m tools.analyze``.

Exit codes: 0 = no unsuppressed findings; 1 = findings; 2 = usage
error (unknown pass, unreadable baseline). Stale baseline entries are
reported but don't fail the run — a fixed violation keeping its
suppression one run too long is safe; the next ``--write-baseline``
drops it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.analyze import baseline as baseline_lib
from tools.analyze import core


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _changed_paths(root: str) -> list[str] | None:
    """Git-changed .py files (worktree vs HEAD + untracked) that are on
    the analyzed surface; None when git itself fails (not a repo)."""
    import subprocess

    out: set[str] = set()
    for args in (("git", "diff", "--name-only", "HEAD", "--"),
                 ("git", "ls-files", "--others", "--exclude-standard")):
        try:
            r = subprocess.run(args, cwd=root, capture_output=True,
                               text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if r.returncode != 0:
            return None
        out.update(line.strip() for line in r.stdout.splitlines()
                   if line.strip())
    surface = set(core.discover(root))
    return sorted(p for p in out if p in surface)


def _json_payload(passes, unsuppressed, suppressed=(), stale=(),
                  syntax_errors=()) -> dict:
    """The one --format json document shape — shared by the normal run
    and the empty clean-tree --changed path so the two can't drift."""
    return {
        "passes": sorted(passes),
        "findings": [f.as_dict() for f in unsuppressed],
        "suppressed": [f.as_dict() for f in suppressed],
        "stale_baseline": list(stale),
        "syntax_errors": list(syntax_errors),
        "counts": {"findings": len(unsuppressed),
                   "suppressed": len(suppressed),
                   "stale_baseline": len(stale)},
    }


def _sarif(passes, findings: list[core.Finding]) -> dict:
    """SARIF 2.1.0 — one run, one rule per pass, one result per
    unsuppressed finding; CI annotates inline from this."""
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "pdtt-analyze",
                "informationUri": "docs/static_analysis.md",
                "rules": [{"id": pid,
                           "shortDescription": {"text": p.description}}
                          for pid, p in sorted(passes.items())],
            }},
            "results": [{
                "ruleId": f.pass_id,
                "level": "warning" if f.severity == "warning" else "error",
                "message": {"text": f.message},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line)},
                }}],
                "partialFingerprints": {
                    "pdttFingerprint/v1":
                        f"{f.pass_id}|{f.path}|{f.key}"},
            } for f in findings],
        }],
    }


def _compare_runtime(graph_path: str, ctx, out) -> int:
    """Diff the static lock-order graph against a syncdbg runtime
    recording. Exit 1 when the runtime saw edges the static pass is
    blind to — each one is a named pass gap, not a silent blind spot."""
    from tools.analyze.passes import lock_order

    try:
        with open(graph_path, encoding="utf-8") as f:
            data = json.load(f)
        runtime_edges = data["edges"]
    except (OSError, ValueError, KeyError) as e:
        print(f"analyze: cannot read runtime graph {graph_path}: {e}",
              file=sys.stderr)
        return 2
    static = lock_order.build_graph(ctx)
    site_to_node: dict[str, str] = {}
    for node, sites in static.nodes.items():
        for path, line in sites:
            site_to_node[f"{path}:{line}"] = node

    covered = 0
    foreign = 0
    gaps: list[str] = []
    for e in runtime_edges:
        a, b = e.get("from", ""), e.get("to", "")
        pa, pb = a.rsplit(":", 1)[0], b.rsplit(":", 1)[0]
        # a lock born outside the pass's SCOPE (tests, soak drivers,
        # native/) can never have a static node — skipping it is
        # honest; only on-scope sites the pass misses are gaps
        if not (core.path_matches(pa, lock_order.SCOPE)
                and core.path_matches(pb, lock_order.SCOPE)):
            foreign += 1
            continue
        na, nb = site_to_node.get(a), site_to_node.get(b)
        if na is None or nb is None:
            missing = a if na is None else b
            gaps.append(
                f"runtime lock at {missing} is UNKNOWN to lock-order "
                f"(edge {a} -> {b}, thread {e.get('thread')}) — the "
                f"creation pattern is outside the pass's lock model")
            continue
        if (na, nb) in static.edges:
            covered += 1
            continue
        gaps.append(
            f"runtime edge {lock_order._short(na)} -> "
            f"{lock_order._short(nb)} (thread {e.get('thread')}) has no "
            f"static counterpart — the acquisition path is invisible to "
            f"lock-order (dynamic dispatch, callback, or an unresolved "
            f"collaborator); cycles through it would go unreported")
    unobserved = [f"{lock_order._short(a)} -> {lock_order._short(b)}"
                  for (a, b) in sorted(static.edges)
                  if not any(
                      site_to_node.get(e.get("from", "")) == a
                      and site_to_node.get(e.get("to", "")) == b
                      for e in runtime_edges)]

    print(f"compare-runtime: {len(runtime_edges)} runtime edge(s): "
          f"{covered} covered statically, {len(gaps)} pass gap(s), "
          f"{foreign} skipped (locks outside the analyzed surface)",
          file=out)
    for g in gaps:
        print(f"  GAP: {g}", file=out)
    if unobserved:
        print(f"  note: {len(unobserved)} static edge(s) never observed "
              f"at runtime (fine — the recording did not drive those "
              f"paths): {', '.join(unobserved[:6])}"
              + (" ..." if len(unobserved) > 6 else ""), file=out)
    return 1 if gaps else 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="AST-based correctness linter for the repo's "
                    "concurrency/clock/tracing/contract invariants")
    p.add_argument("paths", nargs="*",
                   help="repo-relative files to analyze (default: the "
                        "whole production surface)")
    p.add_argument("--changed", action="store_true",
                   help="analyze only git-changed files (working tree "
                        "vs HEAD, plus untracked) — the pre-commit "
                        "fast path; catalog passes skip their whole-"
                        "surface directions as for any scoped run")
    p.add_argument("--only", default=None, metavar="PASS[,PASS...]",
                   help="run only these passes")
    p.add_argument("--compare-runtime", default=None, metavar="GRAPH",
                   help="diff the static lock-order graph against a "
                        "runtime recording (utils/syncdbg.py "
                        "dump_graph JSON); runtime edges the AST pass "
                        "cannot see become a named pass-gap report "
                        "(exit 1)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline suppressions file (default: "
                        f"{baseline_lib.DEFAULT_BASELINE} when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline to suppress every current "
                        "finding (stale entries expire)")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--root", default=None, help=argparse.SUPPRESS)
    p.add_argument("--list-passes", action="store_true",
                   help="list registered passes and exit")
    return p


def main(argv: list[str] | None = None, out=None) -> int:
    out = out if out is not None else sys.stdout
    args = build_parser().parse_args(argv)
    passes = core.all_passes()

    if args.list_passes:
        for pid in sorted(passes):
            print(f"{pid:22s} {passes[pid].description}", file=out)
        return 0

    if args.only:
        wanted = [p.strip() for p in args.only.split(",") if p.strip()]
        unknown = [p for p in wanted if p not in passes]
        if unknown:
            print(f"analyze: unknown pass(es): {', '.join(unknown)} "
                  f"(have: {', '.join(sorted(passes))})", file=sys.stderr)
            return 2
        passes = {pid: passes[pid] for pid in wanted}

    root = os.path.abspath(args.root) if args.root else _repo_root()

    if args.compare_runtime is not None:
        # a diagnostic mode, not a findings run: diff static vs runtime
        # lock-order graphs; exit 1 = the pass has named blind spots.
        # Dispatched BEFORE any --changed/path scoping, always over the
        # FULL surface — a scoped context would misreport locks in
        # un-analyzed files as blind spots (and a clean --changed tree
        # must not skip the comparison entirely).
        return _compare_runtime(args.compare_runtime,
                                core.build_context(root), out)

    paths = list(args.paths) or None
    if args.changed:
        if paths:
            print("analyze: --changed and explicit paths are mutually "
                  "exclusive", file=sys.stderr)
            return 2
        changed = _changed_paths(root)
        if changed is None:
            print("analyze: --changed needs a git worktree", file=sys.stderr)
            return 2
        if not changed:
            # machine formats still get a parseable (empty) document —
            # the CLEAN tree is the common case in a SARIF/JSON
            # pipeline and must not feed it a prose line
            if args.format == "sarif":
                json.dump(_sarif(passes, []), out, indent=2,
                          ensure_ascii=False)
                out.write("\n")
            elif args.format == "json":
                json.dump(_json_payload(passes, []), out, indent=2,
                          ensure_ascii=False)
                out.write("\n")
            else:
                print("analyze: no changed files on the analyzed "
                      "surface", file=out)
            return 0
        paths = changed
    if paths and not args.changed:
        missing = [p for p in paths
                   if not os.path.isfile(os.path.join(root, p))]
        if missing:
            # A typo'd CI path must not stay green having analyzed
            # nothing — same class of mistake as an unknown pass.
            print(f"analyze: no such file(s): {', '.join(missing)}",
                  file=sys.stderr)
            return 2
    ctx = core.build_context(root, paths)

    findings: list[core.Finding] = []
    for pid in sorted(passes):
        findings.extend(passes[pid].run(ctx))
    # A file no pass could parse is unenforced, not clean — surface it
    # as a finding so the gate fails (baselinable like any other, with
    # a reason, if someone truly ships unparseable python).
    for sf in ctx.files:
        if sf.tree is None:
            findings.append(core.Finding(
                "parse-error", sf.path, 1,
                "file does not parse — every invariant pass skipped it",
                key="parse-error"))
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id, f.key))

    bl = None
    bl_path = args.baseline
    if not args.no_baseline:
        if bl_path is None:
            default = os.path.join(root, baseline_lib.DEFAULT_BASELINE)
            bl_path = default if os.path.exists(default) else None
        if bl_path is not None:
            if not os.path.exists(bl_path) and args.write_baseline:
                bl = None  # --write-baseline creates it below
            else:
                try:
                    bl = baseline_lib.Baseline.load(bl_path)
                except (OSError, ValueError, json.JSONDecodeError) as e:
                    print(f"analyze: cannot read baseline {bl_path}: {e}",
                          file=sys.stderr)
                    return 2

    if args.write_baseline:
        target = bl_path or os.path.join(root, baseline_lib.DEFAULT_BASELINE)
        keep: list[dict] = []
        if bl is not None and (args.only or paths):
            # `paths`, not `args.paths`: a --changed run is scoped too
            # A scoped run only re-evaluated (selected passes ×
            # analyzed files): entries outside that product were not
            # looked at and must survive the rewrite.
            analyzed = {sf.path for sf in ctx.files}
            keep = [e for e in bl.entries
                    if e["pass"] not in passes
                    or e["path"] not in analyzed]
        n = baseline_lib.Baseline.write(target, findings, previous=bl,
                                        keep=keep)
        print(f"analyze: wrote {n} suppression(s) to "
              f"{os.path.relpath(target, root)}", file=out)
        return 0

    if bl is not None:
        unsuppressed, suppressed, stale = bl.apply(findings)
    else:
        unsuppressed, suppressed, stale = findings, [], []

    syntax_errors = [sf.path for sf in ctx.files if sf.tree is None]

    if args.format == "sarif":
        json.dump(_sarif(passes, unsuppressed), out, indent=2,
                  ensure_ascii=False)
        out.write("\n")
    elif args.format == "json":
        json.dump(_json_payload(passes, unsuppressed, suppressed, stale,
                                syntax_errors), out, indent=2,
                  ensure_ascii=False)
        out.write("\n")
    else:
        for f in unsuppressed:
            print(f.render(), file=out)
        for e in stale:
            print(f"analyze: stale baseline entry (nothing matches it "
                  f"anymore): {e['pass']} {e['path']} {e['key']!r}"
                  + (f" — {e['reason']}" if e.get("reason") else ""),
                  file=out)
        summary = (f"analyze: {len(unsuppressed)} finding(s), "
                   f"{len(suppressed)} suppressed, {len(stale)} stale "
                   f"baseline entr{'y' if len(stale) == 1 else 'ies'}, "
                   f"{len(passes)} pass(es) over {len(ctx.files)} files")
        print(summary, file=out)

    return 1 if unsuppressed else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
