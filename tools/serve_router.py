#!/usr/bin/env python
"""Fault-tolerant multi-replica router over serve_http replicas.

    # replicas (each on its own host/port, e.g. under a supervisor):
    python tools/serve_http.py ... --port 8000 [--advertise]
    python tools/serve_http.py ... --port 8001 [--advertise]

    # the front:
    python tools/serve_router.py --port 8080 \
        --replica 127.0.0.1:8000 --replica 127.0.0.1:8001
    # or discover replicas from the elastic launcher store:
    TPUSTORE_ADDR=host:port python tools/serve_router.py --port 8080 --store

Thin HTTP front (stdlib only, like serve_http) over N replicas, built
on serving_plane/router.py:

- **discovery** — static ``--replica`` list and/or the elastic
  launcher store (``--store``: replicas registered by
  ``serve_http --advertise``, re-read every probe round so late
  arrivals join without a restart);
- **health** — background ``/healthz`` probes drive per-replica state
  (``up | draining | down``); flips are journaled (``serve`` events);
- **balancing** — least outstanding requests among up replicas; a
  replica whose own admission state says ``shedding`` ranks last;
- **retry** — idempotent requests (no keep/session/prefix) retry on a
  connect failure or retryable status (429/502/503): a dead or
  draining replica costs a journaled failover, not a client error;
  streams retry only before the first relayed byte;
- **hedging** — ``--hedge-after S`` (fixed) or ``--hedge-pct 0.95``
  (latency percentile): a straggling completion gets a second copy on
  another replica, first answer wins (journaled ``hedge``/
  ``hedge_win``);
- **sessions** — replica-local KV: a ``keep`` completion's session id
  is mapped to its replica and later ``session``/``prefix`` requests
  pin there (never retried/hedged). Streamed first turns are not
  tracked — open sessions with non-streamed requests through the
  router;
- **rolling restart** — ``POST /admin/rolling_restart`` (or
  ``--rolling-restart`` one-shot) walks each replica through
  serve_http's drain path (``/admin/drain``) one at a time: zero
  failed requests for a fleet-wide restart;
- **fleet weight sync** — ``POST /admin/weight_sync`` {version?} walks
  each replica through serve_http's live weight swap
  (``/admin/weights``) one at a time and returns the per-replica
  report: the online post-training loop's zero-downtime "swap the
  fleet" (docs/online_training.md);
- **tracing** — every request gets (or continues, via an inbound
  ``traceparent`` header) a distributed trace context; attempts,
  failovers and hedges are child spans, hedge copies are sent
  pre-sampled so the winner's replica retains its subtree, and the
  tail sampler spills retained trees beside the event journal
  (``--trace-dir`` / ``--trace-sample-pct`` / ``--trace-keep-slow-ms``;
  merge with ``tools/timeline_report.py --trace <id>``).

``GET /healthz`` answers 200 while at least one replica is routable,
with the per-replica table in the body; ``GET /metrics`` exposes the
router's own counters (failovers, hedges, replica flips).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# PDTT_SANITIZE=1: patch threading BEFORE the imports below create
# their module-global locks (events/tracing/registry singletons)
from pytorch_distributed_train_tpu.utils import syncdbg  # noqa: E402

syncdbg.maybe_activate()

from pytorch_distributed_train_tpu.obs import events as events_lib  # noqa: E402
from pytorch_distributed_train_tpu.obs import tracing  # noqa: E402
from pytorch_distributed_train_tpu.obs.exposition import (  # noqa: E402
    CONTENT_TYPE as _METRICS_CONTENT_TYPE,
    render_metrics,
)
from pytorch_distributed_train_tpu.obs.registry import get_registry  # noqa: E402
from pytorch_distributed_train_tpu.obs.spans import span  # noqa: E402
from pytorch_distributed_train_tpu.serving_plane.router import (  # noqa: E402
    RETRYABLE_STATUSES,
    HealthProber,
    ReplicaSet,
    Router,
)

_PROXY_PATHS = ("/v1/completions", "/v1/chat/completions", "/v1/preload",
                "/profile")


def make_handler(router: Router, prober: HealthProber):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _send(self, code: int, obj: dict):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _relay(self, code: int, body: bytes):
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if code == 429:
                # rebuild the replica's back-off contract from the body
                # (http_json strips headers): 429 without Retry-After
                # makes clients hammer the overload admission damps
                try:
                    after = json.loads(body).get("retry_after_s")
                except (ValueError, AttributeError):
                    after = None
                if after is not None:
                    self.send_header("Retry-After", str(int(after)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                snap = router.replicas.snapshot()
                up = sum(1 for r in snap if r["state"] == "up")
                self._send(200 if up else 503,
                           {"status": "ok" if up else "no_replicas",
                            "up": up, "replicas": snap,
                            "sessions": len(router.sessions)})
            elif path == "/metrics":
                body = render_metrics().encode()
                self.send_response(200)
                self.send_header("Content-Type", _METRICS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._send(404, {"error": "unknown path"})

        def do_POST(self):
            path = self.path.split("?", 1)[0]
            get_registry().counter(
                "router_requests_total", labels={"path": path},
                help="router requests by path").inc()
            if path == "/admin/weights":
                # fleet-controller rebalance hook: body is a flat
                # {addr: weight} map applied to the routable set
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    weights = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._send(400, {"error": "bad json"})
                    return
                if not isinstance(weights, dict):
                    self._send(400, {"error": "want {addr: weight}"})
                    return
                router.replicas.set_weights(weights)
                self._send(200, {"status": "ok",
                                 "replicas":
                                     router.replicas.snapshot()})
                return
            if path == "/admin/weight_sync":
                # online post-training plane: broadcast a live weight
                # swap (serve_http /admin/weights) across the fleet,
                # one replica at a time; body {version?} (default:
                # newest sealed). Synchronous — the caller (the online
                # loop) wants the per-replica report.
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._send(400, {"error": "bad json"})
                    return
                version = body.get("version")
                report = router.weight_sync(
                    version=int(version) if version is not None else None,
                    traceparent=self.headers.get("traceparent"))
                ok = all("error" not in e and "skipped" not in e
                         for e in report)
                self._send(200 if ok else 502,
                           {"status": "ok" if ok else "partial",
                            "replicas": report})
                return
            if path == "/admin/rolling_restart":
                # walk replicas through their drain path off-thread; the
                # report lands in the journal (serve/rolling_drain per
                # replica), the client gets an immediate 202
                threading.Thread(target=router.rolling_restart,
                                 daemon=True,
                                 name="rolling-restart").start()
                self._send(202, {"status": "rolling"})
                return
            if path not in _PROXY_PATHS:
                self._send(404, {"error": "unknown path"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n) or b"{}"
                body = json.loads(raw)
            except ValueError:
                self._send(400, {"error": "bad json"})
                return
            tp = self.headers.get("traceparent")
            if isinstance(body, dict) and body.get("stream"):
                self._proxy_stream(path, raw, body, tp)
                return
            status, rbody = router.request(path, raw,
                                           body if isinstance(body, dict)
                                           else {}, traceparent=tp)
            self._relay(status, rbody)

        def _proxy_stream(self, path: str, raw: bytes, body: dict,
                          traceparent: str | None = None):
            """SSE passthrough: relay upstream bytes as they arrive.
            Retry/failover happens only BEFORE the first relayed byte —
            once deltas went out, re-running the request would duplicate
            text, so an upstream death mid-stream ends the stream (the
            client retries; idempotent by its own choice). The trace
            context rides the upstream request; a failover flags the
            trace for retention."""
            ctx = tracing.continue_or_start(traceparent)
            t0 = time.monotonic()
            try:
                with tracing.activate(ctx):
                    with span("router.stream", path=path):
                        self._proxy_stream_traced(path, raw, body, ctx)
            finally:
                tracing.get_tracer().finish(
                    ctx.trace_id, dur_s=time.monotonic() - t0)

        def _proxy_stream_traced(self, path: str, raw: bytes,
                                 body: dict, ctx):
            pinned, idempotent = router.classify(body)
            tried: set[str] = set()
            while True:
                addr = pinned or router.replicas.pick(exclude=tried)
                if addr is None:
                    self._send(503, {"error": "no replica available"})
                    return
                tried.add(addr)
                router.replicas.begin(addr)
                headers = {"Content-Type": "application/json"}
                child = tracing.current_child_context(
                    sampled=ctx.sampled or bool(tried - {addr}))
                if child is not None:
                    headers["traceparent"] = \
                        tracing.format_traceparent(child)
                try:
                    upstream = urllib.request.urlopen(
                        urllib.request.Request(
                            f"http://{addr}{path}", data=raw,
                            headers=headers),
                        timeout=router.timeout_s)
                except urllib.error.HTTPError as e:
                    router.replicas.end(addr)
                    if (e.code in RETRYABLE_STATUSES and idempotent
                            and pinned is None):
                        self._failover(ctx, addr, path, e.code)
                        continue
                    self._relay(e.code, e.read())
                    return
                except (urllib.error.URLError, OSError):
                    router.replicas.end(addr)
                    if pinned is None:
                        self._failover(ctx, addr, path, 0)
                        continue
                    self._send(502, {"error": "session replica "
                                              "unreachable"})
                    return
                try:
                    self.send_response(upstream.status)
                    self.send_header("Content-Type",
                                     upstream.headers.get(
                                         "Content-Type",
                                         "text/event-stream"))
                    self.send_header("Cache-Control", "no-cache")
                    self.end_headers()
                    while True:
                        chunk = upstream.read1(8192)
                        if not chunk:
                            break
                        self.wfile.write(chunk)
                        self.wfile.flush()
                except OSError:
                    pass  # client or upstream went away mid-stream
                finally:
                    try:
                        upstream.close()
                    except OSError:
                        pass
                    router.replicas.end(addr)
                return

        @staticmethod
        def _failover(ctx, addr: str, path: str, status: int) -> None:
            tracing.flag(ctx.trace_id, "failover")
            events_lib.emit("serve", "failover", addr=addr, path=path,
                            reason="stream_connect", status=status)
            get_registry().counter(
                "serve_failovers_total",
                help="requests retried on another replica").inc()

    return Handler


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--replica", action="append", default=[],
                   metavar="HOST:PORT", help="static replica address "
                   "(repeatable)")
    p.add_argument("--store", action="store_true",
                   help="discover replicas from the elastic launcher "
                        "store (TPUSTORE_ADDR; serve_http --advertise)")
    p.add_argument("--probe-interval", type=float, default=0.5)
    p.add_argument("--down-after", type=int, default=2,
                   help="consecutive failed probes before a replica is "
                        "marked down")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="upstream request timeout seconds")
    p.add_argument("--hedge-after", type=float, default=0.0,
                   help="hedge a straggling completion onto a second "
                        "replica after this many seconds (0 = off)")
    p.add_argument("--hedge-pct", type=float, default=0.0,
                   help="or: hedge after this percentile of recent "
                        "request latencies (e.g. 0.95; needs >= 8 "
                        "samples; 0 = off)")
    p.add_argument("--rolling-restart", action="store_true",
                   help="one-shot: drain every replica in turn through "
                        "/admin/drain, print the report, exit")
    p.add_argument("--trace-dir", default="",
                   help="retained-trace JSONL directory (default "
                        "$PDTT_TRACE_DIR, else a traces/ sibling of "
                        "the event journal)")
    p.add_argument("--trace-sample-pct", type=float, default=None,
                   help="random baseline %% of traces retained")
    p.add_argument("--trace-keep-slow-ms", type=float, default=None,
                   help="retain any request trace slower than this "
                        "(default $PDTT_TRACE_KEEP_SLOW_MS or 250)")
    args = p.parse_args(argv)

    tracing.configure(args.trace_dir or tracing.default_dir(),
                      who="router",
                      sample_pct=args.trace_sample_pct,
                      keep_slow_ms=args.trace_keep_slow_ms)

    refresh = None
    if args.store:
        from pytorch_distributed_train_tpu import store_plane

        store = store_plane.resilient_worker_store(name="router")
        if store is None:
            print("serve_router: --store needs TPUSTORE_ADDR",
                  file=sys.stderr)
            return 2
        # last-known-good discovery (store_plane.ResilientStore): a
        # registry blackout serves the cached replica set — the router
        # keeps routing, it just can't pick up NEW replicas until the
        # store heals (the prober swallows a never-cached failure)
        refresh = store.discover_replicas
    replicas = ReplicaSet(tuple(args.replica))
    if not args.replica and refresh is None:
        print("serve_router: no replicas (--replica or --store)",
              file=sys.stderr)
        return 2
    prober = HealthProber(replicas, interval_s=args.probe_interval,
                          down_after=args.down_after, refresh=refresh)
    router = Router(replicas, timeout_s=args.timeout,
                    hedge_after_s=args.hedge_after,
                    hedge_pct=args.hedge_pct)
    prober.start()
    if args.rolling_restart:
        report = router.rolling_restart()
        print(json.dumps(report, indent=2))
        prober.stop()
        return 0
    server = ThreadingHTTPServer((args.host, args.port),
                                 make_handler(router, prober))
    print(f"routing on http://{args.host}:{server.server_address[1]} "
          f"over {len(replicas.addrs())} replica(s)", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        prober.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
