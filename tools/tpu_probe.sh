#!/bin/sh
# TPU lease health probe — delegates to bench.probe_once (the canonical
# probe definition) so this manual gate and bench.py's automated
# bring-up retry can never drift. rc 0 = chip executed work.
cd "$(dirname "$0")/.." || exit 2
${PYTHON:-python3} -c "
import sys
sys.path.insert(0, '.')
from bench import probe_once
ok, detail = probe_once(float('${1:-90}'))
print(detail)
sys.exit(0 if ok else 3)
"
