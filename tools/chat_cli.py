#!/usr/bin/env python
"""Interactive chat REPL over the serving stack — the quickest way to
talk to a trained/exported model from a terminal.

    python tools/chat_cli.py --config llama2_7b \
        --safetensors model.st --tokenizer /models/llama2-tok \
        [--system "You are terse."] [--temperature 0.7] [--top-p 0.9]

Each turn resumes the SAME KV session (serving.py keep/session), so the
conversation history stays resident on the chip — turn latency scales
with the new turn's length, not the transcript's. `--system` preloads
the system prompt as a shared-prefix template and forks the chat off it.

Commands: /reset (new conversation, reusing the system template),
/stats (batcher counters), /quit.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(args):
    import jax

    from pytorch_distributed_train_tpu.config import get_preset
    from pytorch_distributed_train_tpu.data.text import load_tokenizer
    from pytorch_distributed_train_tpu.serving import (
        ContinuousBatcher,
        load_params_for_serving,
    )

    cfg = get_preset(args.config)
    cfg.apply_overrides(args.set)
    tok = load_tokenizer(args.tokenizer)
    params = load_params_for_serving(cfg, args.safetensors, args.quantize)
    # 2 slots: one holds the system template (when --system), one chats.
    # A lone chat without a system prompt still only needs one.
    b = ContinuousBatcher(cfg.model, cfg.precision, params, slots=2,
                          top_k=args.top_k, top_p=args.top_p,
                          min_p=args.min_p,
                          rng=jax.random.PRNGKey(args.seed))
    return tok, b


def chat_loop(args, tok, batcher, out=sys.stdout) -> int:
    """The REPL proper; factored from main() so tests can drive it with
    a scripted stdin and a tiny model."""
    template = None
    if args.system:
        sys_ids = tok.encode(args.system)
        try:
            template = batcher.preload(sys_ids)
        except (ValueError, RuntimeError) as e:
            print(f"chat_cli: error: {e.args[0] if e.args else e}",
                  file=sys.stderr)
            return 2
        print(f"[system prompt preloaded: {len(sys_ids)} tokens]",
              file=out)
    session = None

    def one_turn(text: str) -> None:
        nonlocal session
        kw = {}
        # Turn boundaries for a BASE LM: a trailing newline separates the
        # user turn from the model's reply, and resumed turns open with
        # one so the previous (possibly length-capped) reply doesn't run
        # straight into the new input token stream.
        payload = ("\n" + text + "\n") if session is not None \
            else (text + "\n")
        if session is not None:
            kw["session"] = session
        elif template is not None:
            kw["prefix"] = template
        uid = batcher.submit(tok.encode(payload), args.max_new_tokens,
                             temperature=args.temperature,
                             eos_id=tok.eos_id, keep=True, **kw)
        done = {c.uid: c for c in batcher.run()}
        c = done[uid]
        session = c.session
        from pytorch_distributed_train_tpu.serving import trim_at_eos

        print(tok.decode(trim_at_eos(c.tokens, tok.eos_id)), file=out,
              flush=True)

    for line in sys.stdin:
        line = line.rstrip("\n")
        if not line.strip():
            continue
        if line.strip() == "/quit":
            break
        if line.strip() == "/reset":
            session = None  # old session stays parked until LRU-evicted
            print("[new conversation]", file=out)
            continue
        if line.strip() == "/stats":
            print(batcher.stats, file=out)
            continue
        try:
            one_turn(line)
        except ValueError as e:
            # context exhausted or similar — start fresh rather than die
            print(f"[error: {e.args[0] if e.args else e}; /reset to "
                  "continue]", file=out)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default="llama2_7b")
    p.add_argument("--set", action="append", default=[], metavar="KEY=VALUE")
    p.add_argument("--safetensors", required=True)
    p.add_argument("--tokenizer", default="",
                   help="local HF tokenizer dir; empty → byte tokenizer")
    p.add_argument("--system", default="",
                   help="system prompt, preloaded once as a prefix template")
    p.add_argument("--max-new-tokens", type=int, default=128)
    p.add_argument("--temperature", type=float, default=0.7)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=0.0)
    p.add_argument("--min-p", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--quantize", default="", choices=["", "int8"])
    args = p.parse_args(argv)
    try:
        tok, batcher = build(args)
    except (KeyError, ValueError, FileNotFoundError, OSError) as e:
        print(f"chat_cli: error: {e.args[0] if e.args else e}",
              file=sys.stderr)
        return 2
    if sys.stdin.isatty():
        print("[chat ready — /reset, /stats, /quit]", flush=True)
    return chat_loop(args, tok, batcher)


if __name__ == "__main__":
    sys.exit(main())
