#!/usr/bin/env python
"""Probe whether this backend can compile+execute a Mosaic (Pallas)
kernel, and record the verdict (VERDICT r3 #4).

The axon tunnel has historically HUNG on Mosaic remote compiles (>8 min,
wedging the lease), so `attention.impl='auto'` routes around Pallas on
axon backends. This probe replaces that hardcoded heuristic with a
measured record:

- runs a tiny flash-attention forward in a SUBPROCESS with a hard
  timeout (a hang kills the child, never this process or the lease
  bookkeeping of the parent);
- writes MOSAIC_PROBE.json {status: ok|hang|error, detail, elapsed_s}
  at the repo root — `ops.attention._pallas_usable` consults it, so a
  future healed tunnel auto-enables the kernel with no code change;
- on status=ok, immediately runs the flash-vs-chunked timed A/B the
  kernel's 594 LoC have been waiting for, and emits a bench-style row.

Always prints ONE JSON line (bench_sweep contract).

Run:  python tools/mosaic_probe.py [--timeout 300] [--skip-ab]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_CHILD = r"""
import sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from pytorch_distributed_train_tpu.ops import flash_attention as fa

q = jnp.ones((1, 256, 4, 64), jnp.bfloat16)
out = fa.flash_attention(q, q, q, causal=True, interpret=False)
# value fetch: block_until_ready lies over the tunnel (bench.py docstring)
print("v=", float(out.astype(jnp.float32).sum()), "kind=",
      jax.devices()[0].device_kind)
"""

_AB = r"""
import sys, time, json
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from pytorch_distributed_train_tpu.ops.attention import dot_product_attention

B, S, H, D = 4, 2048, 16, 128
q = jnp.ones((B, S, H, D), jnp.bfloat16)


def bench(impl):
    def loss(q):
        return dot_product_attention(q, q, q, causal=True, impl=impl).astype(
            jnp.float32).sum()

    step = jax.jit(jax.grad(loss))
    g = step(q); float(g.sum())  # compile + execute
    t0 = time.perf_counter()
    for _ in range(10):
        g = step(g * 0 + q)
    float(g.sum())
    return (time.perf_counter() - t0) / 10


flash_s = bench("pallas")
chunked_s = bench("chunked")
print(json.dumps({{"flash_ms": flash_s * 1e3, "chunked_ms": chunked_s * 1e3}}))
"""


def run_child(code: str, timeout_s: float) -> tuple[str, str]:
    """(status, detail) from a hard-timeout subprocess run."""
    try:
        r = subprocess.run([sys.executable, "-c", code.format(repo=REPO)],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return "hang", f"no result in {timeout_s:.0f}s (Mosaic remote " \
                       "compile wedged — child killed)"
    if r.returncode == 0:
        return "ok", r.stdout.strip().splitlines()[-1]
    tail = (r.stderr or r.stdout).strip().splitlines()
    return "error", (tail[-1][-300:] if tail else f"rc={r.returncode}")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--timeout", type=float, default=300.0)
    p.add_argument("--skip-ab", action="store_true")
    p.add_argument("--out", default=os.path.join(REPO, "MOSAIC_PROBE.json"))
    args = p.parse_args()

    t0 = time.monotonic()
    status, detail = run_child(_CHILD, args.timeout)
    # Carry forward previously measured A/B timings: a --skip-ab recheck
    # (or a failed A/B child) must not erase the flash-vs-chunked record
    # that keeps _pallas_usable's auto-gate honest — a timing-less "ok"
    # would reopen a measured-slower kernel. Fresh A/B results below
    # overwrite these.
    prev_ab = {}
    try:
        with open(args.out) as f:
            old = json.load(f)
        # Backend identity must match: timings measured on a direct TPU
        # say nothing about the tunnel (and vice versa) — relabeling
        # them under the current env could reopen a kernel the current
        # backend measured slower.
        if (old.get("jax_platforms_env")
                == os.environ.get("JAX_PLATFORMS", "")
                and "flash_ms" in old and "chunked_ms" in old):
            prev_ab = {"flash_ms": old["flash_ms"],
                       "chunked_ms": old["chunked_ms"],
                       "ab_measured": old.get("ab_measured",
                                              old.get("probed"))}
    except (OSError, ValueError):
        pass
    rec = {
        "status": status,
        "detail": detail,
        "elapsed_s": round(time.monotonic() - t0, 1),
        "probed": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "timeout_s": args.timeout,
        # Backend identity: _pallas_usable honors this record ONLY when
        # it was captured against the axon stack (the child inherits
        # this env) — an ok from a direct TPU must not open the tunnel.
        "jax_platforms_env": os.environ.get("JAX_PLATFORMS", ""),
        **prev_ab,
    }
    with open(args.out, "w") as f:
        json.dump(rec, f, indent=1)

    row: dict = {"metric": "mosaic_flash_vs_chunked_ms", "value": None,
                 "unit": "ms/step fwd+bwd (B4 S2048 H16 D128)",
                 "vs_baseline": 1.0, "probe": rec}
    if status == "ok" and not args.skip_ab:
        ab_status, ab_detail = run_child(_AB, max(args.timeout * 2, 600.0))
        if ab_status == "ok":
            try:
                ab = json.loads(ab_detail)
                row["value"] = round(ab["flash_ms"], 2)
                row["chunked_ms"] = round(ab["chunked_ms"], 2)
                row["speedup_vs_chunked"] = round(
                    ab["chunked_ms"] / ab["flash_ms"], 3)
                # Persist the measured A/B into the record so
                # _pallas_usable's auto-gate can pick the WINNER, not
                # merely the compilable: an ok-but-slower kernel must
                # not silently regress impl='auto' users. UNROUNDED —
                # the gate compares these floats exactly (flash <=
                # chunked), and a near-tie can flip under 2-decimal
                # rounding; the bench row above rounds for display only.
                rec["flash_ms"] = ab["flash_ms"]
                rec["chunked_ms"] = ab["chunked_ms"]
                rec["ab_measured"] = rec["probed"]
                with open(args.out, "w") as f:
                    json.dump(rec, f, indent=1)
            except (ValueError, KeyError):
                row["ab_error"] = ab_detail[-300:]
        else:
            row["ab_error"] = f"{ab_status}: {ab_detail[-300:]}"
    print(json.dumps(row), flush=True)
    return 0 if status == "ok" else 4


if __name__ == "__main__":
    sys.exit(main())
