#!/usr/bin/env python
"""Closed-loop fleet controller daemon (fleet/controller.py).

    # observe-only first: journal what WOULD happen
    python tools/fleet_controller.py --store 127.0.0.1:7777 \
        --events run/events --dry-run

    # the real loop: scale serving replicas between 2 and 4, push
    # router weights, cap actuation at 10 acts per 5 minutes
    TPUSTORE_ADDR=127.0.0.1:7777 python tools/fleet_controller.py \
        --min-replicas 2 --max-replicas 4 \
        --router 127.0.0.1:8080 \
        --launch-arg=--fake-backend --launch-arg=--slots=4

Builds the same store-discovered collector + alert engine the fleet
console runs, then closes the loop: sustained overload alerts scale
decode replicas OUT (subprocess ``serve_http --advertise``), a calm
fleet scales IN through ``/admin/drain`` with zero failed requests, a
sick host is drain-and-recycled, and router dispatch weights track
per-replica load (``POST /admin/weights`` on ``--router``). Safety
rails — fleet bounds, hysteresis, per-action cooldowns, the windowed
action budget with its ``degraded (budget_exhausted)`` latch, and
``--dry-run`` — are documented in docs/autoscaler.md, along with the
closed action catalog every decision is journaled against.

Pure stdlib + the repo's obs/fleet packages; no jax — safe on a login
host.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# PDTT_SANITIZE=1: patch threading BEFORE the imports below create
# their module-global locks (events/registry singletons)
from pytorch_distributed_train_tpu.utils import syncdbg  # noqa: E402

syncdbg.maybe_activate()

from pytorch_distributed_train_tpu.fleet.controller import (  # noqa: E402
    FleetController,
    SubprocessReplicaLauncher,
)
from pytorch_distributed_train_tpu.obs import events as events_lib  # noqa: E402


def make_weights_sink(router_addr: str, timeout_s: float = 3.0):
    """The rebalance actuator: POST the weight map to serve_router's
    ``/admin/weights``. Best-effort errors surface to the controller
    as a failed action, which is exactly what they are."""

    def sink(weights: dict) -> None:
        req = urllib.request.Request(
            f"http://{router_addr}/admin/weights",
            data=json.dumps(weights).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=timeout_s).read()

    return sink


def build_controller(args, collector, engine) -> FleetController:
    launcher = None
    if not args.no_launch:
        env = dict(os.environ)
        if args.store:
            env["TPUSTORE_ADDR"] = args.store
        launcher = SubprocessReplicaLauncher(
            serve_http_path=os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "serve_http.py"),
            extra_args=tuple(args.launch_arg or ()), env=env)
    sink = make_weights_sink(args.router) if args.router else None
    cooldowns = {}
    for spec in args.cooldown or ():
        action, _, value = spec.partition("=")
        cooldowns[action] = float(value)
    return FleetController(
        collector, engine, launcher=launcher, weights_sink=sink,
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
        hysteresis=args.hysteresis, calm_ticks=args.calm_ticks,
        cooldown_s=cooldowns,
        budget_window_s=args.budget_window,
        budget_max_actions=args.budget_actions,
        verify_s=args.verify_timeout,
        drain_timeout_s=args.drain_timeout,
        dry_run=args.dry_run)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--store", default="",
                   help="launcher store host:port (default: "
                        "$TPUSTORE_ADDR) for endpoint discovery")
    p.add_argument("--target", action="append", metavar="ROLE=HOST:PORT",
                   help="static scrape target (repeatable)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="collector scrape + controller tick seconds")
    p.add_argument("--stale-after", type=float, default=10.0)
    p.add_argument("--timeout", type=float, default=2.0)
    p.add_argument("--rule", action="append", metavar="RULE.FIELD=VALUE",
                   help="alert-rule override (fleet_console syntax)")
    p.add_argument("--history-dir", default="",
                   help="durable tsdb dir (burn-rate rules evaluate "
                        "when attached)")
    p.add_argument("--history-budget-mb", type=float, default=64.0)
    p.add_argument("--alert-file", default="")
    p.add_argument("--alert-webhook", default="")
    p.add_argument("--profile-on-alert", action="store_true",
                   help="firing anomaly rules POST /profile on the "
                        "offending target (fleet_console semantics)")
    p.add_argument("--profile-cooldown", type=float, default=300.0)
    p.add_argument("--events", default="",
                   help="event-journal directory (default "
                        "$PDTT_EVENTS_DIR) — the action journal")
    p.add_argument("--router", default="",
                   help="serve_router host:port for the rebalance "
                        "weights hook (empty = rebalance off)")
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=4)
    p.add_argument("--hysteresis", type=int, default=2,
                   help="consecutive firing evaluations before acting")
    p.add_argument("--calm-ticks", type=int, default=5,
                   help="consecutive quiet evaluations before scale-in")
    p.add_argument("--cooldown", action="append",
                   metavar="ACTION=SECONDS",
                   help="per-action cooldown override (repeatable)")
    p.add_argument("--budget-window", type=float, default=300.0,
                   help="action-budget rolling window seconds")
    p.add_argument("--budget-actions", type=int, default=10,
                   help="max actions per window; overflow latches "
                        "degraded observe-only mode")
    p.add_argument("--verify-timeout", type=float, default=15.0,
                   help="seconds a launched replica has to answer "
                        "/healthz before rollback")
    p.add_argument("--drain-timeout", type=float, default=30.0)
    p.add_argument("--launch-arg", action="append",
                   help="extra serve_http arg for launched replicas "
                        "(repeatable, e.g. --launch-arg=--fake-backend)")
    p.add_argument("--no-launch", action="store_true",
                   help="no launcher: scale_out/recycle-replace off")
    p.add_argument("--dry-run", action="store_true",
                   help="journal intended actions, act on nothing")
    p.add_argument("--ticks", type=int, default=0,
                   help="exit after N ticks (0 = run until ^C); the "
                        "status JSON prints on exit")
    p.add_argument("--list-actions", action="store_true",
                   help="print the closed action catalog and exit")
    args = p.parse_args(argv)

    if args.list_actions:
        from pytorch_distributed_train_tpu.fleet.controller import (
            ACTIONS,
        )

        for name, a in sorted(ACTIONS.items()):
            print(f"{name:<10} triggers={','.join(a.triggers)}  "
                  f"{a.description}")
        return 0
    if not (args.store or os.environ.get("TPUSTORE_ADDR")
            or args.target):
        print("fleet_controller: no targets (--store, $TPUSTORE_ADDR "
              "or --target)", file=sys.stderr)
        return 2
    events_dir = args.events or os.environ.get(events_lib.ENV_VAR)
    if events_dir:
        events_lib.configure(events_dir, who="controller")
    from tools.fleet_console import build

    collector, engine = build(args)
    controller = build_controller(args, collector, engine)
    print(f"fleet_controller: mode={controller.mode} "
          f"bounds=[{controller.min_replicas},"
          f"{controller.max_replicas}] budget="
          f"{controller.budget_max_actions}/"
          f"{controller.budget_window_s:.0f}s", flush=True)
    n = 0
    try:
        while True:
            collector.poll()
            engine.evaluate(collector)
            for rec in controller.tick():
                print(f"[fleet-controller] {rec['action']} -> "
                      f"{rec['outcome']} ({rec.get('reason') or rec.get('addr') or ''})",
                      flush=True)
            n += 1
            if args.ticks and n >= args.ticks:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        if controller.launcher is not None:
            controller.launcher.stop_all()
    print(json.dumps(controller.status(), indent=2, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
