#!/usr/bin/env python
"""Build a packed pre-decoded sample cache (data/packed_cache.py).

    # ImageNet-layout folder -> packed val cache at 224px
    python -m tools.pack_dataset --src /data/imagenet/val --out /cache \
        --split val --size 224

    # WebDataset tar shards -> packed train cache
    python -m tools.pack_dataset --src '/data/imagenet-train-*.tar' \
        --out /cache --split train --size 224 --shard-records 8192

Decodes every image ONCE — deterministically (shorter-side resize +
center crop, the eval transform; no random draws, so the cache bytes
are a pure function of the source) — and writes fixed-record uint8
shards with a per-shard payload CRC. Training then reads the cache as
one mmap'd strided gather per batch (dataset ``packed_images``, or
``data.packed_cache_dir`` on the original dataset) and applies its
random augmentation on top, host- or device-side.

Every shard written is CRC-verified back before the tool declares
success (``--no-verify`` skips, for very large packs where the writer
is trusted). Exit nonzero on any verification failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pytorch_distributed_train_tpu.data import packed_cache  # noqa: E402
from pytorch_distributed_train_tpu.obs.registry import get_registry  # noqa: E402

_NORMS = {
    "imagenet": ("IMAGENET_MEAN", "IMAGENET_STD"),
    "cifar": ("CIFAR_MEAN", "CIFAR_STD"),
}


def _build_source(src: str, size: int):
    """Source dataset in raw-u8 eval mode: get_item(i) -> deterministic
    center-cropped HWC uint8 + label (datasets.py owns the transform)."""
    from pytorch_distributed_train_tpu.data import datasets as ds_lib

    if os.path.isdir(src):
        return ds_lib.ImageFolderDataset(src, size, train=False,
                                         raw_u8=True)
    return ds_lib.TarShardImageDataset(src, size, train=False,
                                       raw_u8=True)


def pack_items(dataset, out_dir: str, *, split: str, shard_records: int,
               meta: dict, threads: int = 0, verify: bool = True,
               progress=None) -> list[str]:
    """Pack any item-style u8 dataset into shards; returns shard paths.

    Decode fans out over threads (PIL releases the GIL); records land in
    INDEX ORDER regardless of thread scheduling — shard bytes must be
    reproducible, they carry a CRC."""
    os.makedirs(out_dir, exist_ok=True)
    n = len(dataset)
    threads = threads or min(16, os.cpu_count() or 4)
    rng = np.random.default_rng(0)  # unused by eval transforms; API needs one
    reg = get_registry()
    c_rec = reg.counter("packed_cache_build_records_total",
                        help="records decoded + written by the pack tool")
    g_sec = reg.gauge("packed_cache_build_seconds",
                      help="wall seconds of the last pack_dataset build")
    t0 = time.monotonic()
    paths: list[str] = []
    with ThreadPoolExecutor(max_workers=threads) as pool:
        for shard_i, start in enumerate(range(0, n, shard_records)):
            idx = range(start, min(start + shard_records, n))
            items = list(pool.map(
                lambda i: dataset.get_item(i, rng), idx))
            images = np.stack([it["image"] for it in items])
            labels = np.asarray([it["label"] for it in items], np.int32)
            path = os.path.join(
                out_dir,
                f"{split}-{shard_i:05d}{packed_cache.SHARD_SUFFIX}")
            packed_cache.write_packed_shard(path, images, labels, meta)
            c_rec.inc(len(items))
            paths.append(path)
            if progress is not None:
                progress(path, len(items))
    if verify:
        for path in paths:
            if not packed_cache.verify_shard(path):
                raise SystemExit(f"pack_dataset: CRC verification FAILED "
                                 f"for {path}")
    g_sec.set(time.monotonic() - t0)
    return paths


def pack_arrays(images_u8: np.ndarray, labels: np.ndarray, out_dir: str,
                *, split: str = "train", shard_records: int = 0,
                meta: dict | None = None) -> list[str]:
    """Pack in-RAM arrays (benches/tests) — same format, no decode."""
    os.makedirs(out_dir, exist_ok=True)
    n = len(images_u8)
    shard_records = shard_records or n
    paths = []
    for shard_i, start in enumerate(range(0, n, shard_records)):
        sl = slice(start, min(start + shard_records, n))
        path = os.path.join(
            out_dir, f"{split}-{shard_i:05d}{packed_cache.SHARD_SUFFIX}")
        packed_cache.write_packed_shard(path, images_u8[sl], labels[sl],
                                        meta or {})
        paths.append(path)
    return paths


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--src", required=True,
                   help="ImageFolder root dir, or a .tar shard glob")
    p.add_argument("--out", required=True, help="output cache directory")
    p.add_argument("--split", default="train",
                   help="shard name prefix (train|val)")
    p.add_argument("--size", type=int, default=224,
                   help="record edge: shorter-side resize + center crop")
    p.add_argument("--shard-records", type=int, default=8192)
    p.add_argument("--threads", type=int, default=0,
                   help="decode threads (0 = auto)")
    p.add_argument("--norm", choices=sorted(_NORMS), default="imagenet",
                   help="mean/std stamped into shard meta (the training "
                        "normalize constants)")
    p.add_argument("--pad", type=int, default=4,
                   help="reflect-pad crop margin stamped into meta "
                        "(train-time augment of the packed records)")
    p.add_argument("--no-verify", action="store_true",
                   help="skip the CRC read-back pass")
    args = p.parse_args(argv)

    from pytorch_distributed_train_tpu.data import datasets as ds_lib

    mean_name, std_name = _NORMS[args.norm]
    meta = {
        "mean": [float(v) for v in getattr(ds_lib, mean_name)],
        "std": [float(v) for v in getattr(ds_lib, std_name)],
        "pad": args.pad,
        "src": args.src,
        "size": args.size,
    }
    dataset = _build_source(args.src, args.size)
    t0 = time.monotonic()

    def progress(path, count):
        print(f"pack_dataset: {path} ({count} records)", flush=True)

    paths = pack_items(dataset, args.out, split=args.split,
                       shard_records=args.shard_records, meta=meta,
                       threads=args.threads, verify=not args.no_verify,
                       progress=progress)
    total = sum(packed_cache.read_header(p)[0]["n"] for p in paths)
    print(json.dumps({
        "shards": len(paths),
        "records": total,
        "size": args.size,
        "out": args.out,
        "verified": not args.no_verify,
        "wall_s": round(time.monotonic() - t0, 2),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
