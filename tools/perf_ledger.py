#!/usr/bin/env python
"""Perf-ledger CLI: history import, regression gate, kernel-gap audit.

    python -m tools.perf_ledger --show                  # tail the ledger
    python -m tools.perf_ledger --import                # BENCH_r*.json → rows
    python -m tools.perf_ledger --check                 # regression gate
    python -m tools.perf_ledger --audit                 # kernel-gap report

Thin CLI over ``pytorch_distributed_train_tpu.obs.perf.PerfLedger``
(docs/performance.md has the row schema and workflow). The ledger is an
append-only JSONL written by bench.py (every measured record) and
trainer summaries (one row per fit); ``--check`` is the CI gate: it
compares every metric's NEWEST row against the prior rows' median+MAD
(the sentinel SpikeDetector's statistics) and exits nonzero NAMING the
regressed metric, so a throughput/MFU regression fails loudly instead
of drifting into the history it will later be judged against.

Default ledger path: $PDTT_PERF_LEDGER, else <repo>/PERF_LEDGER.jsonl.
Pure stdlib + the repo's obs package; no jax import — safe on a login
host.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pytorch_distributed_train_tpu.obs.perf import (  # noqa: E402
    AUDIT_PRESETS,
    PerfLedger,
    default_ledger_path,
    fusion_worklist,
    fusion_worklist_report,
    kernel_gap_report,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def show(ledger: PerfLedger, tail: int = 20) -> int:
    rows = ledger.load()
    if not rows:
        print(f"perf-ledger: no rows at {ledger.path}")
        return 0
    print(f"perf-ledger: {len(rows)} row(s) at {ledger.path} "
          f"(last {min(tail, len(rows))}):")
    for r in rows[-tail:]:
        mfu = (f" mfu={r['mfu_pct']}%"
               if isinstance(r.get("mfu_pct"), (int, float)) else "")
        src = f" [{r['source']}]" if r.get("source") else ""
        stall = ""
        if isinstance(r.get("stall_split"), dict) and r["stall_split"]:
            top = max(r["stall_split"], key=r["stall_split"].get)
            stall = f" stall_top={top}:{r['stall_split'][top]:.0%}"
        print(f"  {r['metric']:<48} {r['value']:>12} "
              f"{r.get('unit', ''):<18}{mfu}{stall}{src}")
    return 0


def check(ledger: PerfLedger, args) -> int:
    regs = ledger.check(min_rows=args.min_rows, sigma=args.sigma,
                        min_rel=args.min_rel,
                        metrics=args.metric or None)
    if not regs:
        n = len({r["metric"] for r in ledger.load()})
        print(f"perf-ledger: OK — no regression across {n} metric(s) "
              f"({ledger.path})")
        return 0
    for reg in regs:
        print(f"perf-ledger: REGRESSION {reg['metric']}.{reg['key']} = "
              f"{reg['value']} vs median {reg['median']} over "
              f"{reg['n_prior']} prior row(s)")
    return 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--path", default="",
                   help="ledger JSONL (default $PDTT_PERF_LEDGER or "
                        "<repo>/PERF_LEDGER.jsonl)")
    p.add_argument("--show", action="store_true",
                   help="print the newest rows")
    p.add_argument("--tail", type=int, default=20)
    p.add_argument("--import", dest="do_import", action="store_true",
                   help="back-import BENCH_r*.json round records "
                        "(idempotent: already-imported files skip)")
    p.add_argument("--repo", default=_REPO,
                   help="repo root the import scans for BENCH_r*.json")
    p.add_argument("--check", action="store_true",
                   help="regression gate: newest row per metric vs the "
                        "prior median+MAD; exit 1 naming regressions")
    p.add_argument("--min-rows", type=int, default=4,
                   help="prior rows a metric needs before it is gated")
    p.add_argument("--sigma", type=float, default=4.0,
                   help="robust sigmas of deviation that count as a "
                        "regression")
    p.add_argument("--min-rel", type=float, default=0.05,
                   help="absolute deviation floor, relative to the "
                        "median (guards near-zero-MAD histories)")
    p.add_argument("--metric", action="append", default=[],
                   help="gate only these metrics (repeatable)")
    p.add_argument("--audit", action="store_true",
                   help="kernel-gap report: op classes ranked by "
                        "roofline gap per preset")
    p.add_argument("--suggest", action="store_true",
                   help="with --audit: render the gap ranking as an "
                        "actionable fusion worklist (top-N op-class "
                        "gaps per preset -> the repo lever that closes "
                        "them, with config digest + measuring capture)")
    p.add_argument("--top", type=int, default=3,
                   help="worklist entries per preset for --suggest")
    p.add_argument("--presets", default=",".join(AUDIT_PRESETS),
                   help="comma-separated preset prefixes for --audit")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output for --check/--suggest")
    args = p.parse_args(argv)

    ledger = PerfLedger(args.path or default_ledger_path(_REPO))
    did = False
    rc = 0
    if args.do_import:
        did = True
        n = ledger.import_bench_history(args.repo)
        print(f"perf-ledger: imported {n} BENCH round record(s) into "
              f"{ledger.path}")
    if args.check:
        did = True
        if args.json:
            regs = ledger.check(min_rows=args.min_rows, sigma=args.sigma,
                                min_rel=args.min_rel,
                                metrics=args.metric or None)
            json.dump({"regressions": regs, "path": ledger.path},
                      sys.stdout, indent=1)
            print()
            rc = max(rc, 1 if regs else 0)
        else:
            rc = max(rc, check(ledger, args))
    if args.audit or args.suggest:
        did = True
        presets = tuple(s for s in args.presets.split(",") if s)
        rows = ledger.load()
        if args.audit:
            print(kernel_gap_report(rows, presets=presets))
        if args.suggest:
            if args.json:
                json.dump({"worklist": fusion_worklist(
                    rows, presets=presets, top_n=args.top)},
                    sys.stdout, indent=1)
                print()
            else:
                print(fusion_worklist_report(rows, presets=presets,
                                             top_n=args.top))
    if args.show or not did:
        rc = max(rc, show(ledger, tail=args.tail))
    return rc


if __name__ == "__main__":
    sys.exit(main())
