#!/usr/bin/env python
"""Print the resolved parameter sharding table for a config — which mesh
axes shard every param, the per-device shard shape, and per-device memory.

The operator-facing answer to "what will FSDP/TP actually do to this
model before I burn pod time on it" (torch analogue: printing the FSDP
wrapping plan / DTensor placements). Runs anywhere: uses eval_shape (no
weights are materialized) on a virtual device mesh.

    python tools/show_sharding.py --config llama2_7b --devices 16 \
        --set mesh.fsdp=8 --set mesh.tensor=2
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--config", required=True)
    p.add_argument("--devices", type=int, default=8,
                   help="virtual device count for the mesh")
    p.add_argument("--set", action="append", default=[], metavar="K=V")
    p.add_argument("--top", type=int, default=0,
                   help="show only the N largest params (0 = all)")
    args = p.parse_args()

    # CPU-only, like tests/conftest.py: the sandbox sitecustomize
    # force-selects the axon TPU platform (and may have imported jax
    # already), so override BOTH the env and the live jax config.
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={args.devices}"
    ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from flax import traverse_util

    from pytorch_distributed_train_tpu.config import get_preset
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh
    from pytorch_distributed_train_tpu.parallel.partition import (
        rules_for_model, validate_spec,
    )

    cfg = get_preset(args.config)
    cfg.apply_overrides(args.set)

    mesh = build_mesh(cfg.mesh)
    model = build_model(cfg.model, cfg.precision, mesh=mesh, mesh_cfg=cfg.mesh)
    rules = rules_for_model(cfg.model.name)

    from pytorch_distributed_train_tpu.steps import dummy_inputs

    def init(rng):
        # The same loss-keyed input dispatch the Trainer uses — covers
        # vision, LM, MLM, and seq2seq (t5) signatures.
        return model.init({"params": rng},
                          *dummy_inputs(cfg.loss, cfg.model, cfg.data),
                          train=False)

    shapes = jax.eval_shape(init, jax.random.PRNGKey(0))["params"]
    flat = traverse_util.flatten_dict(shapes)

    axes = {k: v for k, v in mesh.shape.items() if v > 1}
    print(f"config={args.config} devices={args.devices} mesh={axes or '{}'}")
    print(f"{'param':58s} {'shape':>20s} {'spec':>24s} {'shard/dev':>20s} "
          f"{'MB/dev':>8s}")

    rows = []
    for key, leaf in flat.items():
        name = "/".join(map(str, key))
        # same resolution the trainer uses: rule lookup, then divisibility
        # fallback (indivisible dims replicate — partition.py validate_spec)
        spec = validate_spec(rules.spec_for(name, leaf.shape), leaf.shape,
                             mesh)
        shard = list(leaf.shape)
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            names = axis if isinstance(axis, tuple) else (axis,)
            factor = int(np.prod([mesh.shape[a] for a in names]))
            shard[dim] //= factor
        itemsize = leaf.dtype.itemsize
        mb = np.prod(shard) * itemsize / 2**20
        rows.append((mb, name, leaf.shape, spec, tuple(shard), itemsize))

    rows.sort(key=lambda r: r[0], reverse=True)  # stable: ties keep layer order
    shown = rows[: args.top] if args.top else rows
    for mb, name, shape, spec, shard, _ in shown:
        print(f"{name:58s} {str(tuple(shape)):>20s} {str(tuple(spec)):>24s} "
              f"{str(shard):>20s} {mb:8.2f}")
    total = sum(r[0] for r in rows)
    full = sum(np.prod(r[2]) * r[5] / 2**20 for r in rows)
    print(f"-- params: {full:.0f} MB unsharded -> {total:.0f} MB/device "
          f"({len(rows)} tensors; optimizer state shards identically)")


if __name__ == "__main__":
    main()
