#!/usr/bin/env python
"""store_outage_drill — blackout the launcher KV store mid-run and
prove the fleet rides it out (docs/fault_tolerance.md degraded-mode
matrix; the store-resilience plane's acceptance drill).

Two arms, each printing one JSON report line (exit 0 = pass):

``--train`` (default): a 2-node elastic gang with the liveness plane
armed (``hang_timeout_s`` SHORTER than the outage) trains through a
seeded client-side store blackout — the ``store.get``/``store.set``/
``store.add`` fault points open a ``for=``-window at a mid-run step on
EVERY host at once, exactly the "all hosts stale simultaneously"
signature that used to read as a cluster hang. Acceptance:

- zero false hang blames: no ``sentinel``/``hang_blamed`` or
  ``cluster_dump`` events, every worker exits 0, the run completes;
- the journal carries the ``store`` arc: degraded (or down) →
  recovered, plus the liveness monitor's blame_suspended /
  blame_resumed bracket;
- step cadence stays within noise of a no-fault CONTROL run of the
  same shape (time-bounded heartbeats: dropped beats are counted,
  never waited on).

``--serve``: two advertised fake-backend replicas + the in-process
router stack (HealthProber refresh = ResilientStore.discover_replicas)
take a registry blackout: every ``store.get`` in the drill process
raises for the window while live traffic flows through the router
front. Acceptance: ZERO failed requests (the replica set serves from
the last-known-good cache, ``store_lkg_reads_total`` > 0) and the
health machine walks degraded → ok on recovery.

Usage::

    python tools/store_outage_drill.py [--train] [--seed 0]
        [--steps 16] [--outage 3.0] [--out DIR]
    python tools/store_outage_drill.py --serve [--outage 2.0]

Registered as slow tests (tests/test_zstore_plane.py) under
``PDTT_SANITIZE=1``; tier-1 stays fast.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import re
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))
if _TOOLS not in sys.path:
    sys.path.insert(1, _TOOLS)

_TRAIN_WORKER = """
import os, sys
sys.path.insert(0, {repo!r})
from pytorch_distributed_train_tpu.utils import syncdbg
syncdbg.maybe_activate()
import jax
jax.config.update("jax_platforms", "cpu")
from pytorch_distributed_train_tpu.config import TrainConfig
from pytorch_distributed_train_tpu.trainer import Trainer

rank = int(os.environ["PROCESS_ID"])
out = {out!r}
cfg = TrainConfig()
cfg.model.name = "resnet18"; cfg.model.num_classes = 10
cfg.model.image_size = 8
cfg.data.dataset = "synthetic_images"; cfg.data.synthetic_size = 48
cfg.data.batch_size = 12; cfg.data.num_workers = 1
cfg.optim.name = "momentum"; cfg.optim.learning_rate = 0.05
cfg.optim.schedule = "constant"; cfg.optim.warmup_steps = 0
cfg.total_steps = {steps}
cfg.checkpoint.dir = os.path.join(out, f"ckpt-{{rank}}")
cfg.checkpoint.save_every_steps = 0
cfg.obs.log_every_steps = 1
cfg.obs.jsonl_path = os.path.join(out, f"metrics-{{rank}}.jsonl")
# liveness armed TIGHTER than the outage: without blame suspension
# this gang would dump-and-die mid-blackout
cfg.sentinel.hang_timeout_s = {hang_timeout}
cfg.sentinel.hang_poll_s = 0.2
cfg.sentinel.heartbeat_every_steps = 1
# pace every step (control AND fault runs identically) so the run
# outlasts the blackout and the recovery arc lands IN-run: the monitor
# must re-arm blame and journal blame_resumed before fit ends
inject = ["step.straggle@step=2:count=1000:delay={pace}:gen=-1"]
if {outage_s} > 0:
    inject += [
        "store.get@step={outage_step}:for={outage_s}:gen=-1",
        "store.set@step={outage_step}:for={outage_s}:gen=-1",
        "store.add@step={outage_step}:for={outage_s}:gen=-1",
    ]
cfg.faults.inject = tuple(inject)
t = Trainer(cfg)
t.fit()
t.close()
"""


def _step_intervals(metrics_path: str) -> list[float]:
    """Wall-clock deltas between consecutive train rows (compile row
    excluded): the per-step cadence a blocked heartbeat would smear."""
    ts = []
    try:
        with open(metrics_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("tag") == "train" and rec.get("step", 0) >= 2:
                    ts.append(float(rec["ts"]))
    except OSError:
        return []
    return [b - a for a, b in zip(ts, ts[1:])]


def _mean(xs: list[float]) -> float | None:
    return sum(xs) / len(xs) if xs else None


def _run_gang(out_dir: str, steps: int, hang_timeout: float,
              outage_step: int, outage_s: float,
              pace: float = 0.35) -> dict[int, int]:
    """One 2-node elastic gang over the worker above; returns agent
    return codes by node rank."""
    import socket

    from pytorch_distributed_train_tpu.elastic import (
        ElasticAgent,
        LaunchConfig,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.makedirs(out_dir, exist_ok=True)
    script = os.path.join(out_dir, "worker.py")
    with open(script, "w") as f:
        f.write(_TRAIN_WORKER.format(
            repo=repo, out=out_dir, steps=steps,
            hang_timeout=hang_timeout, outage_step=outage_step,
            outage_s=outage_s, pace=pace))
    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    env = {"JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    if os.environ.get("PDTT_SANITIZE"):
        env["PDTT_SANITIZE"] = os.environ["PDTT_SANITIZE"]
    rcs: dict[int, int] = {}

    def agent(node_rank: int) -> None:
        cfg = LaunchConfig(
            nprocs=1, max_restarts=0, monitor_interval_s=0.1,
            nnodes=2, node_rank=node_rank, master_addr="127.0.0.1",
            store_port=port, rendezvous_window_s=2.0,
            backoff_base_s=0.05, backoff_max_s=0.1, env=env,
            events_dir=os.path.join(out_dir, "events"))
        rcs[node_rank] = ElasticAgent(
            cfg, [sys.executable, script]).run()

    threads = [threading.Thread(target=agent, args=(r,), daemon=True)
               for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    return rcs


def run_training_drill(seed: int = 0, steps: int = 18,
                       outage_s: float = 3.0, out_dir: str = "") -> dict:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from pytorch_distributed_train_tpu.obs.events import load_events

    out_dir = out_dir or tempfile.mkdtemp(prefix="store-outage-")
    rng = random.Random(seed)
    # early-ish outage: plenty of post-recovery steps for blame_resumed
    outage_step = rng.randrange(3, 6)
    hang_timeout = max(0.5, min(2.0, outage_s * 0.6))

    fault_dir = os.path.join(out_dir, "fault")
    control_dir = os.path.join(out_dir, "control")
    rcs = _run_gang(fault_dir, steps, hang_timeout, outage_step, outage_s)
    rcs_control = _run_gang(control_dir, steps, hang_timeout, 0, 0.0)

    steps_seen: list[int] = []
    try:
        with open(os.path.join(fault_dir, "metrics-0.jsonl")) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("tag") == "train":
                    steps_seen.append(int(rec["step"]))
    except OSError:
        pass
    completed = bool(steps_seen) and max(steps_seen, default=0) == steps

    events = load_events(os.path.join(fault_dir, "events"))
    sentinel_names = [e.get("name") for e in events
                      if e.get("category") == "sentinel"]
    store_names = [e.get("name") for e in events
                   if e.get("category") == "store"]
    false_blames = sum(1 for n in sentinel_names
                       if n in ("hang_blamed", "cluster_dump"))
    degraded = any(n in ("degraded", "down") for n in store_names)
    recovered = "recovered" in store_names
    suspended = "blame_suspended" in store_names
    resumed = "blame_resumed" in store_names

    mean_fault = _mean(_step_intervals(
        os.path.join(fault_dir, "metrics-0.jsonl")))
    mean_control = _mean(_step_intervals(
        os.path.join(control_dir, "metrics-0.jsonl")))
    # "within noise": bounded beats cost at most beat_timeout_s per
    # step; the bound guards the REAL regression (an unbounded publish
    # blocking a step for the store client's multi-second default
    # timeout), with generous headroom for loaded CI boxes
    cadence_ok = (mean_fault is not None and mean_control is not None
                  and mean_fault <= 3.0 * mean_control + 0.35)

    report = {
        "arm": "train", "seed": seed, "steps": steps,
        "outage_step": outage_step, "outage_s": outage_s,
        "hang_timeout_s": hang_timeout,
        "rcs": {str(k): v for k, v in sorted(rcs.items())},
        "rcs_control": {str(k): v for k, v in sorted(rcs_control.items())},
        "completed": completed, "false_hang_blames": false_blames,
        "store_degraded": degraded, "store_recovered": recovered,
        "blame_suspended": suspended, "blame_resumed": resumed,
        "mean_step_s_fault": mean_fault,
        "mean_step_s_control": mean_control,
        "cadence_ok": cadence_ok, "out_dir": out_dir,
    }
    report["ok"] = bool(
        rcs.get(0) == 0 and rcs.get(1) == 0
        and rcs_control.get(0) == 0 and rcs_control.get(1) == 0
        and completed and false_blames == 0
        and degraded and recovered and suspended and resumed
        and cadence_ok)
    return report


# ------------------------------------------------------------- serving arm
def _spawn_replica(out_dir: str, name: str, store_addr: str,
                   proc_id: int) -> tuple[subprocess.Popen, str]:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "TPUSTORE_ADDR": store_addr,
           "PROCESS_ID": str(proc_id), "NUM_PROCESSES": "4",
           "PDTT_EVENTS_DIR": os.path.join(out_dir, "events")}
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "tools", "serve_http.py"),
         "--fake-backend", "--port", "0", "--slots", "4",
         "--advertise", "--drain-grace", "5"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=repo)
    addr = None
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline:
        line = proc.stdout.readline() if proc.stdout else ""
        if not line:
            if proc.poll() is not None:
                break
            continue
        m = re.search(r"serving on http://127\.0\.0\.1:(\d+)", line)
        if m:
            addr = f"127.0.0.1:{m.group(1)}"
            break
    if addr is None:
        try:
            proc.kill()
        except OSError:
            pass
        raise RuntimeError(f"replica {name} never came up")

    def _pump():
        try:
            for _line in proc.stdout:
                pass
        except (OSError, ValueError):
            pass

    threading.Thread(target=_pump, daemon=True,
                     name=f"drill-pump-{name}").start()
    return proc, addr


def run_serving_drill(outage_s: float = 2.0, requests: int = 20,
                      out_dir: str = "") -> dict:
    from http.server import ThreadingHTTPServer

    import serve_router as serve_router_tool
    from pytorch_distributed_train_tpu import store_plane
    from pytorch_distributed_train_tpu.faults import registry as fregistry
    from pytorch_distributed_train_tpu.native.store import (
        StoreClient,
        StoreServer,
    )
    from pytorch_distributed_train_tpu.obs import events as events_lib
    from pytorch_distributed_train_tpu.obs.registry import get_registry
    from pytorch_distributed_train_tpu.serving_plane.router import (
        HealthProber,
        ReplicaSet,
        Router,
    )

    out_dir = out_dir or tempfile.mkdtemp(prefix="store-outage-serve-")
    events_lib.configure(os.path.join(out_dir, "events"), who="router")
    store_plane._reset_for_tests()
    procs = []
    front = None
    prober = None
    rs = None
    try:
        with StoreServer() as srv:
            store_addr = f"127.0.0.1:{srv.port}"
            for i, name in enumerate(("a", "b")):
                procs.append(_spawn_replica(out_dir, name, store_addr,
                                            i + 1))
            host, port_s = store_addr.split(":")
            rs = store_plane.ResilientStore(
                lambda: StoreClient(host, int(port_s)), name="router")
            # prime the last-known-good cache: discovery must have seen
            # both replicas BEFORE the blackout for the cache to serve
            deadline = time.monotonic() + 30.0
            found: list = []
            while time.monotonic() < deadline and len(found) < 2:
                try:
                    found = rs.discover_replicas()
                except OSError:
                    pass
                time.sleep(0.1)
            if len(found) < 2:
                raise RuntimeError("replicas never advertised")
            replicas = ReplicaSet(())
            prober = HealthProber(replicas, interval_s=0.2,
                                  refresh=rs.discover_replicas)
            prober.probe_once()
            router = Router(replicas, timeout_s=30.0)
            front = ThreadingHTTPServer(
                ("127.0.0.1", 0),
                serve_router_tool.make_handler(router, prober))
            threading.Thread(target=front.serve_forever,
                             daemon=True).start()
            prober.start()
            fport = front.server_address[1]

            # ---- blackout: every store.get in THIS process raises for
            # the window; the prober keeps refreshing from LKG cache
            lkg_before = get_registry().get_value(
                "store_lkg_reads_total", {"registry": "replicas"}) or 0.0
            # blackout BOTH discovery ops: the registry read leads with
            # add(COUNT, 0), so a get-only window would let the counter
            # read through and never trip the health machine's
            # consecutive-failure gate
            fregistry.configure(
                (f"store.add@call=1:for={outage_s}:gen=-1",
                 f"store.get@call=1:for={outage_s}:gen=-1"))
            t0 = time.monotonic()
            ok_n, fail_n = 0, 0
            while time.monotonic() - t0 < outage_s:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{fport}/v1/completions",
                    data=json.dumps({"prompt": "through the blackout",
                                     "max_tokens": 4}).encode(),
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=30) as r:
                        ok_n += 1 if r.status == 200 else 0
                        fail_n += 0 if r.status == 200 else 1
                        r.read()
                except Exception:
                    fail_n += 1
                if ok_n + fail_n >= requests:
                    break
                time.sleep(max(0.0, outage_s / max(1, requests) / 2))
            mid_state = store_plane.health_snapshot().get("state")
            lkg_after = get_registry().get_value(
                "store_lkg_reads_total", {"registry": "replicas"}) or 0.0

            # ---- recovery: wait out the window, then a refresh must
            # succeed and walk the health machine back to ok
            deadline = time.monotonic() + max(10.0, outage_s + 10.0)
            state = mid_state
            while time.monotonic() < deadline and state != "ok":
                try:
                    rs.discover_replicas()
                except OSError:
                    pass
                state = store_plane.health_snapshot().get("state")
                time.sleep(0.1)
            report = {
                "arm": "serve", "outage_s": outage_s,
                "requests_ok": ok_n, "requests_failed": fail_n,
                "lkg_reads": lkg_after - lkg_before,
                "state_during_outage": mid_state,
                "state_after": state, "out_dir": out_dir,
            }
            report["ok"] = bool(
                ok_n > 0 and fail_n == 0
                and lkg_after > lkg_before
                and mid_state in ("degraded", "down")
                and state == "ok")
            return report
    finally:
        fregistry.configure(())
        if prober is not None:
            prober.stop()
        if front is not None:
            front.shutdown()
            front.server_close()
        if rs is not None:
            rs.close()
        for proc, _addr in procs:
            try:
                proc.terminate()
                proc.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired):
                try:
                    proc.kill()
                except OSError:
                    pass


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--train", action="store_true",
                   help="training blackout arm (the default)")
    p.add_argument("--serve", action="store_true",
                   help="serving registry-blackout arm instead")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--steps", type=int, default=18)
    p.add_argument("--outage", type=float, default=0.0,
                   help="blackout seconds (default 3.0 train / "
                        "2.0 serve)")
    p.add_argument("--out", default="", help="run dir (default: tempdir)")
    p.add_argument("--sanitize", action="store_true",
                   help="run under the tsan-lite concurrency sanitizer "
                        "(PDTT_SANITIZE=1 inherited by workers)")
    args = p.parse_args(argv)
    if args.sanitize:
        os.environ["PDTT_SANITIZE"] = "1"
    from pytorch_distributed_train_tpu.utils import syncdbg

    syncdbg.maybe_activate()
    if args.serve:
        report = run_serving_drill(outage_s=args.outage or 2.0,
                                   out_dir=args.out)
    else:
        report = run_training_drill(seed=args.seed, steps=args.steps,
                                    outage_s=args.outage or 3.0,
                                    out_dir=args.out)
    if syncdbg.active():
        syncdbg.check_teardown()
        summary = syncdbg.findings_summary()
        report["sanitizer_findings"] = summary
        if summary:
            for f in syncdbg.findings():
                print(f"FAIL: sanitizer {f.kind}: {f.message}",
                      file=sys.stderr)
            report["ok"] = False
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
