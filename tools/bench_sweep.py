#!/usr/bin/env python
"""Run the full queued benchmark battery and write one JSON report.

The moment the device lease recovers, every measurement docs/ROADMAP.md
has been queuing runs with ONE command:

    python tools/bench_sweep.py                 # full battery
    python tools/bench_sweep.py --only serve    # name-substring filter
    python tools/bench_sweep.py --dry-run       # print commands only

Each arm is `bench.py` in a subprocess (its own watchdog + structured
tpu_unavailable record apply); failures are recorded and the sweep
continues. Results land in BENCH_SWEEP.json: {name: {cmd, rc, parsed,
seconds}} — parsed is bench.py's JSON line when one was emitted.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The ROADMAP battery. Names are stable keys for --only and the report.
ARMS: list[tuple[str, list[str]]] = [
    ("resnet50_baseline", []),
    ("resnet50_s2d_stem", ["--stem", "space_to_depth"]),
    ("vit_b16", ["--model", "vit_b16"]),
    # ViT batch-scaling probe (MFU chase, VERDICT r2 weak #2): at seq 197
    # the attention backends are equivalent (chunked tiles start at 256 —
    # a chunked "A/B" would measure dense vs dense), so the lever to probe
    # is per-chip batch: 742 img/s at bs128 leaves the MXU underfed if
    # step time is launch/HBM-bound rather than FLOPs-bound.
    ("vit_b16_bs256", ["--model", "vit_b16", "--batch-per-chip", "256"]),
    ("bert_base_mlm", ["--model", "bert_base"]),
    ("llama_train_best", ["--model", "llama", "--fused-head",
                          "--optimizer", "adafactor"]),
    ("llama_quant_training_int8", ["--model", "llama",
                                   "--quant-training", "int8"]),
    ("t5_train", ["--model", "t5"]),
    ("llama_decode", ["--model", "llama", "--decode-tokens", "64"]),
    ("llama_decode_int8", ["--model", "llama", "--decode-tokens", "64",
                           "--quantize", "int8"]),
    ("llama_decode_int4", ["--model", "llama", "--decode-tokens", "64",
                           "--quantize", "int4"]),
    ("llama_decode_fp8kv", ["--model", "llama", "--decode-tokens", "64",
                            "--kv-cache-dtype", "float8_e4m3fn"]),
    ("llama_spec_floor", ["--model", "llama", "--speculative", "4"]),
    ("llama_spec_ceiling", ["--model", "llama", "--speculative", "4",
                            "--spec-self"]),
    ("llama_spec_plookup", ["--model", "llama", "--speculative", "4",
                            "--prompt-lookup", "3"]),
    ("llama_spec_plookup_periodic", ["--model", "llama", "--speculative",
                                     "4", "--prompt-lookup", "3",
                                     "--plookup-periodic"]),
    ("serve_mixed", ["--model", "llama", "--serve", "64"]),
    ("serve_mixed_spec", ["--model", "llama", "--serve", "64",
                          "--serve-spec", "4"]),
    ("serve_mixed_paged", ["--model", "llama", "--serve", "64",
                           "--serve-paged", "128"]),
    ("serve_chat_sessions", ["--model", "llama", "--serve", "32",
                             "--serve-turns", "4"]),
    ("serve_chat_resend", ["--model", "llama", "--serve", "32",
                           "--serve-turns", "4", "--serve-resend"]),
    ("serve_prefix_fork", ["--model", "llama", "--serve", "32",
                           "--serve-prefix", "1024"]),
    ("serve_prefix_resend", ["--model", "llama", "--serve", "32",
                             "--serve-prefix", "1024", "--serve-resend"]),
    ("host_pipeline_decode_native", ["--model", "pipeline",
                                     "--pipeline-decode",
                                     "--decoder", "native"]),
    # C17 multiprocess-loader arms (grain): first measured 2026-07-31 on
    # the 1-core sandbox (in-process mode); on real multi-core TPU hosts
    # these record the process-worker numbers the torch comparison wants.
    ("host_pipeline_decode_grain_native", ["--model", "pipeline",
                                           "--pipeline-decode",
                                           "--loader", "grain",
                                           "--decoder", "native"]),
    ("host_pipeline_decode_grain_pil", ["--model", "pipeline",
                                        "--pipeline-decode",
                                        "--loader", "grain",
                                        "--decoder", "pil"]),
]

# Arms that are NOT bench.py invocations. The sustained drill (VERDICT r2
# #5 / BASELINE.json:8) runs the real trainer on a synthesized multi-GB
# tar set for wall-clock minutes — only worth the time on a healthy chip,
# so it joins the sweep behind the same probe gate.
EXTRA_ARMS: list[tuple[str, list[str]]] = [
    ("sustained_resnet50_10min",
     [sys.executable, os.path.join(REPO, "tools", "sustained_drill.py"),
      "--minutes", "10"]),
    # VERDICT r3 #4: Mosaic compile probe (hard-timeout subprocess) →
    # MOSAIC_PROBE.json record consumed by attention's auto gating, plus
    # the flash-vs-chunked A/B when the tunnel can actually compile.
    ("mosaic_probe",
     [sys.executable, os.path.join(REPO, "tools", "mosaic_probe.py")]),
    # VERDICT r3 #6: execute 7B per-layer geometry at 2 depths; slope
    # replaces MEMFIT_7B.md's extrapolated temps with measured ones.
    ("llama7b_geometry_step",
     [sys.executable, os.path.join(REPO, "tools", "probe_7b_step.py")]),
    # VERDICT r3 #3: profiler-backed limiter breakdown for the weakest
    # MFU rows — XPlane per-class % + top ops on the default shapes.
    ("resnet50_profile_toptops",
     [sys.executable, os.path.join(REPO, "tools", "profile_toptops.py"),
      "--model", "resnet50"]),
    ("vit_b16_profile_toptops",
     [sys.executable, os.path.join(REPO, "tools", "profile_toptops.py"),
      "--model", "vit_b16"]),
]


def run_arm(name: str, extra: list[str], timeout_s: int,
            tiny: bool) -> dict:
    cmd = [sys.executable, os.path.join(REPO, "bench.py"), *extra]
    if tiny:
        cmd.append("--tiny")
    return run_cmd(cmd, timeout_s)


def run_cmd(cmd: list[str], timeout_s: int) -> dict:
    # The child's bring-up watchdog must fire BEFORE our subprocess
    # timeout, or a hang-mode wedged lease dies as a structureless
    # rc=124 instead of bench.py's tpu_unavailable record — and the
    # sweep's early-abort (which keys on that record) never triggers.
    env = {**os.environ,
           "BENCH_TIMEOUT_S": str(max(timeout_s - 120, 60))}
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, cwd=REPO, env=env)
        rc, out = proc.returncode, proc.stdout
        tail = (proc.stderr or "")[-800:]
    except subprocess.TimeoutExpired as e:
        rc, out = 124, (e.stdout or "")
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        tail = "sweep-level timeout"
    parsed = None
    for line in reversed((out or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    return {"cmd": " ".join(cmd), "rc": rc, "parsed": parsed,
            "seconds": round(time.time() - t0, 1),
            **({} if rc == 0 else {"stderr_tail": tail})}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--only", default="",
                   help="run arms whose name contains this substring")
    p.add_argument("--timeout", type=int, default=1200,
                   help="per-arm wall clock budget (seconds)")
    p.add_argument("--tiny", action="store_true",
                   help="CI smoke: pass --tiny to the arms that take it "
                        "(numbers are NOT comparable to real runs)")
    p.add_argument("--out", default=os.path.join(REPO, "BENCH_SWEEP.json"))
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)

    arms = [(n, a) for n, a in ARMS if args.only in n]
    extra_arms = [] if args.tiny else [
        (n, c) for n, c in EXTRA_ARMS if args.only in n]
    if args.tiny:
        # --tiny exists on the llama decode/spec/serve benches only
        arms = [(n, a) for n, a in arms
                if any(k in n for k in ("decode", "spec", "serve"))
                and "host" not in n]
    if not arms and not extra_arms:
        print(f"no arms match --only {args.only!r}", file=sys.stderr)
        return 2
    if args.dry_run:
        for name, extra in arms:
            print(f"{name}: python bench.py {' '.join(extra)}"
                  f"{' --tiny' if args.tiny else ''}")
        for name, cmd in extra_arms:
            print(f"{name}: {' '.join(cmd[1:] if cmd[0] == sys.executable else cmd)}")
        return 0

    report: dict[str, dict] = {}

    def record(name: str, r: dict) -> None:
        report[name] = r
        status = (r["parsed"]["metric"] + "=" + str(r["parsed"]["value"])
                  if r["parsed"] and r["parsed"].get("metric")
                  else f"rc={r['rc']}")
        print(f"    {status} ({r['seconds']}s)", flush=True)
        with open(args.out, "w") as f:  # persist incrementally
            json.dump(report, f, indent=1)

    for i, (name, extra) in enumerate(arms, 1):
        print(f"[{i}/{len(arms)}] {name} ...", flush=True)
        record(name, run_arm(name, extra, args.timeout, args.tiny))
        r = report[name]
        if (r["parsed"] and r["parsed"].get("error") == "tpu_unavailable"
                ) or r["rc"] == 124:
            print("device lease unavailable (or arm hang) — aborting "
                  "the sweep (every further arm would fail the same "
                  "way)", file=sys.stderr)
            return 3
    # Non-bench arms (sustained drill): long-horizon — run only when every
    # quick arm passed (a sweep with failures shouldn't burn 10+ minutes
    # of lease on the drill); --only can still target them directly.
    quick_ok = all(r["rc"] == 0 for r in report.values())
    if extra_arms and (quick_ok or not arms):
        for name, cmd in extra_arms:
            print(f"[extra] {name} ...", flush=True)
            record(name, run_cmd(cmd, timeout_s=max(args.timeout, 2400)))
    elif extra_arms:
        print("skipping extra arms (quick arms had failures)",
              file=sys.stderr)
    ok = sum(1 for r in report.values() if r["rc"] == 0)
    print(f"done: {ok}/{len(report)} arms ok → {args.out}")
    return 0 if ok == len(report) else 1


if __name__ == "__main__":
    sys.exit(main())
