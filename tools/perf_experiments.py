#!/usr/bin/env python
"""A/B perf experiments for the ResNet-50 north-star (run on a real chip).

Each experiment toggles ONE hypothesis against the current default and
prints a JSON line per arm. Run when the device is healthy:

    python tools/perf_experiments.py --steps 20

Arms:
  baseline     — current defaults (bf16 compute, fp32 BN stats, fp32 input)
  bf16_input   — feed images as bf16 from the host (halves input H2D/read)
  bf16_bnstats — BN statistics reductions in bf16
                 (force_float32_reductions=False; MLPerf-era ResNets did
                 this — validate loss parity before adopting)
  s2d_stem     — space-to-depth stem rewrite (exact; MXU-friendly C_in 12)

Keep arms additive and honest: any adopted change must land in the model
code with its measured delta recorded in BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def run_arm(name: str, *, steps: int, warmup: int, bn_fp32_stats: bool,
            input_dtype: str, stem: str = "conv", image_size: int = 224,
            bs: int = 128) -> dict:
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.config import (
        MeshConfig, ModelConfig, OptimConfig, PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.losses import get_loss_fn
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.optim import make_optimizer
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh
    from pytorch_distributed_train_tpu.parallel.partition import rules_for_model
    from pytorch_distributed_train_tpu.train_state import TrainState

    mesh = build_mesh(MeshConfig(data=-1))
    model = build_model(ModelConfig(name="resnet50", num_classes=1000,
                                    image_size=image_size, stem=stem),
                        PrecisionConfig(compute_dtype="bfloat16"))
    tx, _ = make_optimizer(OptimConfig(name="momentum", learning_rate=0.1,
                                       schedule="constant", warmup_steps=0),
                           total_steps=1000)
    rules = rules_for_model("resnet50")

    orig_bn = nn.BatchNorm
    if not bn_fp32_stats:
        # Swap in a subclass with the default flipped. A plain class-attr
        # assignment would be a silent no-op: flax Modules are dataclasses,
        # so the default is baked into the generated __init__. resnet.py
        # resolves `nn.BatchNorm` at call time through the module attr, so
        # the swap takes effect for models built inside this arm.
        class _BF16StatsBN(nn.BatchNorm):
            force_float32_reductions: bool = False

        nn.BatchNorm = _BF16StatsBN
    try:
        def init_state(rng):
            variables = model.init({"params": rng},
                                   jnp.zeros((2, image_size, image_size, 3)),
                                   train=False)
            return TrainState.create(params=variables["params"], tx=tx,
                                     batch_stats=variables["batch_stats"])

        rng = jax.random.PRNGKey(0)
        shape = jax.eval_shape(init_state, rng)
        sharding = steps_lib.state_shardings(mesh, rules, shape)
        state = jax.jit(init_state, out_shardings=sharding)(rng)
        step = steps_lib.jit_train_step(
            steps_lib.make_train_step(model, get_loss_fn("softmax_xent"), tx),
            mesh, sharding)

        rng_np = np.random.default_rng(0)
        batch = {
            "image": jnp.asarray(
                rng_np.standard_normal((bs, image_size, image_size, 3)),
                                 jnp.dtype(input_dtype)),
            "label": jnp.asarray(rng_np.integers(0, 1000, bs), jnp.int32),
        }
        for _ in range(max(warmup, 1)):  # >=1: timing must exclude compile
            state, metrics = step(state, batch, rng)
        float(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch, rng)
        loss = float(metrics["loss"])
        wall = time.perf_counter() - t0
        return {"arm": name, "images_per_sec": round(bs * steps / wall, 1),
                "loss": round(loss, 4)}
    finally:
        nn.BatchNorm = orig_bn


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--arms", default="baseline,bf16_input,bf16_bnstats,s2d_stem")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--batch", type=int, default=128)
    args = p.parse_args()

    specs = {
        "baseline": dict(bn_fp32_stats=True, input_dtype="float32"),
        "bf16_input": dict(bn_fp32_stats=True, input_dtype="bfloat16"),
        "bf16_bnstats": dict(bn_fp32_stats=False, input_dtype="float32"),
        # exact 4x4/s1 rewrite of the 7x7/s2 stem over s2d input
        # (models/resnet.py SpaceToDepthStem)
        "s2d_stem": dict(bn_fp32_stats=True, input_dtype="float32",
                         stem="space_to_depth"),
    }
    for arm in args.arms.split(","):
        out = run_arm(arm, steps=args.steps, warmup=args.warmup,
                      image_size=args.image_size, bs=args.batch, **specs[arm])
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
