#!/usr/bin/env python
"""Incident postmortem: one alert's whole story as one report.

    python tools/postmortem.py --run-dir RUN --alert <id or prefix>
    python tools/postmortem.py --run-dir RUN --from -30m --to -10m
    python tools/postmortem.py --run-dir RUN --alert <id> --out pm.txt

PR 13 built the alert→capture→resolve chain; this tool reconstructs
it AFTER the fact into a single artifact, joining every plane the
incident touched:

- the **alert lifecycle** — the journal's fired / profile_requested /
  resolved records threaded by the alert id the engine mints at FIRE
  (``rule@host@epoch_ms``; any unique prefix selects it);
- **before / during / after series** from the durable history store
  (obs/tsdb.py): the rule's own series plus the core trajectories of
  the offending target, each phase with stats and the whole padded
  window as a sparkline — the shape of the incident, not just its
  peak;
- the **event journal slice** for the window (per-category counts +
  the notable landmarks);
- **retained traces** finished inside the window (obs/tracing.py) and
  **profiler captures** it requested (capture dirs touched in the
  window);
- the **SLO budget impact**: remaining error budget at window start
  vs end per applicable objective (obs/slo_budget.py).

Sections are independent — a run missing a plane (no traces, no
store) degrades that section to one line, never the report. Pure
stdlib + the repo's obs package; no jax (login-host safe).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import fleet_console  # noqa: E402  (parse_since/parse_duration/sparkline)

from pytorch_distributed_train_tpu.obs.events import load_events  # noqa: E402

# series rendered for the offending target beyond the rule's own
CORE_SERIES = ("ttft_p95_s", "shed_per_s", "steps_per_s",
               "goodput_pct")

# model-health incidents (obs/model_health.py) read as a PAIR of
# training-dynamics series — a grad-norm spike without the update
# ratio (or a KL runaway without the reward level) is half the story,
# so each rule pulls its companion series into the window render
MODEL_HEALTH_SERIES = {
    "grad_norm_spike": ("grad_norm", "update_ratio", "loss"),
    "reward_collapse": ("reward_mean", "kl_behavior"),
    "kl_runaway": ("kl_behavior", "reward_mean"),
}


def find_alert(events: list[dict], alert_id: str) -> dict | None:
    """The incident's fired/resolved/profile records by id (exact, or
    a prefix/substring that selects exactly one fired record)."""
    recs = [e for e in events if e.get("category") == "alert"
            and (e.get("detail") or {}).get("id")]
    fired = [e for e in recs if e.get("name") == "fired"]
    exact = [e for e in fired
             if (e.get("detail") or {}).get("id") == alert_id]
    hits = exact or [e for e in fired
                     if alert_id in (e.get("detail") or {}).get("id", "")]
    if len(hits) != 1:
        return None if not hits else {"ambiguous": [
            (e.get("detail") or {}).get("id") for e in hits]}
    aid = (hits[0].get("detail") or {}).get("id")
    chain = [e for e in recs if (e.get("detail") or {}).get("id") == aid]
    return {"id": aid, "fired": hits[0],
            "resolved": next((e for e in chain
                              if e.get("name") == "resolved"), None),
            "chain": sorted(chain, key=lambda e: e.get("ts", 0.0))}


def _phase_stats(pts: list[tuple]) -> str:
    if not pts:
        return "n=0"
    vals = [v for _ts, v in pts]
    return (f"n={len(vals)} mean={sum(vals) / len(vals):.4g} "
            f"max={max(vals):.4g}")


def series_section(store, target_key: str, series_names,
                   start: float, end: float, pad: float) -> list[str]:
    if store is None:
        return ["series: no history store (run without --history-dir "
                "collector?)"]
    out = [f"series for {target_key} "
           f"(before {pad:.0f}s | during {end - start:.0f}s | "
           f"after {pad:.0f}s):"]
    shown = 0
    for name in series_names:
        try:
            before = store.query(target_key, name, start - pad, start)
            during = store.query(target_key, name, start, end)
            after = store.query(target_key, name, end, end + pad)
        except Exception:
            continue
        if not (before or during or after):
            continue
        shown += 1
        allpts = before + during + after
        out.append(f"  {name}:")
        out.append(f"    before  {_phase_stats(before)}")
        out.append(f"    during  {_phase_stats(during)}")
        out.append(f"    after   {_phase_stats(after)}")
        out.append("    shape   "
                   + fleet_console.sparkline(
                       [v for _ts, v in allpts], width=48))
    if not shown:
        out.append("  (store holds no samples for this target/window)")
    return out


def lifecycle_section(incident: dict) -> list[str]:
    out = ["alert lifecycle:"]
    for e in incident["chain"]:
        d = e.get("detail") or {}
        ts = time.strftime("%H:%M:%S", time.localtime(e.get("ts", 0.0)))
        extra = " ".join(f"{k}={d[k]}" for k in
                         ("value", "baseline", "after_s", "status")
                         if k in d)
        out.append(f"  {ts} {e.get('name'):<18} rule={d.get('rule')} "
                   f"host={d.get('host')} {extra}".rstrip())
    if incident["resolved"] is None:
        out.append("  (never resolved inside the journal)")
    return out


def journal_section(events: list[dict], start: float, end: float,
                    pad: float, limit: int = 20) -> list[str]:
    window = [e for e in events
              if start - pad <= e.get("ts", 0.0) <= end + pad]
    if not window:
        return ["journal: no events inside the window"]
    by_key: dict[str, int] = {}
    for e in window:
        k = f"{e.get('category')}.{e.get('name')}"
        by_key[k] = by_key.get(k, 0) + 1
    out = [f"journal slice ({len(window)} events): "
           + "  ".join(f"{k}={n}" for k, n in sorted(
               by_key.items(), key=lambda kv: -kv[1])[:8])]
    notable = [e for e in window if e.get("category") in
               ("alert", "elastic", "sentinel", "profile", "serve")]
    for e in notable[:limit]:
        ts = time.strftime("%H:%M:%S", time.localtime(e.get("ts", 0.0)))
        out.append(f"  {ts} [{e.get('host')}] {e.get('category')}."
                   f"{e.get('name')}")
    if len(notable) > limit:
        out.append(f"  ... {len(notable) - limit} more")
    return out


def traces_section(traces_dir: str, start: float, end: float,
                   pad: float, top: int = 5) -> list[str]:
    if not traces_dir or not os.path.isdir(traces_dir):
        return ["traces: no retained-traces directory"]
    from pytorch_distributed_train_tpu.obs.tracing import load_traces

    trees = [t for t in load_traces(traces_dir)
             if start - pad <= t.get("ts", 0.0) <= end + pad]
    if not trees:
        return ["traces: none retained inside the window"]
    out = [f"retained traces in window ({len(trees)}):"]
    for t in sorted(trees, key=lambda t: -(t.get("dur_ms") or 0.0))[:top]:
        out.append(f"  {str(t.get('trace_id'))[:16]}.. "
                   f"{t.get('dur_ms', 0.0):>9.1f}ms "
                   f"[{t.get('reason')}; {t.get('host')}]")
    return out


def captures_section(profiles_dir: str, start: float, end: float,
                     pad: float) -> list[str]:
    if not profiles_dir or not os.path.isdir(profiles_dir):
        return ["captures: no profiler directory"]
    hits = []
    for name in sorted(os.listdir(profiles_dir)):
        path = os.path.join(profiles_dir, name)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        if start - pad <= mtime <= end + pad:
            hits.append((mtime, name))
    if not hits:
        return ["captures: none taken inside the window"]
    out = [f"profiler captures in window ({len(hits)}):"]
    for mtime, name in hits:
        ts = time.strftime("%H:%M:%S", time.localtime(mtime))
        out.append(f"  {ts} {name}")
    return out


def budget_section(store, target_key: str, role: str, start: float,
                   end: float) -> list[str]:
    if store is None:
        return []
    from pytorch_distributed_train_tpu.obs.slo_budget import (
        SLO_CATALOG,
        SLOBudgetTracker,
    )

    tracker = SLOBudgetTracker(store)
    out = ["SLO budget impact (remaining, window start -> end):"]
    shown = 0
    for name, slo in sorted(SLO_CATALOG.items()):
        if role not in slo.roles:
            continue
        b0 = tracker.budget_remaining(name, target_key, now=start)
        b1 = tracker.budget_remaining(name, target_key, now=end)
        if b0 is None and b1 is None:
            continue
        shown += 1
        fmt = lambda b: "-" if b is None else f"{b:+.2f}"  # noqa: E731
        out.append(f"  {name:<22} {fmt(b0)} -> {fmt(b1)}"
                   + ("  OVERSPENT" if (b1 or 0) < 0 else ""))
    return out if shown else []


def report(run_dir: str, *, alert_id: str = "", t_from: str = "",
           t_to: str = "", events_dir: str = "", history_dir: str = "",
           traces_dir: str = "", profiles_dir: str = "",
           pad_s: float = 60.0) -> tuple[str, int]:
    """(report text, exit code). Sections degrade independently."""
    events_dir = events_dir or os.path.join(run_dir, "events")
    history_dir = history_dir or os.path.join(run_dir, "tsdb")
    traces_dir = traces_dir or os.path.join(run_dir, "traces")
    profiles_dir = profiles_dir or os.path.join(run_dir, "profiles")
    events = load_events(events_dir) if os.path.isdir(events_dir) else []

    incident = None
    if alert_id:
        incident = find_alert(events, alert_id)
        if incident is None:
            return (f"postmortem: no alert matching {alert_id!r} in "
                    f"{events_dir}", 2)
        if "ambiguous" in incident:
            return ("postmortem: ambiguous alert id, candidates:\n  "
                    + "\n  ".join(incident["ambiguous"]), 2)
        start = incident["fired"].get("ts", 0.0)
        end = (incident["resolved"].get("ts", start)
               if incident["resolved"] else
               max((e.get("ts", start) for e in events), default=start))
        d = incident["fired"].get("detail") or {}
        rule, host = d.get("rule", "?"), d.get("host", "?")
        role = d.get("role", "?")
        target_key = f"{role}@{host}"
        title = (f"incident {incident['id']} — {rule} on {host} "
                 f"({end - start:.1f}s)")
    else:
        if not t_from:
            return ("postmortem: need --alert or --from", 2)
        start = fleet_console.parse_since(t_from)
        end = (fleet_console.parse_since(t_to) if t_to
               else start + 900.0)
        rule, host, role, target_key = "?", "?", "?", ""
        title = (f"window {time.strftime('%H:%M:%S', time.localtime(start))}"
                 f" -> {time.strftime('%H:%M:%S', time.localtime(end))}")

    store = None
    if os.path.isdir(history_dir):
        try:
            from pytorch_distributed_train_tpu.obs.tsdb import (
                TimeSeriesStore,
            )

            store = TimeSeriesStore(history_dir)
        except Exception:
            store = None

    pad = max(pad_s, end - start)
    lines = [f"== postmortem: {title} =="]
    rule_series = ()
    if incident is not None:
        try:
            from pytorch_distributed_train_tpu.obs.alerts import RULES

            if rule in RULES:
                rule_series = (RULES[rule].series,)
        except Exception:
            rule_series = ()
    series_names = list(dict.fromkeys(
        (*rule_series, *MODEL_HEALTH_SERIES.get(rule, ()),
         *CORE_SERIES)))

    def targets_to_show():
        if target_key:
            return [target_key]
        return store.targets() if store is not None else []

    sections = []
    if incident is not None:
        sections.append(lambda: lifecycle_section(incident))
    if store is None:
        sections.append(lambda: ["series: no history store at "
                                 f"{history_dir}"])
    else:
        for tk in targets_to_show():
            sections.append(
                lambda tk=tk: series_section(
                    store, tk, series_names, start, end, pad))
    sections.append(lambda: journal_section(events, start, end, pad))
    sections.append(lambda: traces_section(traces_dir, start, end, pad))
    sections.append(lambda: captures_section(
        profiles_dir, start, end, pad))
    if target_key and role != "?":
        sections.append(lambda: budget_section(
            store, target_key, role, start, end))
    for build in sections:
        try:
            section = build()
        except Exception as e:
            section = [f"(section unrenderable: "
                       f"{type(e).__name__}: {e})"]
        if not section:
            continue
        lines.append("")
        lines.extend(section)
    return "\n".join(lines), 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--run-dir", default="",
                   help="run directory (events/, tsdb/, traces/, "
                        "profiles/)")
    p.add_argument("--alert", default="",
                   help="alert id (or unique prefix) from the journal "
                        "/ console firing list")
    p.add_argument("--from", dest="t_from", default="",
                   help="window start (epoch, ISO, or -30m style) "
                        "when no --alert")
    p.add_argument("--to", dest="t_to", default="",
                   help="window end (default start+15m)")
    p.add_argument("--events", default="", help="explicit events dir")
    p.add_argument("--history-dir", default="",
                   help="explicit tsdb store dir")
    p.add_argument("--traces", default="", help="explicit traces dir")
    p.add_argument("--profiles", default="",
                   help="explicit profiler captures dir")
    p.add_argument("--pad", type=float, default=60.0,
                   help="seconds of before/after context")
    p.add_argument("--out", default="",
                   help="also write the report to this file")
    args = p.parse_args(argv)
    if not (args.run_dir or args.events):
        print("postmortem: need --run-dir (or explicit --events/"
              "--history-dir)", file=sys.stderr)
        return 2
    text, rc = report(
        args.run_dir, alert_id=args.alert, t_from=args.t_from,
        t_to=args.t_to, events_dir=args.events,
        history_dir=args.history_dir, traces_dir=args.traces,
        profiles_dir=args.profiles, pad_s=args.pad)
    print(text)
    if args.out and rc == 0:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    return rc


if __name__ == "__main__":
    sys.exit(main())
