#!/usr/bin/env python
"""Compiler-side A/B evidence without a device lease (VERDICT r4 #6).

Four rounds of wedged TPU lease showed the round's perf story cannot
hinge on one flaky tunnel. This tool grounds the queued A/B arms in the
COMPILER'S OWN ACCOUNTING instead: ``jit(...).lower().compile()`` runs
the full XLA pipeline (SPMD partitioner, fusion, buffer assignment)
without touching a device, and ``compiled.cost_analysis()`` /
``memory_analysis()`` report FLOPs, bytes accessed, and temp sizes.
These are a COMPILER MODEL, not a measurement — rows are labeled so —
but ratios between two arms of an A/B (same compiler, same shapes) are
exactly the quantity the queued hardware runs would estimate.

Strategy per the verdict: a deviceless TPU-topology AOT
(`jax.experimental.topologies`) — which the sandbox's LOCAL libtpu
turns out to serve (round-5 discovery: only execution needs the
tunnel), so the arms compile with the real v5e cost model and the
real 15.75G HBM budget enforced at buffer assignment; if the topology
probe ever fails, a structured record lands in the output and the
arms fall back to XLA:CPU (the memfit_7b.py-validated fallback).

Arms (mirroring BASELINE.md's pending list + the ISSUE 14 compute arms):
  stem     — ResNet-50 train step: conv 7x7/s2 stem vs space_to_depth
  attn     — llama train step: attention_impl xla vs chunked
  quant    — llama decode step: int8 vs int4 weight-only params (bytes)
  epilogue — train-step optimizer epilogue: optax chain + gate select
             vs the one-pass fused epilogue (ops/fused_update.py) —
             bytes-accessed is the decision metric
  overlap  — shard_map DP train step: monolithic post-backward pmean
             vs per-bucket in-scan pmeans (collective count + bytes)

Run:  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      python tools/aot_ab.py [--arms stem attn quant epilogue overlap] \
      [--small]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_train_tpu.utils.deviceless import (  # noqa: E402
    scrub_axon_identity,
)

scrub_axon_identity()


def _probe_tpu_topology():
    """Can this sandbox compile deviceless against a TPU topology?
    Returns (record, topology-or-None) — the record lands in the output
    either way (VERDICT asked for the failure to be recorded, not
    silently swallowed). Round-5 discovery: the local libtpu DOES serve
    deviceless v5e AOT (the wedged lease only blocks execution), so the
    arms below compile with the real TPU cost model, Mosaic included."""
    try:
        from jax.experimental import topologies

        topo = topologies.get_topology_desc(
            topology_name="v5e:2x2x1", platform="tpu")
        return {"available": True,
                "topology": "v5e:2x2x1",
                "devices": len(topo.devices)}, topo
    except Exception as e:  # noqa: BLE001 — any failure = unavailable
        return {"available": False,
                "error": f"{type(e).__name__}: {str(e)[:200]}"}, None


def _guarded(fn, *a, **kw) -> dict:
    """Per-arm fault isolation. A v5e RESOURCE_EXHAUSTED at buffer
    assignment is EVIDENCE, not a tool failure — the TPU AOT pipeline
    enforces the real 15.75G HBM budget (discovered on the full-shape
    llama/adamw arm), so 'this config does not fit a single v5e' comes
    straight from the compiler and is recorded as such."""
    import re

    try:
        return fn(*a, **kw)
    except Exception as e:  # noqa: BLE001
        msg = str(e)
        m = re.search(r"Used ([\d.]+[GMK]) of ([\d.]+[GMK]) hbm", msg)
        rec = {"ok": False,
               "error": f"{type(e).__name__}: {msg[:250]}"}
        if m:
            rec["oom"] = {"needs": m.group(1), "hbm": m.group(2)}
        return rec


def _analyze(compiled) -> dict:
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    ma = compiled.memory_analysis()
    out = {
        "gflops": round(float(ca.get("flops", 0.0)) / 1e9, 3),
        "gbytes_accessed": round(
            float(ca.get("bytes accessed", 0.0)) / 1e9, 3),
        "temp_mib": round(
            getattr(ma, "temp_size_in_bytes", 0) / 2**20, 1),
        "arg_mib": round(
            getattr(ma, "argument_size_in_bytes", 0) / 2**20, 1),
    }
    return out


def _attach(tree, sh):
    """Pin every ShapeDtypeStruct leaf to ``sh`` (the AOT target device);
    None = current-backend default (CPU fallback)."""
    import jax

    if sh is None:
        return tree
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        tree)


def _compile_train(model_cfg, loss_name: str, batch_n: int,
                   seq_or_img, sh=None) -> dict:
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.config import (
        OptimConfig,
        PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.losses import get_loss_fn
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.optim import make_optimizer
    from pytorch_distributed_train_tpu.train_state import TrainState

    precision = PrecisionConfig(compute_dtype="bfloat16")
    model = build_model(model_cfg, precision)
    tx, _ = make_optimizer(
        OptimConfig(name="adamw", learning_rate=1e-3, schedule="constant",
                    warmup_steps=0), total_steps=10)

    is_img = model_cfg.name.startswith(("resnet", "vit"))
    if is_img:
        x = jax.ShapeDtypeStruct(
            (batch_n, seq_or_img, seq_or_img, 3), jnp.bfloat16)
        batch = {"image": x,
                 "label": jax.ShapeDtypeStruct((batch_n,), jnp.int32)}
        init_inputs = (jnp.zeros((1, seq_or_img, seq_or_img, 3),
                                 jnp.bfloat16),)
    else:
        ids = jax.ShapeDtypeStruct((batch_n, seq_or_img), jnp.int32)
        batch = {"input_ids": ids}
        init_inputs = (jnp.zeros((1, seq_or_img), jnp.int32),)

    def init_state(rng):
        variables = model.init({"params": rng}, *init_inputs, train=False)
        return TrainState.create(params=variables["params"], tx=tx,
                                 batch_stats=variables.get("batch_stats"))

    state_shape = jax.eval_shape(init_state, jax.random.PRNGKey(0))
    step = steps_lib.make_train_step(model, get_loss_fn(loss_name), tx)
    rng_s = jax.ShapeDtypeStruct((2,), jnp.uint32)
    t0 = time.time()
    compiled = jax.jit(step).lower(
        _attach(state_shape, sh), _attach(batch, sh),
        _attach(rng_s, sh)).compile()
    out = _analyze(compiled)
    out["compile_s"] = round(time.time() - t0, 1)
    return out


def _compile_decode(model_cfg, quantize: str, sh=None) -> dict:
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_train_tpu import quant
    from pytorch_distributed_train_tpu.config import PrecisionConfig
    from pytorch_distributed_train_tpu.generate import (
        _cache_shapes,
        build_decode_model,
    )
    from pytorch_distributed_train_tpu.models.registry import build_model

    precision = PrecisionConfig(compute_dtype="bfloat16")
    dm = build_decode_model(model_cfg, precision)
    base = jax.eval_shape(
        lambda: build_model(model_cfg, precision).init(
            {"params": jax.random.PRNGKey(0)},
            jnp.zeros((1, 2), jnp.int32), train=False))["params"]
    params = (jax.eval_shape(
        lambda p: quant.quantize_tree_named(p, quantize), base)
        if quantize else base)
    cache = _cache_shapes(dm, 1)
    ids = jax.ShapeDtypeStruct((1, 1), jnp.int32)

    def decode_step(p, c, i):
        p = quant.dequantize_tree(p, dm.dtype)
        logits, updated = dm.apply({"params": p, "cache": c}, i,
                                   train=False, mutable=["cache"])
        return logits[:, -1], updated["cache"]

    t0 = time.time()
    compiled = jax.jit(decode_step, donate_argnums=(1,)).lower(
        _attach(params, sh), _attach(cache, sh),
        _attach(ids, sh)).compile()
    out = _analyze(compiled)
    out["compile_s"] = round(time.time() - t0, 1)
    out["param_bytes_mib"] = round(sum(
        x.size * x.dtype.itemsize
        for x in jax.tree.leaves(params)) / 2**20, 1)
    return out


def _count_collectives(hlo_text: str) -> dict:
    """All-reduce placement in a compiled HLO dump — the evidence of
    the overlap A/B. Post-optimization XLA may COMBINE adjacent
    all-reduces, so the raw count can coincide between arms; what
    cannot coincide is WHERE they live: the bucketed arm issues its
    reductions inside the accumulation scan (a while-body computation,
    i.e. any non-ENTRY computation), the monolithic arm reduces the
    accumulated tree in the entry computation after the loop."""
    import re

    entry = nested = 0
    in_entry = False
    for line in hlo_text.splitlines():
        if not line.startswith(" ") and "{" in line:
            in_entry = line.lstrip().startswith("ENTRY")
        if re.search(r" all-reduce(?:-start)?\(", line):
            if in_entry:
                entry += 1
            else:
                nested += 1
    return {"all_reduce": entry + nested,
            "all_reduce_entry": entry,
            "all_reduce_in_loop": nested}


def _compile_epilogue_arm(small: bool, fused: bool, sh=None) -> dict:
    """ViT train step, adamw + clip + numeric guard: the optax-chain
    epilogue (three tree passes + whole-state gate select) vs the
    one-pass fused epilogue. Same model, same shapes — bytes-accessed
    is the decision metric."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.config import (
        ModelConfig,
        OptimConfig,
        PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.losses import get_loss_fn
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.optim import (
        make_fused_update,
        make_optimizer,
    )
    from pytorch_distributed_train_tpu.train_state import TrainState

    if small:
        mc = ModelConfig(name="vit_b16", num_classes=10, image_size=16,
                         patch_size=4, hidden_size=64, num_layers=2,
                         num_heads=4, mlp_dim=128)
        bs = 8
    else:
        mc = ModelConfig(name="vit_b16", num_classes=1000, image_size=224,
                         patch_size=16, hidden_size=768, num_layers=12,
                         num_heads=12, mlp_dim=3072)
        bs = 64
    opt = OptimConfig(name="adamw", learning_rate=1e-3,
                      schedule="constant", warmup_steps=0,
                      weight_decay=0.01, grad_clip_norm=1.0)
    model = build_model(mc, PrecisionConfig(compute_dtype="bfloat16"))
    tx, sched = make_optimizer(opt, total_steps=100)
    fe = make_fused_update(opt, sched) if fused else None

    def init_state(rng):
        variables = model.init(
            {"params": rng},
            jnp.zeros((1, mc.image_size, mc.image_size, 3)), train=False)
        return TrainState.create(params=variables["params"], tx=tx)

    state_shape = jax.eval_shape(init_state, jax.random.PRNGKey(0))
    step = steps_lib.make_train_step(
        model, get_loss_fn("softmax_xent"), tx, numeric_guard=True,
        fused_update=fe)
    batch = {
        "image": jax.ShapeDtypeStruct(
            (bs, mc.image_size, mc.image_size, 3), jnp.float32),
        "label": jax.ShapeDtypeStruct((bs,), jnp.int32),
    }
    rng_s = jax.ShapeDtypeStruct((2,), jnp.uint32)
    t0 = time.time()
    compiled = jax.jit(step, donate_argnums=(0,)).lower(
        _attach(state_shape, sh), _attach(batch, sh),
        _attach(rng_s, sh)).compile()
    out = _analyze(compiled)
    out["compile_s"] = round(time.time() - t0, 1)
    return out


def _compile_overlap_arm(small: bool, bucketed: bool) -> dict:
    """shard_map DP train step over the local device mesh: monolithic
    post-backward pmean of the whole accumulated grad tree vs
    per-bucket pmeans inside the accumulation scan. Collective counts
    from the compiled HLO are the placement evidence; always compiles
    on the LOCAL devices (the CPU fake-device mesh in tests/CI) — a
    deviceless topology has no executable collective lowering to
    count."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.config import (
        MeshConfig,
        ModelConfig,
        OptimConfig,
        PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.losses import get_loss_fn
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.optim import make_optimizer
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh
    from pytorch_distributed_train_tpu.parallel.partition import (
        rules_for_model,
    )
    from pytorch_distributed_train_tpu.train_state import TrainState

    devs = jax.devices()
    n = 8 if len(devs) >= 8 else len(devs)
    mesh = build_mesh(MeshConfig(data=n, fsdp=1), devs[:n])
    if small:
        mc = ModelConfig(name="vit_b16", num_classes=10, image_size=16,
                         patch_size=4, hidden_size=64, num_layers=2,
                         num_heads=4, mlp_dim=128)
        bs, accum, bucket_mb = 2 * n, 2, 1
    else:
        mc = ModelConfig(name="vit_b16", num_classes=1000, image_size=224,
                         patch_size=16, hidden_size=768, num_layers=12,
                         num_heads=12, mlp_dim=3072)
        bs, accum, bucket_mb = 8 * n, 4, 25
    opt = OptimConfig(name="momentum", learning_rate=0.1,
                      schedule="constant", warmup_steps=0)
    model = build_model(mc, PrecisionConfig(compute_dtype="bfloat16"))
    tx, _ = make_optimizer(opt, total_steps=100)
    rules = rules_for_model(mc.name)

    def init_state(rng):
        variables = model.init(
            {"params": rng},
            jnp.zeros((1, mc.image_size, mc.image_size, 3)), train=False)
        return TrainState.create(params=variables["params"], tx=tx)

    state_shape = jax.eval_shape(init_state, jax.random.PRNGKey(0))
    sharding = steps_lib.state_shardings(mesh, rules, state_shape)
    axes = ("data", "fsdp")
    n_buckets = 0
    if bucketed:
        reduce_grads, buckets = steps_lib.overlap_grad_reducer(
            state_shape.params, bucket_mb, axes)
        reduce_accum = None
        n_buckets = len(buckets)
    else:
        reduce_grads = None
        reduce_accum = steps_lib.monolithic_grad_reducer(axes)
    step = steps_lib.make_train_step(
        model, get_loss_fn("softmax_xent"), tx, grad_accum_steps=accum,
        reduce_grads=reduce_grads, reduce_grads_accum=reduce_accum,
        reduce_metrics=steps_lib.metrics_reducer(axes))
    jitted = steps_lib.jit_overlap_train_step(step, mesh, sharding)
    batch = {
        "image": jax.ShapeDtypeStruct(
            (bs, mc.image_size, mc.image_size, 3), jnp.float32),
        "label": jax.ShapeDtypeStruct((bs,), jnp.int32),
    }
    rng_s = jax.ShapeDtypeStruct((2,), jnp.uint32)
    t0 = time.time()
    compiled = jitted.lower(state_shape, batch, rng_s).compile()
    out = _analyze(compiled)
    out["compile_s"] = round(time.time() - t0, 1)
    out.update(_count_collectives(compiled.as_text()))
    out["devices"] = n
    out["grad_accum_steps"] = accum
    if bucketed:
        out["grad_buckets"] = n_buckets
    return out


def main(argv=None) -> int:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    p = argparse.ArgumentParser()
    p.add_argument("--arms", nargs="+",
                   default=["stem", "attn", "quant"],
                   choices=["stem", "attn", "quant", "epilogue",
                            "overlap"])
    p.add_argument("--small", action="store_true",
                   help="tiny shapes (smoke/test mode, minutes -> seconds)")
    args = p.parse_args(argv)

    from pytorch_distributed_train_tpu.config import ModelConfig

    out = {"tool": "aot_ab",
           "backend": "tpu-topology", "date": time.strftime("%Y-%m-%d"),
           "note": ("compiler model (cost_analysis/memory_analysis), "
                    "NOT a hardware measurement; ratios between arms "
                    "are the decision signal")}
    rec, topo = _probe_tpu_topology()
    out["tpu_topology_probe"] = rec
    sh = None
    if topo is not None:
        sh = jax.sharding.SingleDeviceSharding(topo.devices[0])
    else:
        out["backend"] = f"xla:{jax.devices()[0].platform}"

    if "stem" in args.arms:
        img = 64 if args.small else 224
        bs = 8 if args.small else 128
        name = "resnet18" if args.small else "resnet50"
        arms = {}
        for stem in ("conv", "space_to_depth"):
            arms[stem] = _guarded(
                _compile_train,
                ModelConfig(name=name, num_classes=1000, stem=stem),
                "softmax_xent", bs, img, sh=sh)
        out["stem_ab"] = {"config": f"{name} bs{bs} {img}px", **arms}

    if "attn" in args.arms:
        mc = dict(vocab_size=32000, hidden_size=2048, num_layers=16,
                  num_heads=16, num_kv_heads=16, mlp_dim=5504,
                  max_seq_len=2048, fused_lm_loss=True)
        bs, seq = 4, 2048
        if args.small:
            mc.update(vocab_size=512, hidden_size=128, num_layers=2,
                      num_heads=4, num_kv_heads=4, mlp_dim=256,
                      max_seq_len=256)
            bs, seq = 2, 256
        arms = {}
        for impl in ("xla", "chunked"):
            arms[impl] = _guarded(
                _compile_train,
                ModelConfig(name="llama", attention_impl=impl, **mc),
                "fused_causal_lm_xent", bs, seq, sh=sh)
        out["attn_ab"] = {"config": f"llama h{mc['hidden_size']} "
                                    f"L{mc['num_layers']} bs{bs} s{seq}",
                          **arms}

    if "epilogue" in args.arms:
        arms = {}
        for fused in (False, True):
            arms["fused" if fused else "chain"] = _guarded(
                _compile_epilogue_arm, args.small, fused, sh=sh)
        out["epilogue_ab"] = {
            "config": ("vit train step, adamw+clip+numeric-guard, "
                       + ("small" if args.small else "b16 bs64")),
            "decision": "fused gbytes_accessed <= chain (one-pass "
                        "epilogue reads/writes the grad tree once)",
            **arms}

    if "overlap" in args.arms:
        arms = {}
        for bucketed in (False, True):
            arms["bucketed" if bucketed else "monolithic"] = _guarded(
                _compile_overlap_arm, args.small, bucketed)
        out["overlap_ab"] = {
            "config": ("shard_map DP vit train step over local devices "
                       + ("(small)" if args.small else "(b16)")),
            "decision": "bucketed arm emits per-bucket all-reduces "
                        "inside the accumulation scan (count changes "
                        "vs the monolithic post-backward reduction)",
            **arms}

    if "quant" in args.arms:
        mc = dict(vocab_size=32000, hidden_size=2048, num_layers=16,
                  num_heads=16, num_kv_heads=16, mlp_dim=5504,
                  max_seq_len=512)
        if args.small:
            mc.update(vocab_size=512, hidden_size=128, num_layers=2,
                      num_heads=4, num_kv_heads=4, mlp_dim=256,
                      max_seq_len=128)
        arms = {}
        for q in ("int8", "int4"):
            arms[q] = _guarded(
                _compile_decode, ModelConfig(name="llama", **mc),
                q, sh=sh)
        out["quant_ab"] = {"config": f"llama h{mc['hidden_size']} "
                                     f"L{mc['num_layers']} decode bs1",
                           **arms}

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
