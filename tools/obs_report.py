#!/usr/bin/env python
"""One-screen run report from a run's observability artifacts.

    python tools/obs_report.py --run-dir checkpoints/
    python tools/obs_report.py --jsonl metrics.jsonl --trace trace.json

Reads the ``metrics.jsonl`` the MetricLogger writes and (optionally) the
Chrome ``trace.json`` the span recorder exports, and prints:

- the goodput breakdown (wall-time buckets from the summary record; a
  run that died before its summary still reports the last train
  record's running goodput_pct — the crashed-run case a report tool
  exists for),
- the step-time p50/p99 trend over the logged windows,
- the cluster straggler table (multi-host runs logging
  ``obs.straggler_metrics`` aggregates),
- top span names by total time (from the trace file),
- the event-journal summary (obs/events.py: counts per category, the
  last rewind / restart / profiler capture) — the one-line version of
  tools/timeline_report.py's full cross-host timeline,
- the slowest retained distributed traces (obs/tracing.py: top-K by
  request duration with the queue/prefill/decode/stream split and the
  trace ids ``timeline_report --trace`` takes).

Pure stdlib + the repo; no jax import — safe on a login host against a
run directory on shared storage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def load_jsonl(path: str) -> list[dict]:
    recs = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                recs.append(json.loads(line))
            except ValueError:
                continue  # torn tail line of a crashed run
    return recs


def _bar(frac: float, width: int = 30) -> str:
    n = max(0, min(width, round(frac * width)))
    return "#" * n + "." * (width - n)


def goodput_section(recs: list[dict]) -> list[str]:
    src = None
    for r in reversed(recs):
        if any(k.startswith("goodput_s_") for k in r):
            src = r
            break
    if src is None:
        # Crashed run: no summary record was written. Train records
        # carry only the running pct — report that instead of nothing.
        for r in reversed(recs):
            if "goodput_pct" in r:
                return [f"goodput: {r['goodput_pct']:.1f}% productive "
                        f"(running pct at step {r.get('step')}; run died "
                        "before the summary breakdown)"]
        return ["goodput: no goodput records (pre-obs run?)"]
    wall = float(src.get("goodput_wall_s", 0.0)) or sum(
        v for k, v in src.items() if k.startswith("goodput_s_"))
    out = [f"goodput: {src.get('goodput_pct', 0.0):.1f}% productive of "
           f"{wall:.1f}s wall (tag={src.get('tag')}, step={src.get('step')})"]
    for k in sorted((k for k in src if k.startswith("goodput_s_")),
                    key=lambda k: -float(src[k])):
        v = float(src[k])
        out.append(f"  {k[len('goodput_s_'):]:<12} {v:>10.2f}s "
                   f"{_bar(v / wall if wall else 0.0)} "
                   f"{100.0 * v / wall if wall else 0.0:5.1f}%")
    return out


def trend_section(recs: list[dict], width: int = 8) -> list[str]:
    rows = [r for r in recs
            if r.get("tag") == "train" and "step_time_ms_p50" in r]
    if not rows:
        return ["step-time: no windows logged"]
    out = ["step-time trend (per log window):",
           f"  {'step':>8} {'p50 ms':>10} {'p99 ms':>10} "
           f"{'stall %':>8} {'goodput %':>10}"]
    # First/last windows matter most; elide the middle to keep one screen
    show = (rows if len(rows) <= 2 * width
            else rows[:width] + [None] + rows[-width:])
    for r in show:
        if r is None:
            out.append(f"  {'...':>8}")
            continue
        out.append(
            f"  {r['step']:>8} {r['step_time_ms_p50']:>10.2f} "
            f"{r.get('step_time_ms_p99', float('nan')):>10.2f} "
            f"{r.get('input_stall_pct', 0.0):>8.2f} "
            f"{r.get('goodput_pct', float('nan')):>10.2f}")
    return out


def straggler_section(recs: list[dict]) -> list[str]:
    rows = [r for r in recs
            if r.get("tag") == "train" and "step_time_p50_max" in r]
    if not rows:
        return ["stragglers: no cross-host aggregates "
                "(single host, or obs.straggler_metrics off)"]
    last = rows[-1]
    out = [f"stragglers (last window, step {last['step']}):",
           f"  {'metric':<18} {'min':>10} {'med':>10} {'max':>10} "
           f"{'max host':>9}"]
    for key in ("step_time_p50", "input_stall_pct", "hbm_used"):
        if f"{key}_max" not in last:
            continue
        out.append(f"  {key:<18} {last[f'{key}_min']:>10.3f} "
                   f"{last[f'{key}_med']:>10.3f} {last[f'{key}_max']:>10.3f} "
                   f"{int(last[f'{key}_max_host']):>9}")
    # Chronic straggler: the host that is the step-time max most often
    hosts = [int(r["step_time_p50_max_host"]) for r in rows
             if "step_time_p50_max_host" in r]
    if hosts:
        worst = max(set(hosts), key=hosts.count)
        out.append(f"  step-time max host over {len(hosts)} windows: "
                   f"host {worst} ({hosts.count(worst)}x)")
    return out


def spans_section(trace_path: str, top: int = 8) -> list[str]:
    if not trace_path or not os.path.exists(trace_path):
        return ["spans: no trace file"]
    try:
        with open(trace_path) as f:
            events = json.load(f).get("traceEvents", [])
    except ValueError:
        return [f"spans: unreadable trace {trace_path}"]
    agg: dict[str, list[float]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        agg.setdefault(e["name"], []).append(float(e.get("dur", 0.0)) / 1e6)
    if not agg:
        return ["spans: trace has no complete events"]
    out = [f"spans ({sum(len(v) for v in agg.values())} events, "
           f"top {min(top, len(agg))} by total time):",
           f"  {'name':<28} {'count':>7} {'total s':>10} {'mean ms':>10}"]
    for name, durs in sorted(agg.items(),
                             key=lambda kv: -sum(kv[1]))[:top]:
        tot = sum(durs)
        out.append(f"  {name:<28} {len(durs):>7} {tot:>10.2f} "
                   f"{1e3 * tot / len(durs):>10.2f}")
    return out


def _load_events(events_dir: str) -> list[dict] | None:
    """Parse the journal once (None = no journal directory at all)."""
    if not events_dir or not os.path.isdir(events_dir):
        return None
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from pytorch_distributed_train_tpu.obs.events import load_events

    return load_events(events_dir)


def events_section(events_dir: str,
                   events: list[dict] | None = None) -> list[str]:
    """Journal summary: per-category counts + the newest occurrence of
    the events an operator reaches for first (rewind/restart/capture)."""
    if events is None:
        events = _load_events(events_dir)
    if events is None:
        return ["events: no journal directory (obs.events off, or a "
                "pre-journal run)"]
    if not events:
        return [f"events: journal at {events_dir} is empty"]
    by_cat: dict[str, int] = {}
    for e in events:
        by_cat[e.get("category", "?")] = by_cat.get(
            e.get("category", "?"), 0) + 1
    out = [f"events ({len(events)} journaled, "
           f"{len({e.get('host') for e in events})} writers): "
           + "  ".join(f"{c}={n}" for c, n in sorted(
               by_cat.items(), key=lambda kv: -kv[1]))]
    for label, pred in (
            ("last rewind", lambda e: e.get("category") == "sentinel"
             and e.get("name") == "rewind"),
            ("last restart", lambda e: e.get("category") == "elastic"
             and e.get("name") in ("restart", "spawn")),
            ("last capture", lambda e: e.get("category") == "profile"
             and e.get("name") == "capture_end"),
    ):
        hit = next((e for e in reversed(events) if pred(e)), None)
        if hit is None:
            out.append(f"  {label:<12} -")
            continue
        detail = " ".join(
            f"{k}={v}" for k, v in (hit.get("detail") or {}).items()
            if k != "summary")[:64]
        out.append(f"  {label:<12} {hit.get('name')}@step "
                   f"{hit.get('step')} [{hit.get('host')} "
                   f"g{hit.get('gen')}] {detail}".rstrip())
    out.append("  (full cross-host story: tools/timeline_report.py)")
    return out


def input_section(recs: list[dict]) -> list[str]:
    """Input-pipeline plane (ISSUE 12): stage bars from the summary's
    staged split + shared-memory worker-pool occupancy + packed-cache
    hit rate. Quiet (empty) for runs that predate the plane; one line
    when the run had neither pool nor cache."""
    stage_rec = next(
        (r for r in reversed(recs)
         if any(k.startswith("input_stage_s_") for k in r)), None)
    pool_rec = next(
        (r for r in reversed(recs) if "input_worker_batches" in r
         or "packed_cache_hits" in r or "packed_cache_misses" in r), None)
    if stage_rec is None and pool_rec is None:
        return []
    out = ["input pipeline:"]
    if stage_rec is not None:
        stages = {k[len("input_stage_s_"):]: float(v)
                  for k, v in stage_rec.items()
                  if k.startswith("input_stage_s_")}
        total = sum(stages.values())
        for name, v in sorted(stages.items(), key=lambda kv: -kv[1]):
            out.append(f"  {name:<8} {v:>10.2f}s "
                       f"{_bar(v / total if total else 0.0)} "
                       f"{100.0 * v / total if total else 0.0:5.1f}%")
    if pool_rec is None:
        out.append("  (no decode pool / packed cache in this run)")
        return out
    if "input_worker_batches" in pool_rec:
        occ = float(pool_rec.get("input_worker_occupancy", 0.0))
        out.append(
            f"  decode pool: {int(pool_rec['input_worker_batches'])} "
            f"batches via workers, occupancy {100.0 * occ:.1f}% "
            f"{_bar(occ, 16)}")
    if "input_effective_workers" in pool_rec:
        out.append(f"  effective workers: "
                   f"{int(pool_rec['input_effective_workers'])}")
    hits = float(pool_rec.get("packed_cache_hits", 0.0))
    misses = float(pool_rec.get("packed_cache_misses", 0.0))
    if hits or misses:
        rate = hits / (hits + misses)
        out.append(
            f"  packed cache: {int(hits)} hit(s) / {int(misses)} "
            f"miss(es) ({100.0 * rate:.0f}% hit rate), "
            f"{int(pool_rec.get('packed_cache_records_read', 0))} "
            "records served")
    return out


def perf_section(recs: list[dict],
                 events: list[dict] | None = None,
                 ledger_rows: list[dict] | None = None) -> list[str]:
    """Perf-attribution summary (obs/perf.py): achieved MFU, the last
    capture's op-class split (from the ``perf`` journal category), and
    the staged input breakdown from the summary record — the one-screen
    view of 'where did the step go'."""
    out: list[str] = []
    mfu_rec = next((r for r in reversed(recs) if "mfu_pct" in r), None)
    stage_rec = next(
        (r for r in reversed(recs)
         if any(k.startswith("input_stage_s_") for k in r)), None)
    if stage_rec is not None:
        stages = {k[len("input_stage_s_"):]: float(v)
                  for k, v in stage_rec.items()
                  if k.startswith("input_stage_s_")}
        total = sum(stages.values())
        out.append("  input stages (host pipeline seconds):")
        for name, v in sorted(stages.items(), key=lambda kv: -kv[1]):
            out.append(f"    {name:<8} {v:>10.2f}s "
                       f"{_bar(v / total if total else 0.0)} "
                       f"{100.0 * v / total if total else 0.0:5.1f}%")
    attribution = next(
        (e for e in reversed(events or [])
         if e.get("category") == "perf"
         and e.get("name") == "attribution"
         and (e.get("detail") or {}).get("opclass_ms")), None)
    if attribution is not None:
        d = attribution.get("detail") or {}
        split = d["opclass_ms"]
        total = sum(split.values())
        out.append(f"  op classes (last capture, "
                   f"{d.get('total_ms', total):.1f} ms on "
                   f"{d.get('plane', '?')}):")
        for cls, ms in sorted(split.items(), key=lambda kv: -kv[1]):
            out.append(f"    {cls:<12} {ms:>10.2f}ms "
                       f"{_bar(ms / total if total else 0.0)} "
                       f"{100.0 * ms / total if total else 0.0:5.1f}%")
    # Fusion worklist (obs/perf.py fusion_worklist): the audit's
    # actionable rendering — top kernel-gap classes per preset mapped
    # to the repo lever that closes them. Reads the RUN's own perf
    # ledger rows (report() finds <run-dir>/perf_ledger.jsonl) — never
    # the repo-global history, which would pollute every run's report
    # with other machines' gaps.
    if ledger_rows:
        try:
            from pytorch_distributed_train_tpu.obs.perf import (
                fusion_worklist,
            )

            for it in fusion_worklist(ledger_rows)[:6]:
                digest = (f" cfg={it['config_digest']}"
                          if it.get("config_digest") else "")
                out.append(
                    f"  worklist: {it['preset']} {it['op_class']} gap "
                    f"{it['gap_share']:.1%} ({it['mfu_pct']:.1f}% MFU"
                    f"{digest}) -> {it['suggestion']}")
        except Exception:
            pass  # advisory; its absence must not fail the perf section
    if mfu_rec is None and not out:
        return ["perf: no attribution records (no mfu_pct metric, no "
                "perf journal events — pre-perf-plane run?)"]
    if mfu_rec is not None:
        head = (f"perf: {mfu_rec['mfu_pct']:.2f}% MFU "
                f"(tag={mfu_rec.get('tag')}, step={mfu_rec.get('step')})")
    else:
        head = "perf: no MFU metric (CPU backend or unlisted model)"
    return [head] + out


def serving_section(events_dir: str,
                    events: list[dict] | None = None) -> list[str]:
    """Serving-SLO summary from the ``serve`` journal category
    (docs/serving_reliability.md): reliability-event counts by name +
    the newest tail-latency / failover / drain — the one-line health of
    the request path. A run with no serve events (training-only) gets a
    single quiet line."""
    if events is None:
        events = _load_events(events_dir)
    if events is None:
        return []
    serve = [e for e in events if e.get("category") == "serve"]
    if not serve:
        return ["serving: no serve events (training-only run, or the "
                "reliability plane saw no incidents)"]
    by_name: dict[str, int] = {}
    for e in serve:
        by_name[e.get("name", "?")] = by_name.get(e.get("name", "?"), 0) + 1
    out = [f"serving ({len(serve)} serve events): "
           + "  ".join(f"{n}={c}" for n, c in sorted(
               by_name.items(), key=lambda kv: -kv[1]))]
    for label, name in (("last tail anomaly", "tail_latency"),
                        ("last failover", "failover"),
                        ("last hedge", "hedge"),
                        ("last drain", "drain_begin")):
        hit = next((e for e in reversed(serve) if e.get("name") == name),
                   None)
        if hit is None:
            out.append(f"  {label:<17} -")
            continue
        detail = " ".join(f"{k}={v}" for k, v in
                          (hit.get("detail") or {}).items())[:56]
        out.append(f"  {label:<17} [{hit.get('host')} "
                   f"g{hit.get('gen')}] {detail}".rstrip())
    return out


def controller_section(events_dir: str,
                       events: list[dict] | None = None,
                       last: int = 8) -> list[str]:
    """Fleet-controller summary from the ``action`` journal category
    (docs/autoscaler.md): per-(action, outcome) counts, any mode
    latches, and the last K actions with their triggering alert and
    latency from ``requested`` to the terminal outcome. Quiet when no
    controller ran against this journal."""
    if events is None:
        events = _load_events(events_dir)
    if events is None:
        return []
    acts = [e for e in events if e.get("category") == "action"]
    if not acts:
        return []
    terminal_names = ("effective", "failed", "rolled_back", "skipped")
    requested_ts: dict[str, float] = {}
    terminal: dict[str, dict] = {}
    order: list[str] = []
    counts: dict[tuple, int] = {}
    latches = []
    for e in acts:
        d = e.get("detail") or {}
        aid = d.get("id")
        if e.get("name") == "mode":
            latches.append(d.get("mode"))
            continue
        if not aid:
            continue
        if e.get("name") == "requested":
            requested_ts[aid] = e.get("ts", 0.0)
            if aid not in order:
                order.append(aid)
        if e.get("name") in terminal_names:
            terminal[aid] = e
            key = (d.get("action", "?"), e.get("name"))
            counts[key] = counts.get(key, 0) + 1
    out = [f"controller actions ({len(order)}): "
           + "  ".join(f"{a}/{o}={c}" for (a, o), c in sorted(
               counts.items(), key=lambda kv: -kv[1]))]
    if latches:
        out.append(f"  mode transitions: {' -> '.join(str(m) for m in latches)}")
    for aid in order[-last:]:
        t = terminal.get(aid)
        if t is None:
            out.append(f"  {aid} requested, no terminal outcome "
                       "journaled (in flight at journal end?)")
            continue
        d = t.get("detail") or {}
        lat = t.get("ts", 0.0) - requested_ts.get(aid, t.get("ts", 0.0))
        line = (f"  {d.get('action', '?'):<10} {t.get('name'):<12} "
                f"+{lat:6.2f}s  trigger={d.get('trigger', '?')}")
        if d.get("alert_id"):
            line += f"  alert={d.get('alert_id')}"
        if d.get("addr"):
            line += f"  addr={d.get('addr')}"
        if d.get("reason"):
            line += f"  reason={d.get('reason')}"
        out.append(line)
    return out


def weights_section(events_dir: str,
                    events: list[dict] | None = None) -> list[str]:
    """Online weight-sync summary from the ``weights`` journal category
    (docs/online_training.md): publish cadence, per-replica applied
    swaps and their durations, rejects with reasons, and the rollout
    harvest count. Quiet when no online loop ran against this
    journal."""
    if events is None:
        events = _load_events(events_dir)
    if events is None:
        return []
    recs = [e for e in events if e.get("category") == "weights"]
    if not recs:
        return []
    publishes = [e for e in recs if e.get("name") == "publish"]
    swaps = [e for e in recs if e.get("name") == "swap"]
    rejects = [e for e in recs if e.get("name") == "swap_rejected"]
    batches = [e for e in recs if e.get("name") == "rollout_batch"]
    out = [f"weight sync ({len(recs)} weights events): "
           f"publishes={len(publishes)}  swaps={len(swaps)}  "
           f"rejects={len(rejects)}  rollout_batches={len(batches)}"]
    if publishes:
        d = publishes[-1].get("detail") or {}
        out.append(f"  last publish: v{d.get('version')} @ "
                   f"step {publishes[-1].get('step')} "
                   f"({d.get('hosts')} host shard(s))")
    last_by_host: dict[str, dict] = {}
    for e in swaps:
        last_by_host[e.get("host", "?")] = e
    for host, e in sorted(last_by_host.items()):
        d = e.get("detail") or {}
        out.append(f"  {host:<10} serving v{d.get('version')} "
                   f"(from v{d.get('old_version')}, "
                   f"{d.get('dur_s', 0):.3f}s swap)")
    if rejects:
        reasons: dict[str, int] = {}
        for e in rejects:
            r = str((e.get("detail") or {}).get("reason", "?"))
            reasons[r] = reasons.get(r, 0) + 1
        out.append("  reject reasons: " + "  ".join(
            f"{r}={c}" for r, c in sorted(reasons.items(),
                                          key=lambda kv: -kv[1])))
    return out


def store_section(events_dir: str,
                  events: list[dict] | None = None) -> list[str]:
    """Launcher-store health from the ``store`` journal category
    (store_plane.py / sentinel/liveness.py): the degraded→recovered
    arc, dropped-beat pressure and liveness blame suspensions. Quiet
    when the run never journaled store trouble — a healthy store is
    the default and needs no line."""
    if events is None:
        events = _load_events(events_dir)
    if events is None:
        return []
    srecs = [e for e in events if e.get("category") == "store"]
    if not srecs:
        return []
    state = "ok"
    counts: dict[str, int] = {}
    for e in srecs:
        name = str(e.get("name", "?"))
        counts[name] = counts.get(name, 0) + 1
        if name in ("degraded", "down"):
            state = name
        elif name == "recovered":
            state = "ok"
    out = [f"store health ({len(srecs)} store events, "
           f"{state.upper() if state != 'ok' else 'ok'} at journal end): "
           + "  ".join(f"{n}={c}" for n, c in sorted(counts.items()))]
    last = srecs[-1]
    detail = " ".join(f"{k}={v}" for k, v in
                      (last.get("detail") or {}).items())[:64]
    out.append(f"  last: {last.get('name')} [{last.get('host')} "
               f"g{last.get('gen')}] {detail}".rstrip())
    return out


def model_health_section(recs: list[dict],
                         events: list[dict] | None = None) -> list[str]:
    """Model-health plane (obs/model_health.py): the training-dynamics
    trend over the logged windows — grad norm, worst update-to-param
    ratio, reward/KL/entropy when the run is online — plus the
    ``model`` journal's early-warning arc. Quiet (empty) for runs
    without the plane: no ``update_ratio_max``-bearing train records
    (``grad_norm`` alone is every run's baseline metric, not the
    plane) and no ``model`` events."""
    health_keys = ("grad_norm", "update_ratio_max", "update_norm",
                   "reward_mean", "kl_behavior", "token_entropy")
    rows = [r for r in recs if r.get("tag") == "train"
            and ("update_ratio_max" in r or "kl_behavior" in r
                 or "token_entropy" in r)]
    mrecs = [e for e in (events or [])
             if e.get("category") == "model"]
    if not rows and not mrecs:
        return []
    out = ["model health:"]
    if rows:
        out.append(f"  {'series':<18} {'n':>5} {'first':>10} "
                   f"{'last':>10} {'max':>10}")
        for key in health_keys:
            vals = [float(r[key]) for r in rows
                    if isinstance(r.get(key), (int, float))]
            if not vals:
                continue
            out.append(f"  {key:<18} {len(vals):>5} {vals[0]:>10.4g} "
                       f"{vals[-1]:>10.4g} {max(vals):>10.4g}")
    if mrecs:
        by_name: dict[str, int] = {}
        for e in mrecs:
            by_name[e.get("name", "?")] = by_name.get(
                e.get("name", "?"), 0) + 1
        out.append(f"  model events ({len(mrecs)}): " + "  ".join(
            f"{n}={c}" for n, c in sorted(by_name.items(),
                                          key=lambda kv: -kv[1])))
        for label, name in (("last warning", "early_warning"),
                            ("last rewind armed", "rewind_armed")):
            hit = next((e for e in reversed(mrecs)
                        if e.get("name") == name), None)
            if hit is None:
                continue
            detail = " ".join(
                f"{k}={v}" for k, v in
                (hit.get("detail") or {}).items())[:64]
            out.append(f"  {label:<17} @step {hit.get('step')} "
                       f"[{hit.get('host')} g{hit.get('gen')}] "
                       f"{detail}".rstrip())
    elif rows:
        out.append("  model events: none journaled (no warnings fired)")
    return out


def traces_section(traces_dir: str, top: int = 5) -> list[str]:
    """Slowest retained distributed traces (obs/tracing.py): top-K by
    whole-request duration with the per-phase (queue / prefill / decode
    / stream) time split and the ids ``timeline_report --trace`` takes.
    Empty when the run kept no traces (training-only, or a healthy
    fleet under default knobs — which is the point of tail sampling)."""
    if not traces_dir or not os.path.isdir(traces_dir):
        return []
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from pytorch_distributed_train_tpu.obs.tracing import load_traces

    trees = load_traces(traces_dir)
    if not trees:
        return ["traces: directory present but no retained traces"]
    # one trace may span several records (router + replicas): group
    by_id: dict[str, dict] = {}
    for t in trees:
        g = by_id.setdefault(t["trace_id"], {
            "dur_ms": 0.0, "reasons": [], "hosts": set(), "phases": {}})
        if isinstance(t.get("dur_ms"), (int, float)):
            g["dur_ms"] = max(g["dur_ms"], float(t["dur_ms"]))
        if t.get("reason") and t["reason"] not in g["reasons"]:
            g["reasons"].append(t["reason"])
        g["hosts"].add(t.get("host", "?"))
        for s in t.get("spans") or []:
            name = str(s.get("name", ""))
            if name.startswith("serve.") and name != "serve.admission":
                phase = name[len("serve."):]
                g["phases"][phase] = (g["phases"].get(phase, 0.0)
                                      + float(s.get("dur_s", 0.0)) * 1e3)
    ranked = sorted(by_id.items(), key=lambda kv: -kv[1]["dur_ms"])
    out = [f"slowest traces (top {min(top, len(ranked))} of "
           f"{len(ranked)} retained):"]
    for tid, g in ranked[:top]:
        phases = " ".join(
            f"{p}={g['phases'][p]:.1f}ms"
            for p in ("queue", "prefill", "decode", "stream")
            if p in g["phases"])
        out.append(f"  {tid[:16]}.. {g['dur_ms']:>9.1f}ms "
                   f"[{','.join(g['reasons'])}; "
                   f"{len(g['hosts'])} host(s)] {phases}".rstrip())
    out.append("  (one tree: tools/timeline_report.py --trace <id>)")
    return out


def slo_section(history_dir: str) -> list[str]:
    """SLO error budgets from the run-local history store
    (<run-dir>/tsdb, written by a collector with --history-dir):
    remaining budget + the worst burn window per objective. Absent
    (empty) when the run kept no store — pre-history runs stay
    quiet, the input/serving-section convention."""
    if not history_dir or not os.path.isdir(history_dir):
        return []
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from pytorch_distributed_train_tpu.obs.slo_budget import (
        SLOBudgetTracker,
    )
    from pytorch_distributed_train_tpu.obs.tsdb import TimeSeriesStore

    store = TimeSeriesStore(history_dir)
    # report as of the newest sample, not the wall clock: the run may
    # have ended hours ago and "the last hour" of a dead store is empty
    newest = 0.0
    for target in store.targets():
        for series in store.series(target):
            last = store.latest(target, series)
            if last is not None:
                newest = max(newest, last[0])
    if not newest:
        return ["SLO budgets: store present but empty"]
    status = SLOBudgetTracker(store, clock=lambda: newest).status()
    if not status:
        return ["SLO budgets: store holds no SLI series"]
    out = ["SLO budgets (as of the store's newest sample):"]
    for name, st in sorted(status.items()):
        rem = st.get("budget_remaining")
        burns = {w: b for w, b in (st.get("burn") or {}).items()
                 if isinstance(b, (int, float))}
        worst = st.get("worst_window")
        wtxt = (f"worst burn {worst} {burns[worst]:.2f}x"
                if worst in burns else "burn unknown")
        out.append(
            f"  {name:<22} budget {rem:+.2f} "
            f"({'OVERSPENT' if rem < 0 else 'ok'}), {wtxt} "
            f"[{st.get('worst_target')}]")
    return out


def report(jsonl_path: str, trace_path: str = "",
           events_dir: str = "", traces_dir: str = "",
           history_dir: str = "") -> str:
    recs = load_jsonl(jsonl_path)
    lines = [f"== run report: {jsonl_path} ({len(recs)} records) =="]
    try:
        events = _load_events(events_dir)
    except Exception:
        events = None
    # Run-local perf ledger (trainer writes <run-dir>/perf_ledger.jsonl)
    # feeds the perf section's fusion worklist.
    ledger_rows = None
    try:
        run_ledger = os.path.join(os.path.dirname(jsonl_path),
                                  "perf_ledger.jsonl")
        if os.path.exists(run_ledger):
            from pytorch_distributed_train_tpu.obs.perf import PerfLedger

            ledger_rows = PerfLedger(run_ledger).load()
    except Exception:
        ledger_rows = None
    # Sections are INDEPENDENT by contract (pinned in
    # tests/test_obs_report.py): one malformed source — a trace.json
    # that parses but isn't the expected shape, a journal record with
    # a non-numeric field — degrades to a one-line note for ITS
    # section instead of suppressing everything after it. A report
    # tool that dies on a crashed run's artifacts defeats its purpose.
    for name, build in (
            ("goodput", lambda: goodput_section(recs)),
            ("step-time", lambda: trend_section(recs)),
            ("perf", lambda: perf_section(recs, events, ledger_rows)),
            ("input pipeline", lambda: input_section(recs)),
            ("stragglers", lambda: straggler_section(recs)),
            ("model health",
             lambda: model_health_section(recs, events)),
            ("spans", lambda: spans_section(trace_path)),
            ("events", lambda: events_section(events_dir, events)),
            ("serving", lambda: serving_section(events_dir, events)),
            ("controller actions",
             lambda: controller_section(events_dir, events)),
            ("weight sync",
             lambda: weights_section(events_dir, events)),
            ("store health", lambda: store_section(events_dir, events)),
            ("SLO budgets", lambda: slo_section(
                history_dir or os.path.join(
                    os.path.dirname(jsonl_path), "tsdb"))),
            ("traces", lambda: traces_section(traces_dir))):
        try:
            section = build()
        except Exception as e:
            section = [f"{name}: unrenderable source "
                       f"({type(e).__name__}: {e})"]
        if not section:
            continue
        lines.append("")
        lines.extend(section)
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--run-dir", default="",
                   help="run directory holding metrics.jsonl (+ trace.json)")
    p.add_argument("--jsonl", default="", help="explicit metrics.jsonl path")
    # --span-trace matches timeline_report.py (whose --trace now selects
    # a distributed trace id); --trace stays as a compat alias here
    p.add_argument("--span-trace", "--trace", dest="trace", default="",
                   help="explicit span trace.json path")
    p.add_argument("--events", default="",
                   help="explicit events directory "
                        "(default <run-dir>/events)")
    p.add_argument("--traces", default="",
                   help="retained-traces directory "
                        "(default <run-dir>/traces)")
    args = p.parse_args(argv)
    jsonl = args.jsonl or (os.path.join(args.run_dir, "metrics.jsonl")
                           if args.run_dir else "")
    if not jsonl or not os.path.exists(jsonl):
        print(f"obs_report: no metrics.jsonl at {jsonl!r} "
              "(--run-dir or --jsonl)", file=sys.stderr)
        return 2
    trace = args.trace or (os.path.join(args.run_dir, "trace.json")
                           if args.run_dir else "")
    events_dir = args.events or (os.path.join(args.run_dir, "events")
                                 if args.run_dir else "")
    traces_dir = args.traces or (os.path.join(args.run_dir, "traces")
                                 if args.run_dir else "")
    print(report(jsonl, trace, events_dir, traces_dir))
    return 0


if __name__ == "__main__":
    sys.exit(main())
