#!/usr/bin/env python
"""autoscale_drill — prove the closed fleet-control loop end to end.

The controller drill (ISSUE 17 acceptance): a seeded flash-crowd
traffic shape (tools/slo_soak.py ``scenario_schedule``) hits a 2-replica
fake-backend serving fleet through the failover router, the alert
engine diagnoses the overload, and the REAL controller — subprocess
launcher and all — must:

1. scale OUT: launch a 3rd ``serve_http --fake-backend --advertise``
   replica (action journaled ``requested → acting → effective``,
   cross-linked to the triggering alert incident id);
2. absorb the spike: the router discovers the new replica and shed
   recovers;
3. scale IN: once calm, drain one replica through ``/admin/drain``
   with ZERO hard-failed client requests (429 shed during the spike is
   honest degradation and does not count);
4. leave the whole arc visible: ``fleet_console --snapshot`` shows the
   fleet, and the event journal carries the
   ``alert fired → action requested → effective → alert resolved``
   chain tools/timeline_report.py renders.

``--budget-drill`` runs the safety-rail variant instead: the same
storm against a controller given an action budget of ZERO must latch
``degraded (budget_exhausted)`` observe-only mode, journal the
suppressed actions as ``skipped``, and act on nothing.

Prints one JSON report line; exit 0 = pass. Registered as slow-marked
tests (tests/test_zautoscale_drill.py) so tier-1 stays fast.

Usage::

    python tools/autoscale_drill.py [--seed 0] [--sanitize]
    python tools/autoscale_drill.py --budget-drill
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mk_launcher(store_addr: str, events_dir: str, *,
                 step_delay: float, slots: int, queue_depth: int):
    from pytorch_distributed_train_tpu.fleet.controller import (
        SubprocessReplicaLauncher,
    )

    env = dict(os.environ)
    env["TPUSTORE_ADDR"] = store_addr
    env["PDTT_EVENTS_DIR"] = events_dir
    env.setdefault("JAX_PLATFORMS", "cpu")
    here = os.path.dirname(os.path.abspath(__file__))
    return SubprocessReplicaLauncher(
        serve_http_path=os.path.join(here, "serve_http.py"),
        extra_args=("--slots", str(slots),
                    "--fake-step-delay", str(step_delay),
                    "--max-queue-depth", str(queue_depth),
                    "--drain-grace", "10"),
        env=env, start_timeout_s=30.0)


def _drive(router, phases: list, seed: int, counts: dict,
           lock: threading.Lock, stop: threading.Event) -> None:
    """Client load: the scenario schedule through the in-process
    failover router. Counts per-phase outcomes; a hard failure is a
    5xx or transport error — 429/504 are honest admission answers."""
    import numpy as np

    rng = np.random.default_rng(seed)
    sem = threading.Semaphore(64)
    threads = []

    def one(phase, i):
        body = {"prompt": f"{phase.name} req {i} xxxx",
                "max_tokens": phase.max_tokens}
        raw = json.dumps(body).encode()
        status = -1
        with sem:
            try:
                status, _ = router.request("/v1/completions", raw, body)
            except Exception:  # noqa: BLE001 — any escape is a failure
                status = -1
        with lock:
            c = counts.setdefault(
                phase.name, {"ok": 0, "shed": 0, "deadline": 0,
                             "failed": 0})
            if status == 200:
                c["ok"] += 1
            elif status == 429:
                c["shed"] += 1
            elif status == 504:
                c["deadline"] += 1
            else:
                c["failed"] += 1

    for pi, phase in enumerate(phases):
        n = max(1, int(phase.rps * phase.duration_s))
        gap = phase.duration_s / n
        for i in range(n):
            if stop.is_set():
                break
            th = threading.Thread(target=one, args=(phase, i),
                                  daemon=True,
                                  name=f"drill-load-{phase.name}-{i}")
            th.start()
            threads.append(th)
            time.sleep(max(0.0, gap * float(rng.uniform(0.6, 1.4))))
        if stop.is_set():
            break
    for th in threads:
        th.join(timeout=45.0)


def _snapshot_console(store_addr: str) -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    r = subprocess.run(
        [sys.executable, os.path.join(here, "fleet_console.py"),
         "--store", store_addr, "--snapshot", "--interval", "0.3"],
        capture_output=True, text=True, timeout=60)
    return r.stdout


def _chain_ok(events: list[dict]) -> dict:
    """The journal must carry the closed-loop arc: an overload alert
    fired, a scale_out action requested cross-linked to its incident
    id, the same action effective, the alert later resolved. Journal
    records nest their payload under ``detail``."""
    fired = {e["detail"].get("id") for e in events
             if e.get("category") == "alert"
             and e.get("name") == "fired"}
    resolved = {e["detail"].get("id") for e in events
                if e.get("category") == "alert"
                and e.get("name") == "resolved"}
    by_id: dict[str, dict] = {}
    for e in events:
        if e.get("category") != "action":
            continue
        d = e.get("detail", {})
        aid = d.get("id")
        if not aid or not str(aid).startswith("act-"):
            continue
        slot = by_id.setdefault(aid, {"names": [], "detail": d})
        slot["names"].append(e.get("name"))
    for aid, slot in by_id.items():
        d = slot["detail"]
        if (d.get("action") == "scale_out"
                and "requested" in slot["names"]
                and "effective" in slot["names"]
                and d.get("alert_id") in fired):
            return {"ok": True, "action_id": aid,
                    "alert_id": d.get("alert_id"),
                    "alert_resolved": d.get("alert_id") in resolved}
    return {"ok": False, "action_ids": sorted(by_id)}


def run_drill(seed: int = 0, budget_drill: bool = False,
              time_scale: float = 1.0) -> dict:
    from pytorch_distributed_train_tpu.elastic import discover_replicas
    from pytorch_distributed_train_tpu.fleet.controller import (
        FleetController,
    )
    from pytorch_distributed_train_tpu.native.store import (
        StoreClient,
        StoreServer,
    )
    from pytorch_distributed_train_tpu.obs import events as events_lib
    from pytorch_distributed_train_tpu.obs.alerts import AlertEngine
    from pytorch_distributed_train_tpu.obs.collector import (
        FleetCollector,
    )
    from pytorch_distributed_train_tpu.serving_plane.router import (
        HealthProber,
        ReplicaSet,
        Router,
    )
    from tools.slo_soak import Phase, scenario_schedule

    report: dict = {"seed": seed,
                    "variant": "budget_drill" if budget_drill
                    else "flash_crowd"}
    events_dir = tempfile.mkdtemp(prefix="autoscale-drill-events-")
    report["events_dir"] = events_dir
    os.environ["PDTT_EVENTS_DIR"] = events_dir
    events_lib.configure(events_dir, who="drill")

    server = StoreServer()
    store_addr = f"127.0.0.1:{server.port}"
    report["store"] = store_addr
    launcher = _mk_launcher(store_addr, events_dir,
                            step_delay=0.03, slots=2, queue_depth=4)
    store = StoreClient("127.0.0.1", server.port)
    replicas = ReplicaSet()
    prober = HealthProber(replicas, interval_s=0.25, down_after=3,
                          refresh=lambda: discover_replicas(store))
    router = Router(replicas, timeout_s=30.0)
    collector = FleetCollector(
        store_factory=lambda: StoreClient("127.0.0.1", server.port),
        poll_s=0.4, stale_after_s=4.0, timeout_s=2.0)
    # drill-tight rules: the storm must diagnose in seconds, and the
    # incident must resolve fast enough for the arc to complete. The
    # fake backend quantizes TTFT into coarse histogram buckets and a
    # scrape often covers a single request, so the windowed p95 is
    # really max-sampling: one benign queue collision reads ~4x the
    # idle median (0.256 bucket vs 0.064). min_abs must sit ABOVE that
    # collision noise — otherwise the rule fires off calm-phase noise
    # and every recovery-phase collision resets the healthy streak, so
    # the alert never resolves and calm never accrues. Storm TTFT
    # (queue full) lands at >= 0.512, comfortably over 0.3.
    engine = AlertEngine(overrides={
        "shed_storm.min_samples": 4,
        "shed_storm.window": 16,
        "shed_storm.resolve_after": 3,
        "shed_storm.cooldown_s": 1.0,
        "ttft_regression.min_abs": 0.3,
        "ttft_regression.cooldown_s": 1.0,
        # once the drill's traffic ends the ttft series goes quiet;
        # resolve fast so calm can accrue inside the settle window
        "ttft_regression.quiet_resolve_s": 5.0,
    })
    controller = FleetController(
        collector, engine, launcher=launcher,
        min_replicas=2, max_replicas=3,
        hysteresis=2, calm_ticks=8,
        cooldown_s={"scale_out": 3.0, "scale_in": 3.0,
                    "recycle": 3.0, "rebalance": 2.0},
        budget_window_s=120.0,
        budget_max_actions=0 if budget_drill else 10,
        verify_s=15.0, drain_timeout_s=20.0)
    if budget_drill:
        # a zero budget means the very first decided action latches
        # the degraded observe-only mode — the rail under test
        controller.mode = "active"

    counts: dict = {}
    lock = threading.Lock()
    stop = threading.Event()
    action_log: list[dict] = []
    ctl_stop = threading.Event()

    def control_loop():
        while not ctl_stop.wait(0.5):
            try:
                collector.poll()
                engine.evaluate(collector)
                for rec in controller.tick():
                    action_log.append(rec)
            except Exception as e:  # noqa: BLE001 — drill must report
                action_log.append({"action": "loop_error",
                                   "outcome": "failed",
                                   "error": f"{type(e).__name__}: {e}"})

    try:
        for _ in range(2):
            addr = launcher.launch()
            if addr is None:
                report["ok"] = False
                report["error"] = "seed replica failed to start"
                return report
        prober.start()
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and len(
                replicas.snapshot()) < 2:
            time.sleep(0.2)
        ctl = threading.Thread(target=control_loop, daemon=True,
                               name="drill-control-loop")
        ctl.start()
        # warm baseline so the shed_per_s spike detector has a healthy
        # window before the storm
        time.sleep(3.0)

        phases = scenario_schedule("flash_crowd", seed=seed,
                                   time_scale=2.2 * time_scale,
                                   rps_scale=3.0)
        # stretch recovery so calm_ticks can elapse and scale-in runs
        # while clients are still live (the zero-failed contract)
        phases = [*phases[:-1],
                  Phase("recovery", phases[-1].duration_s + 20.0,
                        phases[-1].rps, phases[-1].max_tokens,
                        phases[-1].prompt_chars, phases[-1].tenants)]
        _drive(router, phases, seed, counts, lock, stop)
        # let drains / resolves settle: the overload alerts must
        # RESOLVE (healthy samples / quiet_resolve_s) before calm
        # ticks can even start accruing, so this window is generous —
        # the loop exits the moment the arc completes
        settle = time.monotonic() + 35.0
        while time.monotonic() < settle:
            if budget_drill and controller.mode.startswith("degraded"):
                break
            if not budget_drill and any(
                    r["action"] == "scale_in"
                    and r["outcome"] == "effective"
                    for r in action_log):
                break
            time.sleep(0.5)
        report["console_snapshot"] = _snapshot_console(store_addr)
    finally:
        stop.set()
        ctl_stop.set()
        prober.stop()
        collector.stop()
        launcher.stop_all()
        try:
            server.stop()
        except OSError:
            pass

    report["traffic"] = counts
    report["actions"] = [
        {k: r.get(k) for k in ("action", "outcome", "id", "trigger",
                               "alert_id", "addr", "reason", "error")}
        for r in action_log]
    report["controller"] = {"mode": controller.mode,
                            "calm_streak": controller._calm_streak,
                            "pending": len(controller._expected),
                            **{k: v for k, v
                               in controller.status().items()
                               if k != "actions"}}
    report["firing_at_end"] = engine.firing()
    failed_total = sum(c.get("failed", 0) for c in counts.values())
    shed_total = sum(c.get("shed", 0) for c in counts.values())
    ok_total = sum(c.get("ok", 0) for c in counts.values())
    report["failed_total"] = failed_total
    report["shed_total"] = shed_total
    report["ok_total"] = ok_total

    events = events_lib.load_events(events_dir)
    if budget_drill:
        skipped = [r for r in action_log
                   if r.get("outcome") == "skipped"
                   and r.get("reason") == "budget_exhausted"]
        latched = any(e.get("category") == "action"
                      and e.get("name") == "mode"
                      and str(e.get("detail", {}).get("mode", ""))
                      .startswith("degraded")
                      for e in events)
        acted = [r for r in action_log
                 if r.get("outcome") in ("effective", "failed",
                                         "rolled_back")]
        report["skipped_actions"] = len(skipped)
        report["latched"] = latched
        report["ok"] = bool(
            controller.mode == "degraded (budget_exhausted)"
            and latched and skipped and not acted
            and failed_total == 0 and ok_total > 0)
        if not report["ok"]:
            report["why"] = {"mode": controller.mode,
                             "latched": latched,
                             "skipped": len(skipped),
                             "acted": len(acted),
                             "failed_total": failed_total}
        return report

    scale_out_ok = any(r["action"] == "scale_out"
                       and r["outcome"] == "effective"
                       for r in action_log)
    scale_in_ok = any(r["action"] == "scale_in"
                      and r["outcome"] == "effective"
                      for r in action_log)
    chain = _chain_ok(events)
    report["chain"] = chain
    shed_fired = any(e.get("category") == "alert"
                     and e.get("name") == "fired"
                     and e.get("detail", {}).get("rule") in
                     ("shed_storm", "ttft_regression")
                     for e in events)
    report["ok"] = bool(
        shed_fired and scale_out_ok and scale_in_ok
        and chain["ok"] and failed_total == 0
        and shed_total > 0 and ok_total > 0
        and "serving" in report.get("console_snapshot", ""))
    if not report["ok"]:
        report["why"] = {"shed_fired": shed_fired,
                         "scale_out": scale_out_ok,
                         "scale_in": scale_in_ok,
                         "chain": chain["ok"],
                         "failed_total": failed_total,
                         "shed_total": shed_total,
                         "ok_total": ok_total}
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--budget-drill", action="store_true",
                   help="run the budget-zero latch variant instead of "
                        "the flash-crowd scale drill")
    p.add_argument("--time-scale", type=float, default=1.0,
                   help="stretch the traffic phases (slow machines)")
    p.add_argument("--sanitize", action="store_true",
                   help="run under the tsan-lite concurrency "
                        "sanitizer (utils/syncdbg.py); replica "
                        "subprocesses inherit PDTT_SANITIZE=1; any "
                        "finding fails the drill")
    args = p.parse_args(argv)
    if args.sanitize:
        os.environ["PDTT_SANITIZE"] = "1"
    from pytorch_distributed_train_tpu.utils import syncdbg

    syncdbg.maybe_activate()
    report = run_drill(seed=args.seed, budget_drill=args.budget_drill,
                       time_scale=args.time_scale)
    if syncdbg.active():
        syncdbg.check_teardown()
        summary = syncdbg.findings_summary()
        report["sanitizer_findings"] = summary
        if summary:
            for f in syncdbg.findings():
                print(f"FAIL: sanitizer {f.kind}: {f.message}",
                      file=sys.stderr)
            report["ok"] = False
    print(json.dumps(report))
    return 0 if report.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
