#!/usr/bin/env python
"""7B memory-fit evidence via AOT compile analysis (VERDICT r2 #9).

``BASELINE.json:11`` ("llama2_7b pretrain, GSPMD sharding") is the hardest
[SPEC] row and, without pod hardware, the only honest way to ground a
"fits on N chips" claim is the compiler's own accounting:
``jit(...).lower().compile()`` runs the FULL XLA pipeline — SPMD
partitioner, layout, buffer assignment — without allocating a single
parameter, and ``compiled.memory_analysis()`` then reports per-device
argument/output/temp/code sizes. We compile the real fused-loss train
step for the llama2_7b preset over fake CPU meshes of 8/16/32 devices
and tabulate per-device HBM against the chips' capacities.

Caveats (recorded in the table, not hidden):
- CPU-backend buffer assignment differs from TPU's in layout padding and
  fusion temps; argument/output sizes (params, optimizer state, grads —
  the dominant terms at 7B) are dtype-exact, temps are an estimate.
- Activation temps depend on remat policy; the preset compiles with its
  shipping ``remat=True`` config.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=32 \
      python tools/memfit_7b.py [--mesh-devices 8 16 32] [--out docs/MEMFIT_7B.md]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HBM_PER_CHIP = {  # bytes, marketing GB -> usable ~= capacity here
    "v5e": 16 * 1024**3,
    "v5p": 95 * 1024**3,
}


def _mesh_cfg_for(n: int):
    """The llama2_7b scaling ladder: fsdp-major (ZeRO-3 is what makes 7B
    fit at all), tensor=2 once there's room — mirroring the preset docs."""
    from pytorch_distributed_train_tpu.config import MeshConfig

    if n == 8:
        return MeshConfig(data=1, fsdp=8)
    if n == 16:
        return MeshConfig(data=1, fsdp=8, tensor=2)
    if n == 32:
        return MeshConfig(data=2, fsdp=8, tensor=2)
    return MeshConfig(data=1, fsdp=n)


def measure(n_devices: int, batch_per_device: int = 1) -> dict:
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.config import get_preset
    from pytorch_distributed_train_tpu.losses import get_loss_fn
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.optim import make_optimizer
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh
    from pytorch_distributed_train_tpu.parallel.partition import rules_for_model
    from pytorch_distributed_train_tpu.train_state import TrainState

    devices = jax.devices("cpu")
    if len(devices) < n_devices:
        raise SystemExit(
            f"need {n_devices} fake devices "
            f"(set XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    cfg = get_preset("llama2_7b")
    mesh_cfg = _mesh_cfg_for(n_devices)
    mesh = build_mesh(mesh_cfg, devices[:n_devices])
    model = build_model(cfg.model, cfg.precision, mesh=mesh, mesh_cfg=mesh_cfg)
    tx, _ = make_optimizer(cfg.optim, total_steps=100)
    rules = rules_for_model(cfg.model.name)

    def init_state(rng):
        ids = jnp.zeros((2, cfg.model.max_seq_len), jnp.int32)
        variables = model.init({"params": rng}, ids, train=False)
        return TrainState.create(params=variables["params"], tx=tx)

    state_shape = jax.eval_shape(init_state, jax.random.PRNGKey(0))
    sharding = steps_lib.state_shardings(mesh, rules, state_shape)
    step = steps_lib.jit_train_step(
        steps_lib.make_train_step(model, get_loss_fn(cfg.loss), tx),
        mesh, sharding,
    )
    batch = {"input_ids": jax.ShapeDtypeStruct(
        (batch_per_device * n_devices, cfg.model.max_seq_len), jnp.int32)}
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    t0 = time.time()
    print(f"[memfit] lowering {n_devices}-device "
          f"{dict((k, v) for k, v in mesh.shape.items() if v > 1)} ...",
          flush=True)
    lowered = step.lower(state_shape, batch, rng)
    print(f"[memfit] lowered in {time.time() - t0:.0f}s; compiling "
          "(XLA full pipeline, no buffers) ...", flush=True)
    t0 = time.time()
    compiled = lowered.compile()
    compile_s = time.time() - t0
    ma = compiled.memory_analysis()
    res = {
        "n_devices": n_devices,
        "mesh": {k: v for k, v in mesh.shape.items() if v > 1},
        "batch_global": batch_per_device * n_devices,
        "compile_s": round(compile_s, 1),
        "arg_bytes": int(ma.argument_size_in_bytes),
        "out_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
    }
    # Donated state aliases args<->outputs: resident = args + temps
    # (+ non-aliased outputs, tiny metrics). Peak adds transient slack the
    # analysis already folds into temps.
    res["resident_bytes"] = res["arg_bytes"] + res["temp_bytes"]
    return res


def fmt_gb(b: int) -> str:
    return f"{b / 1024**3:.2f}"


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--mesh-devices", type=int, nargs="+", default=[8, 16, 32])
    p.add_argument("--batch-per-device", type=int, default=1)
    p.add_argument("--out", default="")
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    rows = []
    for n in args.mesh_devices:
        r = measure(n, args.batch_per_device)
        rows.append(r)
        print(f"[memfit] {n} devices {r['mesh']}: args {fmt_gb(r['arg_bytes'])} "
              f"GiB + temps {fmt_gb(r['temp_bytes'])} GiB = "
              f"{fmt_gb(r['resident_bytes'])} GiB/device "
              f"(compile {r['compile_s']}s)", flush=True)

    lines = [
        "# MEMFIT — llama2_7b per-device HBM from AOT compile analysis",
        "",
        "Generated by `tools/memfit_7b.py` (see its docstring for the",
        "methodology and CPU-backend caveats). `resident` = sharded",
        "arguments (params + adamw mu/nu fp32 + step scalars) + XLA temp",
        "buffers (activations under the preset's remat policy, fusion",
        "scratch). Donated state aliases outputs onto arguments.",
        "",
        "| devices | mesh | global batch | args GiB/dev | temps GiB/dev |"
        " resident GiB/dev | fits v5e (16G) | fits v5p (95G) |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        res = r["resident_bytes"]
        lines.append(
            f"| {r['n_devices']} | {r['mesh']} | {r['batch_global']} "
            f"| {fmt_gb(r['arg_bytes'])} | {fmt_gb(r['temp_bytes'])} "
            f"| {fmt_gb(res)} "
            f"| {'yes' if res < HBM_PER_CHIP['v5e'] else 'NO'} "
            f"| {'yes' if res < HBM_PER_CHIP['v5p'] else 'NO'} |")
    doc = "\n".join(lines) + "\n"
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc)
        print(f"[memfit] wrote {args.out}")


if __name__ == "__main__":
    main()
