#!/usr/bin/env python
"""7B memory-fit evidence via AOT compile analysis (VERDICT r2 #9).

``BASELINE.json:11`` ("llama2_7b pretrain, GSPMD sharding") is the hardest
[SPEC] row and, without pod hardware, the only honest way to ground a
"fits on N chips" claim is the compiler's own accounting:
``jit(...).lower().compile()`` runs the FULL XLA pipeline — SPMD
partitioner, layout, buffer assignment — without allocating a single
parameter, and ``compiled.memory_analysis()`` then reports per-device
argument/output/temp/code sizes. We compile the real fused-loss train
step for the llama2_7b preset over fake CPU meshes of 8/16/32 devices
and tabulate per-device HBM against the chips' capacities.

Caveats (recorded in the table, not hidden):
- CPU-backend buffer assignment differs from TPU's in layout padding and
  fusion temps; argument/output sizes (params, optimizer state, grads —
  the dominant terms at 7B) are dtype-exact, temps are an estimate.
- Activation temps depend on remat policy; the preset compiles with its
  shipping ``remat=True`` config.

Run:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=32 \
      python tools/memfit_7b.py [--mesh-devices 8 16 32] [--out docs/MEMFIT_7B.md]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_train_tpu.utils.deviceless import (  # noqa: E402
    scrub_axon_identity,
)

scrub_axon_identity()

HBM_PER_CHIP = {  # bytes, marketing GB -> usable ~= capacity here
    "v5e": 16 * 1024**3,
    "v5p": 95 * 1024**3,
}


def _mesh_cfg_for(n: int):
    """The llama2_7b scaling ladder: fsdp-major (ZeRO-3 is what makes 7B
    fit at all), tensor=2 once there's room — mirroring the preset docs."""
    from pytorch_distributed_train_tpu.config import MeshConfig

    if n == 8:
        return MeshConfig(data=1, fsdp=8)
    if n == 16:
        return MeshConfig(data=1, fsdp=8, tensor=2)
    if n == 32:
        return MeshConfig(data=2, fsdp=8, tensor=2)
    return MeshConfig(data=1, fsdp=n)


def _state_and_shardings(cfg, mesh, mesh_cfg):
    """ONE construction of (state_shape, sharding, model, tx) — both the
    exact-args and compiled-temps measurements must describe the SAME
    state or the table's columns silently drift apart."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.optim import make_optimizer
    from pytorch_distributed_train_tpu.parallel.partition import rules_for_model
    from pytorch_distributed_train_tpu.train_state import TrainState

    model = build_model(cfg.model, cfg.precision, mesh=mesh, mesh_cfg=mesh_cfg)
    tx, _ = make_optimizer(cfg.optim, total_steps=100)
    rules = rules_for_model(cfg.model.name)

    def init_state(rng):
        ids = jnp.zeros((2, cfg.model.max_seq_len), jnp.int32)
        variables = model.init({"params": rng}, ids, train=False)
        return TrainState.create(params=variables["params"], tx=tx)

    state_shape = jax.eval_shape(init_state, jax.random.PRNGKey(0))
    sharding = steps_lib.state_shardings(mesh, rules, state_shape)
    return state_shape, sharding, model, tx


def _compiled_temp_bytes(cfg, mesh, mesh_cfg, batch_global: int) -> int:
    """Compile the REAL train step at the preset's shapes (layer count comes
    from cfg) and return the per-device XLA temp allocation."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.losses import get_loss_fn

    state_shape, sharding, model, tx = _state_and_shardings(
        cfg, mesh, mesh_cfg)
    step = steps_lib.jit_train_step(
        steps_lib.make_train_step(model, get_loss_fn(cfg.loss), tx),
        mesh, sharding,
    )
    batch = {"input_ids": jax.ShapeDtypeStruct(
        (batch_global, cfg.model.max_seq_len), jnp.int32)}
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    compiled = step.lower(state_shape, batch, rng).compile()
    return int(compiled.memory_analysis().temp_size_in_bytes)


def _exact_arg_bytes(cfg, mesh, mesh_cfg) -> int:
    """Per-device bytes of the sharded TrainState — dtype- and
    shape-exact from eval_shape + the partition specs; no compile, no
    backend dependence. This is the dominant, reliable term at 7B
    (params fp32 + adamw mu/nu fp32)."""
    import jax
    import numpy as np

    state_shape, sharding, _, _ = _state_and_shardings(cfg, mesh, mesh_cfg)
    total = 0
    for leaf, shd in zip(jax.tree.leaves(state_shape),
                         jax.tree.leaves(sharding)):
        n_bytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        shards = 1
        spec = getattr(shd, "spec", None)
        if spec is not None:
            for axes in spec:
                if axes is None:
                    continue
                for ax in ([axes] if isinstance(axes, str) else axes):
                    shards *= mesh.shape[ax]
        total += -(-n_bytes // shards)  # ceil-div: padding counts
    return total


TPU_TOPOLOGY_FOR = {4: "v5e:2x2x1", 8: "v5e:4x2x1", 16: "v5e:4x4x1",
                    32: "v5e:8x4x1"}


def _devices_for(n_devices: int, platform: str):
    """CPU fake devices, or REAL v5e topology devices (round-5
    discovery: the local libtpu serves deviceless AOT, so the 7B step
    can compile against ACTUAL TPU buffer assignment — temps become a
    measurement of the compiler's allocation, not a CPU-arena
    extrapolation)."""
    import jax

    if platform == "tpu":
        from jax.experimental import topologies

        name = TPU_TOPOLOGY_FOR.get(n_devices)
        if name is None:
            raise SystemExit(f"no v5e topology mapped for {n_devices}")
        topo = topologies.get_topology_desc(topology_name=name,
                                            platform="tpu")
        return list(topo.devices)
    devices = jax.devices("cpu")
    if len(devices) < n_devices:
        raise SystemExit(
            f"need {n_devices} fake devices "
            f"(set XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    return devices[:n_devices]


def measure(n_devices: int, batch_per_device: int = 1,
            platform: str = "cpu", full_depth: bool = False) -> dict:
    """Per-device HBM for the llama2_7b step on an ``n_devices`` mesh.

    Two-part methodology (each part using the tool best suited to it):

    - **args** (params + optimizer state): exact, from shapes + partition
      specs (_exact_arg_bytes). Backend-independent.
    - **temps** (activations under remat, fusion scratch): XLA:CPU's
      buffer assignment gives each unrolled layer's remat region its OWN
      allocation, so its temp number scales ~linearly with depth — a ~Lx
      overestimate of TPU behavior, where sequential remat regions reuse
      one arena. We compile the REAL step at 2 and 4 layers (fast),
      take slope W (per-layer region) and intercept C (embed/head/update
      scratch), and report:
        cpu upper bound  = C + W * L        (what XLA:CPU would allocate)
        tpu estimate     = C + W + r * L    (one live region + per-layer
                                             bf16 block-boundary residual r)
      r = B_loc * S * H/tp * 2 bytes. The spread between the two bounds
      is printed rather than hidden; the *args* column is exact either way.
    """
    import dataclasses as _dc

    import jax

    from pytorch_distributed_train_tpu.config import get_preset
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh

    devices = _devices_for(n_devices, platform)
    cfg = get_preset("llama2_7b")
    # Pin the attention impl the TPU run would take: 'auto' resolves to the
    # chunked flash-style path at seq 4096 on TPU backends; letting the
    # CPU lowering pick dense attention would put O(S^2) score temps in
    # the table that the real run never allocates.
    cfg.model.attention_impl = "chunked"
    mesh_cfg = _mesh_cfg_for(n_devices)
    mesh = build_mesh(mesh_cfg, devices[:n_devices])
    batch_global = batch_per_device * n_devices
    L = cfg.model.num_layers

    t0 = time.time()
    arg_bytes = _exact_arg_bytes(cfg, mesh, mesh_cfg)
    if full_depth and platform != "tpu":
        raise SystemExit(
            "--full-depth is only meaningful with --platform tpu: the "
            "CPU backend's per-layer arenas overestimate temps ~Lx and "
            "its buffer assignment enforces no HBM budget, so a CPU "
            "full-depth 'verdict' would be authoritative-looking noise")
    if full_depth:
        # The definitive form (TPU topologies only): compile the REAL
        # 32-layer program and let the v5e buffer assigner itself
        # answer — success returns the exact temp allocation, a
        # RESOURCE_EXHAUSTED is the compiler's own "does not fit",
        # no extrapolation anywhere. (The slope model remains for
        # quick runs: TPU AOT scheduling proved nonlinear between
        # L=2 and L=4 — 8d slope 0.215 GiB/layer vs 16d 0.745 — so
        # extrapolated rows are upper-ish estimates only.)
        res = {
            "n_devices": n_devices, "platform": platform,
            "mesh": {k: v for k, v in mesh.shape.items() if v > 1},
            "batch_global": batch_global,
            "arg_bytes": int(arg_bytes),
            "full_depth": True,
        }
        try:
            tb = _compiled_temp_bytes(cfg, mesh, mesh_cfg, batch_global)
            res["temp_tpu_est_bytes"] = int(tb)
            res["temp_cpu_upper_bytes"] = int(tb)
            res["resident_bytes"] = int(arg_bytes + tb)
            res["resident_upper_bytes"] = res["resident_bytes"]
            # compile success bounds PROGRAM memory only — arguments
            # (params + optimizer state) still must fit beside the
            # temps at runtime, so the verdict compares resident
            # (args + temps) against the chip
            fits = res["resident_bytes"] < HBM_PER_CHIP["v5e"]
            res["compiler_verdict"] = (
                f"compiles; resident {fmt_gb(res['resident_bytes'])} "
                f"GiB/dev → {'fits v5e' if fits else 'does NOT fit v5e'}")
        except Exception as e:  # noqa: BLE001 — OOM IS the answer
            import re as _re

            msg = str(e)
            if "RESOURCE_EXHAUSTED" not in msg:
                raise
            m = _re.search(r"Used ([\d.]+[GMK]) of ([\d.]+[GMK]) hbm",
                           msg)
            res["temp_tpu_est_bytes"] = 0
            res["temp_cpu_upper_bytes"] = 0
            res["resident_bytes"] = 0
            res["resident_upper_bytes"] = 0
            res["compiler_verdict"] = (
                f"OOM: needs {m.group(1)} of {m.group(2)} hbm"
                if m else "OOM")
        res["compile_s"] = round(time.time() - t0, 1)
        return res
    temps = {}
    for probe_layers in (2, 4):
        probe = _dc.replace(
            cfg, model=_dc.replace(cfg.model, num_layers=probe_layers))
        temps[probe_layers] = _compiled_temp_bytes(
            probe, mesh, mesh_cfg, batch_global)
        print(f"[memfit] {n_devices}d probe L={probe_layers}: temps "
              f"{fmt_gb(temps[probe_layers])} GiB", flush=True)
    W = (temps[4] - temps[2]) / 2.0
    C = temps[2] - 2 * W
    tp = max(mesh.shape.get("tensor", 1), 1)
    batch_shards = max(mesh.shape.get("data", 1), 1) * max(
        mesh.shape.get("fsdp", 1), 1)
    b_loc = max(batch_global // batch_shards, 1)
    residual = b_loc * cfg.model.max_seq_len * (cfg.model.hidden_size // tp) * 2
    res = {
        "n_devices": n_devices,
        "platform": platform,
        "mesh": {k: v for k, v in mesh.shape.items() if v > 1},
        "batch_global": batch_global,
        "compile_s": round(time.time() - t0, 1),
        "arg_bytes": int(arg_bytes),
    }
    if platform == "tpu":
        # REAL v5e buffer assignment: the slope model needs no arena
        # correction — C + W*L is what the TPU compiler itself would
        # allocate at L layers (linearity of the remat regions is the
        # only extrapolation left).
        res["temp_tpu_est_bytes"] = int(max(C + W * L, 0))
        res["temp_cpu_upper_bytes"] = res["temp_tpu_est_bytes"]
    else:
        res["temp_cpu_upper_bytes"] = int(C + W * L)
        res["temp_tpu_est_bytes"] = int(max(C, 0) + W + residual * L)
    res["resident_bytes"] = res["arg_bytes"] + res["temp_tpu_est_bytes"]
    res["resident_upper_bytes"] = res["arg_bytes"] + res["temp_cpu_upper_bytes"]
    return res


def fmt_gb(b: int) -> str:
    return f"{b / 1024**3:.2f}"


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--mesh-devices", type=int, nargs="+", default=[8, 16, 32])
    p.add_argument("--batch-per-device", type=int, default=1)
    p.add_argument("--platform", default="cpu", choices=["cpu", "tpu"],
                   help="tpu = deviceless v5e-topology AOT (real TPU "
                        "buffer assignment; needs the local libtpu)")
    p.add_argument("--full-depth", action="store_true",
                   help="compile the REAL 32-layer program (slow) and "
                        "take fits/OOM from the buffer assigner itself "
                        "— no extrapolation")
    p.add_argument("--out", default="")
    args = p.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    rows = []
    for n in args.mesh_devices:
        r = measure(n, args.batch_per_device, args.platform,
                    args.full_depth)
        rows.append(r)
        if r.get("compiler_verdict"):
            print(f"[memfit] {n} devices {r['mesh']} FULL-DEPTH: "
                  f"{r['compiler_verdict']} (args "
                  f"{fmt_gb(r['arg_bytes'])} GiB, compiles "
                  f"{r['compile_s']}s)", flush=True)
            continue
        print(f"[memfit] {n} devices {r['mesh']}: args "
              f"{fmt_gb(r['arg_bytes'])} GiB + temps est "
              f"{fmt_gb(r['temp_tpu_est_bytes'])} (cpu-upper "
              f"{fmt_gb(r['temp_cpu_upper_bytes'])}) GiB = "
              f"{fmt_gb(r['resident_bytes'])} GiB/device "
              f"(compiles {r['compile_s']}s)", flush=True)

    lines = [
        "# MEMFIT — llama2_7b per-device HBM from AOT compile analysis",
        "",
        "Generated by `tools/memfit_7b.py` — see `measure()`'s docstring",
        "for the two-part methodology: `args` (params + adamw mu/nu fp32)",
        "is EXACT from shapes x partition specs; `temps` comes from",
        "compiling the real step at 2 and 4 layers and extrapolating,",
        "with both the TPU estimate (sequential remat regions share one",
        "arena) and the XLA:CPU upper bound (they don't) shown. Donated",
        "state aliases outputs onto arguments.",
        "",
        "| devices | mesh | global batch | args GiB/dev "
        "| temps est / upper GiB | resident est GiB/dev "
        "| fits v5e (16G) | fits v5p (95G) |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in rows:
        res = r["resident_bytes"]
        if r.get("compiler_verdict", "").startswith("OOM"):
            lines.append(
                f"| {r['n_devices']} | {r['mesh']} | {r['batch_global']} "
                f"| {fmt_gb(r['arg_bytes'])} | full-depth compile "
                f"| {r['compiler_verdict']} | **NO (compiler)** | — |")
            continue
        verdict = (" (full-depth compiled)"
                   if str(r.get("compiler_verdict", "")).startswith(
                       "compiles") else "")
        lines.append(
            f"| {r['n_devices']} | {r['mesh']} | {r['batch_global']} "
            f"| {fmt_gb(r['arg_bytes'])} "
            f"| {fmt_gb(r['temp_tpu_est_bytes'])} / "
            f"{fmt_gb(r['temp_cpu_upper_bytes'])} "
            f"| {fmt_gb(res)}{verdict} "
            f"| {'yes' if res < HBM_PER_CHIP['v5e'] else 'NO'} "
            f"| {'yes' if res < HBM_PER_CHIP['v5p'] else 'NO'} |")
    doc = "\n".join(lines) + "\n"
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc)
        print(f"[memfit] wrote {args.out}")


if __name__ == "__main__":
    main()
