#!/usr/bin/env python
"""Seeded SLO soak of the serving reliability plane.

    python tools/slo_soak.py                      # defaults: short soak
    python tools/slo_soak.py --requests 300 --slow-decode \
        "p=0.1:count=100000:delay=0.05"

Mixed complete / stream / abandon / cancel / tight-deadline traffic
from N seeded client threads against the FAKE token batcher
(serving_plane/testing.py — the plane's behavior under load is the
subject, not the model), with ``serve.slow_decode`` injected through
the fault registry so the decode path actually stutters. Asserts the
reliability plane's contract end to end:

- **zero slot leaks** — ``serve_slot_leaks_total`` unchanged and every
  slot free once traffic drains (abandoned/cancelled/expired requests
  all released their slots);
- **shed rate bounded** — admission control degraded, it didn't
  collapse (and didn't refuse everything either);
- **p99 TTFT within budget** — the SLO the whole plane exists to
  defend, measured by the plane's own tracker;
- **trace retention under load** (obs/tracing.py) — every client
  request runs under a trace context: the retained-trace JSONL stays
  BOUNDED (the sampler's file-size cap is honored while its in-memory
  pending table rides the ring cap), every 504'd (deadline-expired)
  request has a retained trace, and — in the router hedge phase, two
  in-process HTTP replicas (one slow) behind a hedging Router — every
  hedged request has a retained trace too.

Exit 0 = all bounds held (the report prints either way). The tier-1
smoke runs this with small numbers; the slow-marked test soaks longer.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# PDTT_SANITIZE=1: patch threading BEFORE the imports below create
# their module-global locks — the "zero findings end-to-end" gate must
# see the events/tracing/registry singletons, not miss them
from pytorch_distributed_train_tpu.utils import syncdbg  # noqa: E402

syncdbg.maybe_activate()

import numpy as np  # noqa: E402

from pytorch_distributed_train_tpu.faults import registry as fregistry  # noqa: E402
from pytorch_distributed_train_tpu.obs import tracing  # noqa: E402
from pytorch_distributed_train_tpu.obs.registry import get_registry  # noqa: E402
from pytorch_distributed_train_tpu.serving_plane import (  # noqa: E402
    DeadlineExceeded,
    OverloadShed,
    ReliabilityPlane,
    TailLatencyMonitor,
)
from pytorch_distributed_train_tpu.serving_plane.testing import (  # noqa: E402
    FakeByteTok,
    FakeTokenBatcher,
)


def run_soak(args) -> dict:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serve_http

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="slo_soak_tr_")
    tracer = tracing.configure(trace_dir, who="soak",
                               sample_pct=0.0,
                               keep_slow_ms=args.trace_keep_slow_ms,
                               max_file_mb=args.trace_cap_mb)
    if args.slow_decode:
        fregistry.configure(
            specs=(f"serve.slow_decode@{args.slow_decode}",),
            seed=args.seed)
    else:
        fregistry.configure(seed=args.seed)
    plane = ReliabilityPlane(
        max_queue_depth=args.max_queue_depth,
        shed_ttft_s=args.shed_ttft,
        deadline_default_s=0.0,  # deadlines are per-request below
        slots=args.slots,
        monitor=TailLatencyMonitor(min_samples=8))
    batcher = FakeTokenBatcher(slots=args.slots,
                               step_delay_s=args.step_delay)
    service = serve_http.BatcherService(batcher, FakeByteTok(),
                                        plane=plane,
                                        orphan_grace_s=0.5)
    leaks0 = get_registry().get_value("serve_slot_leaks_total") or 0.0
    capdrops0 = get_registry().get_value(
        "trace_dropped_total", {"where": "file_cap"}) or 0.0
    counts = {"ok": 0, "shed": 0, "deadline": 0, "abandoned": 0,
              "cancelled": 0, "error": 0}
    lock = threading.Lock()

    def note(k):
        with lock:
            counts[k] += 1

    def client(ci: int):
        rng = np.random.default_rng(args.seed * 1000 + ci)
        for i in range(args.requests // args.clients):
            prompt = f"client {ci} req {i} " + "x" * int(rng.integers(1, 24))
            toks = int(rng.integers(4, 16))
            kind = ["plain", "plain", "stream", "abandon", "cancel",
                    "deadline"][int(rng.integers(0, 6))]
            # every request runs under a trace context (the soak is its
            # own client, so minting a root here is the sanctioned
            # path); the tail sampler decides retention at finish —
            # deadline-504s are flagged by the service and MUST retain
            ctx = tracing.start_trace()
            t_req = time.monotonic()
            try:
                with tracing.activate(ctx):
                    one_request(kind, prompt, toks, rng)
            finally:
                tracer.finish(ctx.trace_id,
                              dur_s=time.monotonic() - t_req)

    def one_request(kind, prompt, toks, rng):
        try:
            if kind == "plain":
                service.complete(prompt, toks, 0.0, timeout_s=30.0)
                note("ok")
            elif kind == "stream":
                _, _, chunks = service.stream(prompt, toks, 0.0,
                                              timeout_s=30.0)
                for _toks, c in chunks:
                    if c is not None:
                        break
                note("ok")
            elif kind == "abandon":
                uid, _, chunks = service.stream(prompt, toks, 0.0,
                                                timeout_s=30.0)
                next(chunks, None)  # consume at most one tick
                service.abandon_stream(uid)
                note("abandoned")
            elif kind == "cancel":
                uid, _, _chunks = service.stream(prompt, toks, 0.0,
                                                 timeout_s=30.0)
                service.cancel_stream(uid)
                note("cancelled")
            else:  # tight deadline: often expires mid-decode
                service.complete(
                    prompt, toks, 0.0, timeout_s=30.0,
                    deadline_s=float(rng.uniform(0.001, 0.05)))
                note("ok")
        except OverloadShed:
            note("shed")
            time.sleep(0.005)  # honor the back-off in spirit
        except DeadlineExceeded:
            note("deadline")
        except (TimeoutError, RuntimeError):
            note("error")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(args.clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    # drain: every slot must come back (the leak assertion's setup)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        acct = batcher.slot_accounting()
        if acct["active"] == 0 and acct["queued"] == 0:
            break
        time.sleep(0.02)
    time.sleep(2 * service._orphan_grace_s)  # let the orphan sweep run
    wall = time.monotonic() - t0
    leaks = (get_registry().get_value("serve_slot_leaks_total") or 0.0) \
        - leaks0
    acct = batcher.slot_accounting()
    slo = plane.slo.snapshot()
    service.shutdown()
    total = sum(counts.values())
    shed_rate = counts["shed"] / max(1, total)
    # ---- trace-retention accounting (fresh spill dir per soak run)
    trees = tracing.load_traces(trace_dir)
    deadline_ids = {t["trace_id"] for t in trees
                    if "deadline" in (t.get("flags")
                                      or [t.get("reason")])}
    trace_bytes = (os.path.getsize(tracer.path)
                   if tracer.path and os.path.exists(tracer.path) else 0)
    return {"wall_s": round(wall, 2), "counts": counts,
            "shed_rate": round(shed_rate, 4),
            "slot_leaks": int(leaks), "slots": acct,
            "ttft_p99_s": slo["ttft_s"]["p99"],
            "inter_token_p99_s": slo["inter_token_s"]["p99"],
            "scheduler_alive": service.error is None,
            "trace_dir": trace_dir,
            "trace_file_bytes": trace_bytes,
            "trace_cap_bytes": tracer.max_file_bytes,
            "trace_file_cap_drops": int((get_registry().get_value(
                "trace_dropped_total", {"where": "file_cap"}) or 0.0)
                - capdrops0),
            "deadline_504s": counts["deadline"],
            "deadline_traces_retained": len(deadline_ids)}


def run_hedge_phase(args) -> dict:
    """Router hedge phase: two in-process HTTP replicas over fake
    batchers — one slow by construction — behind a hedging Router.
    Every hedge the router fires flags its trace, so every hedged
    request must end retained in the (same) spill dir."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serve_http
    from http.server import ThreadingHTTPServer

    from pytorch_distributed_train_tpu.serving_plane.router import (
        HealthProber,
        ReplicaSet,
        Router,
    )

    fregistry.configure(seed=args.seed)  # no injected faults here
    reg = get_registry()
    hedges0 = reg.family_total("serve_hedges_total")

    def mk(delay):
        svc = serve_http.BatcherService(
            FakeTokenBatcher(slots=4, step_delay_s=delay), FakeByteTok())
        srv = ThreadingHTTPServer(("127.0.0.1", 0), None)
        srv.RequestHandlerClass = serve_http.make_handler(svc, None)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return svc, srv, f"127.0.0.1:{srv.server_address[1]}"

    boxes = [mk(args.hedge_slow_delay), mk(0.002)]
    rs = ReplicaSet(tuple(b[2] for b in boxes))
    prober = HealthProber(rs, interval_s=0.2)
    prober.start()
    router = Router(rs, timeout_s=30.0, hedge_after_s=args.hedge_after)
    sent = [0]
    fails = [0]

    def one(i):
        body = {"prompt": f"hedge probe {i}", "max_tokens": 5}
        status, _ = router.request("/v1/completions",
                                   json.dumps(body).encode(), body)
        sent[0] += 1
        fails[0] += status != 200
    # concurrent rounds so least-outstanding balancing actually spreads
    # traffic onto the slow replica (a serial client would pin to the
    # fastest) — run until at least two hedges fired or the cap
    deadline = time.monotonic() + 30.0
    i = 0
    while time.monotonic() < deadline:
        ts = [threading.Thread(target=one, args=(i + k,))
              for k in range(3)]
        i += 3
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        if reg.family_total("serve_hedges_total") - hedges0 >= 2 \
                or i >= args.hedge_requests:
            break
    prober.stop()
    for svc, srv, _addr in boxes:
        srv.shutdown()
        svc.shutdown()
    hedges = int(reg.family_total("serve_hedges_total") - hedges0)
    tracer = tracing.get_tracer()
    trees = tracing.load_traces(tracer.dir or "")
    hedged_ids = {t["trace_id"] for t in trees
                  if "hedged" in (t.get("flags")
                                  or [t.get("reason")])}
    return {"requests": sent[0], "failed": fails[0],
            "hedges_fired": hedges,
            "hedged_traces_retained": len(hedged_ids)}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--requests", type=int, default=120)
    p.add_argument("--clients", type=int, default=6)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--step-delay", type=float, default=0.002,
                   help="fake batcher seconds per decode step")
    p.add_argument("--max-queue-depth", type=int, default=16)
    p.add_argument("--shed-ttft", type=float, default=0.0)
    p.add_argument("--slow-decode",
                   default="p=0.05:count=1000000:delay=0.03",
                   help="serve.slow_decode spec clauses ('' = no "
                        "injection)")
    p.add_argument("--ttft-budget", type=float, default=2.0,
                   help="p99 TTFT bound in seconds")
    p.add_argument("--max-shed-rate", type=float, default=0.5)
    p.add_argument("--trace-dir", default="",
                   help="retained-trace spill dir (default: a fresh "
                        "temp dir, so the retention accounting is "
                        "exact)")
    p.add_argument("--trace-keep-slow-ms", type=float, default=250.0)
    p.add_argument("--trace-cap-mb", type=float, default=4.0,
                   help="spill-file size cap the soak asserts is "
                        "honored")
    p.add_argument("--hedge-requests", type=int, default=30,
                   help="max requests in the router hedge phase "
                        "(0 = skip the phase)")
    p.add_argument("--hedge-after", type=float, default=0.2,
                   help="router hedge delay in the hedge phase")
    p.add_argument("--hedge-slow-delay", type=float, default=0.1,
                   help="slow replica's per-step decode delay in the "
                        "hedge phase")
    args = p.parse_args(argv)

    report = run_soak(args)
    if args.hedge_requests > 0:
        report["hedge_phase"] = run_hedge_phase(args)
    print("== slo_soak report ==")
    for k, v in report.items():
        print(f"  {k}: {v}")
    ok = True
    if not report["scheduler_alive"]:
        print("FAIL: scheduler died", file=sys.stderr)
        ok = False
    if report["slot_leaks"] != 0:
        print(f"FAIL: {report['slot_leaks']} slot leak(s)",
              file=sys.stderr)
        ok = False
    if (report["slots"]["active"] != 0 or report["slots"]["queued"] != 0):
        print(f"FAIL: slots not drained: {report['slots']}",
              file=sys.stderr)
        ok = False
    if report["shed_rate"] > args.max_shed_rate:
        print(f"FAIL: shed rate {report['shed_rate']} > "
              f"{args.max_shed_rate}", file=sys.stderr)
        ok = False
    if report["ttft_p99_s"] > args.ttft_budget:
        print(f"FAIL: p99 TTFT {report['ttft_p99_s']}s > "
              f"{args.ttft_budget}s", file=sys.stderr)
        ok = False
    # ---- tracing plane bounds (docs/observability.md)
    if report["trace_file_bytes"] > report["trace_cap_bytes"]:
        print(f"FAIL: trace JSONL {report['trace_file_bytes']}B over "
              f"the {report['trace_cap_bytes']}B cap", file=sys.stderr)
        ok = False
    # a long soak may legitimately saturate the spill cap — those drops
    # are counted, not silent, so the retention check credits them
    # instead of reporting a false regression at saturation
    if (report["deadline_traces_retained"]
            + report["trace_file_cap_drops"] < report["deadline_504s"]):
        print(f"FAIL: {report['deadline_504s']} deadline-504s but only "
              f"{report['deadline_traces_retained']} retained traces "
              f"(+{report['trace_file_cap_drops']} cap drops)",
              file=sys.stderr)
        ok = False
    hp = report.get("hedge_phase")
    if hp is not None:
        if hp["failed"]:
            print(f"FAIL: {hp['failed']} hedge-phase request(s) failed",
                  file=sys.stderr)
            ok = False
        if hp["hedges_fired"] == 0:
            print("FAIL: hedge phase fired no hedges", file=sys.stderr)
            ok = False
        if hp["hedged_traces_retained"] < min(hp["hedges_fired"], 1):
            print(f"FAIL: {hp['hedges_fired']} hedges but "
                  f"{hp['hedged_traces_retained']} retained hedged "
                  "trace(s)", file=sys.stderr)
            ok = False
    if syncdbg.active():
        syncdbg.check_teardown()
        summary = syncdbg.findings_summary()
        report["sanitizer_findings"] = summary
        print(f"  sanitizer_findings: {summary or 0}")
        if summary:
            for f in syncdbg.findings():
                print(f"FAIL: sanitizer {f.kind}: {f.message}",
                      file=sys.stderr)
            ok = False
    if ok:
        print("slo_soak: all bounds held")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
