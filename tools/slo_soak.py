#!/usr/bin/env python
"""Seeded SLO soak of the serving reliability plane.

    python tools/slo_soak.py                      # defaults: short soak
    python tools/slo_soak.py --requests 300 --slow-decode \
        "p=0.1:count=100000:delay=0.05"

Mixed complete / stream / abandon / cancel / tight-deadline traffic
from N seeded client threads against the FAKE token batcher
(serving_plane/testing.py — the plane's behavior under load is the
subject, not the model), with ``serve.slow_decode`` injected through
the fault registry so the decode path actually stutters. Asserts the
reliability plane's contract end to end:

- **zero slot leaks** — ``serve_slot_leaks_total`` unchanged and every
  slot free once traffic drains (abandoned/cancelled/expired requests
  all released their slots);
- **shed rate bounded** — admission control degraded, it didn't
  collapse (and didn't refuse everything either);
- **p99 TTFT within budget** — the SLO the whole plane exists to
  defend, measured by the plane's own tracker.

Exit 0 = all bounds held (the report prints either way). The tier-1
smoke runs this with small numbers; the slow-marked test soaks longer.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from pytorch_distributed_train_tpu.faults import registry as fregistry  # noqa: E402
from pytorch_distributed_train_tpu.obs.registry import get_registry  # noqa: E402
from pytorch_distributed_train_tpu.serving_plane import (  # noqa: E402
    DeadlineExceeded,
    OverloadShed,
    ReliabilityPlane,
    TailLatencyMonitor,
)
from pytorch_distributed_train_tpu.serving_plane.testing import (  # noqa: E402
    FakeByteTok,
    FakeTokenBatcher,
)


def run_soak(args) -> dict:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serve_http

    if args.slow_decode:
        fregistry.configure(
            specs=(f"serve.slow_decode@{args.slow_decode}",),
            seed=args.seed)
    else:
        fregistry.configure(seed=args.seed)
    plane = ReliabilityPlane(
        max_queue_depth=args.max_queue_depth,
        shed_ttft_s=args.shed_ttft,
        deadline_default_s=0.0,  # deadlines are per-request below
        slots=args.slots,
        monitor=TailLatencyMonitor(min_samples=8))
    batcher = FakeTokenBatcher(slots=args.slots,
                               step_delay_s=args.step_delay)
    service = serve_http.BatcherService(batcher, FakeByteTok(),
                                        plane=plane,
                                        orphan_grace_s=0.5)
    leaks0 = get_registry().get_value("serve_slot_leaks_total") or 0.0
    counts = {"ok": 0, "shed": 0, "deadline": 0, "abandoned": 0,
              "cancelled": 0, "error": 0}
    lock = threading.Lock()

    def note(k):
        with lock:
            counts[k] += 1

    def client(ci: int):
        rng = np.random.default_rng(args.seed * 1000 + ci)
        for i in range(args.requests // args.clients):
            prompt = f"client {ci} req {i} " + "x" * int(rng.integers(1, 24))
            toks = int(rng.integers(4, 16))
            kind = ["plain", "plain", "stream", "abandon", "cancel",
                    "deadline"][int(rng.integers(0, 6))]
            try:
                if kind == "plain":
                    service.complete(prompt, toks, 0.0, timeout_s=30.0)
                    note("ok")
                elif kind == "stream":
                    _, _, chunks = service.stream(prompt, toks, 0.0,
                                                  timeout_s=30.0)
                    for _toks, c in chunks:
                        if c is not None:
                            break
                    note("ok")
                elif kind == "abandon":
                    uid, _, chunks = service.stream(prompt, toks, 0.0,
                                                    timeout_s=30.0)
                    next(chunks, None)  # consume at most one tick
                    service.abandon_stream(uid)
                    note("abandoned")
                elif kind == "cancel":
                    uid, _, _chunks = service.stream(prompt, toks, 0.0,
                                                     timeout_s=30.0)
                    service.cancel_stream(uid)
                    note("cancelled")
                else:  # tight deadline: often expires mid-decode
                    service.complete(
                        prompt, toks, 0.0, timeout_s=30.0,
                        deadline_s=float(rng.uniform(0.001, 0.05)))
                    note("ok")
            except OverloadShed:
                note("shed")
                time.sleep(0.005)  # honor the back-off in spirit
            except DeadlineExceeded:
                note("deadline")
            except (TimeoutError, RuntimeError):
                note("error")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(args.clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    # drain: every slot must come back (the leak assertion's setup)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        acct = batcher.slot_accounting()
        if acct["active"] == 0 and acct["queued"] == 0:
            break
        time.sleep(0.02)
    time.sleep(2 * service._orphan_grace_s)  # let the orphan sweep run
    wall = time.monotonic() - t0
    leaks = (get_registry().get_value("serve_slot_leaks_total") or 0.0) \
        - leaks0
    acct = batcher.slot_accounting()
    slo = plane.slo.snapshot()
    service.shutdown()
    total = sum(counts.values())
    shed_rate = counts["shed"] / max(1, total)
    return {"wall_s": round(wall, 2), "counts": counts,
            "shed_rate": round(shed_rate, 4),
            "slot_leaks": int(leaks), "slots": acct,
            "ttft_p99_s": slo["ttft_s"]["p99"],
            "inter_token_p99_s": slo["inter_token_s"]["p99"],
            "scheduler_alive": service.error is None}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--requests", type=int, default=120)
    p.add_argument("--clients", type=int, default=6)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--step-delay", type=float, default=0.002,
                   help="fake batcher seconds per decode step")
    p.add_argument("--max-queue-depth", type=int, default=16)
    p.add_argument("--shed-ttft", type=float, default=0.0)
    p.add_argument("--slow-decode",
                   default="p=0.05:count=1000000:delay=0.03",
                   help="serve.slow_decode spec clauses ('' = no "
                        "injection)")
    p.add_argument("--ttft-budget", type=float, default=2.0,
                   help="p99 TTFT bound in seconds")
    p.add_argument("--max-shed-rate", type=float, default=0.5)
    args = p.parse_args(argv)

    report = run_soak(args)
    print("== slo_soak report ==")
    for k, v in report.items():
        print(f"  {k}: {v}")
    ok = True
    if not report["scheduler_alive"]:
        print("FAIL: scheduler died", file=sys.stderr)
        ok = False
    if report["slot_leaks"] != 0:
        print(f"FAIL: {report['slot_leaks']} slot leak(s)",
              file=sys.stderr)
        ok = False
    if (report["slots"]["active"] != 0 or report["slots"]["queued"] != 0):
        print(f"FAIL: slots not drained: {report['slots']}",
              file=sys.stderr)
        ok = False
    if report["shed_rate"] > args.max_shed_rate:
        print(f"FAIL: shed rate {report['shed_rate']} > "
              f"{args.max_shed_rate}", file=sys.stderr)
        ok = False
    if report["ttft_p99_s"] > args.ttft_budget:
        print(f"FAIL: p99 TTFT {report['ttft_p99_s']}s > "
              f"{args.ttft_budget}s", file=sys.stderr)
        ok = False
    if ok:
        print("slo_soak: all bounds held")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
