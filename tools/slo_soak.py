#!/usr/bin/env python
"""Seeded SLO soak of the serving reliability plane.

    python tools/slo_soak.py                      # defaults: short soak
    python tools/slo_soak.py --requests 300 --slow-decode \
        "p=0.1:count=100000:delay=0.05"

Mixed complete / stream / abandon / cancel / tight-deadline traffic
from N seeded client threads against the FAKE token batcher
(serving_plane/testing.py — the plane's behavior under load is the
subject, not the model), with ``serve.slow_decode`` injected through
the fault registry so the decode path actually stutters. Asserts the
reliability plane's contract end to end:

- **zero slot leaks** — ``serve_slot_leaks_total`` unchanged and every
  slot free once traffic drains (abandoned/cancelled/expired requests
  all released their slots);
- **shed rate bounded** — admission control degraded, it didn't
  collapse (and didn't refuse everything either);
- **p99 TTFT within budget** — the SLO the whole plane exists to
  defend, measured by the plane's own tracker;
- **trace retention under load** (obs/tracing.py) — every client
  request runs under a trace context: the retained-trace JSONL stays
  BOUNDED (the sampler's file-size cap is honored while its in-memory
  pending table rides the ring cap), every 504'd (deadline-expired)
  request has a retained trace, and — in the router hedge phase, two
  in-process HTTP replicas (one slow) behind a hedging Router — every
  hedged request has a retained trace too.

Exit 0 = all bounds held (the report prints either way). The tier-1
smoke runs this with small numbers; the slow-marked test soaks longer.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# PDTT_SANITIZE=1: patch threading BEFORE the imports below create
# their module-global locks — the "zero findings end-to-end" gate must
# see the events/tracing/registry singletons, not miss them
from pytorch_distributed_train_tpu.utils import syncdbg  # noqa: E402

syncdbg.maybe_activate()

import numpy as np  # noqa: E402

from pytorch_distributed_train_tpu.faults import registry as fregistry  # noqa: E402
from pytorch_distributed_train_tpu.obs import tracing  # noqa: E402
from pytorch_distributed_train_tpu.obs.registry import get_registry  # noqa: E402
from pytorch_distributed_train_tpu.serving_plane import (  # noqa: E402
    DeadlineExceeded,
    OverloadShed,
    ReliabilityPlane,
    TailLatencyMonitor,
)
from pytorch_distributed_train_tpu.serving_plane.testing import (  # noqa: E402
    FakeByteTok,
    FakeTokenBatcher,
)


# --------------------------------------------------- traffic scenarios
@dataclasses.dataclass(frozen=True)
class Phase:
    """One segment of a scenario schedule: a target request rate with
    a request shape, held for a duration."""

    name: str
    duration_s: float
    rps: float
    max_tokens: int = 6
    prompt_chars: int = 24
    tenants: int = 1


def scenario_schedule(name: str, seed: int = 0,
                      time_scale: float = 1.0,
                      rps_scale: float = 1.0) -> list[Phase]:
    """The seeded phase schedule for a named traffic SHAPE — the
    controller/admission planes are proven against shapes, not just
    rates. Deterministic for a (name, seed) pair; ``time_scale`` /
    ``rps_scale`` stretch it to the harness at hand (a drill runs the
    same shape in seconds that production sees over hours)."""
    rng = np.random.default_rng(seed)

    def ph(pname, dur, rps, **kw):
        return Phase(pname, dur * time_scale, rps * rps_scale, **kw)

    if name == "diurnal":
        base = 3.0 + float(rng.uniform(0.0, 1.0))
        steps = (0.4, 0.8, 1.3, 1.7, 1.2, 0.5)
        return [ph(f"hour{i}", 2.0,
                   base * f * float(rng.uniform(0.9, 1.1)))
                for i, f in enumerate(steps)]
    if name == "flash_crowd":
        calm = 2.0 + float(rng.uniform(0.0, 0.5))
        return [ph("calm", 3.0, calm),
                ph("spike", 4.0, calm * 10.0),
                ph("recovery", 6.0, calm * 0.8)]
    if name == "long_prompt_storm":
        calm = 3.0 + float(rng.uniform(0.0, 0.5))
        return [ph("normal", 2.5, calm),
                ph("storm", 4.0, calm * 1.5,
                   prompt_chars=int(rng.integers(2000, 4000)),
                   max_tokens=12),
                ph("normal2", 2.5, calm)]
    if name == "mixed_tenant":
        base = 4.0 + float(rng.uniform(0.0, 1.0))
        return [ph("warm", 2.0, base * 0.6, tenants=2),
                ph("contend", 4.0, base * 1.4, tenants=4),
                ph("tail", 2.0, base * 0.8, tenants=4)]
    raise SystemExit(f"unknown scenario {name!r} (want diurnal | "
                     f"flash_crowd | long_prompt_storm | mixed_tenant)")


def drive_phase(url: str, phase: Phase, seed: int,
                timeout_s: float = 30.0, stop=None) -> dict:
    """Run one phase's seeded request stream against ``url``
    (a ``/v1/completions`` endpoint). Outcome accounting separates
    honest degradation (429 shed, 504 deadline) from real failures
    (transport errors, 5xx) — the zero-failed-requests assertions key
    off ``failed`` alone."""
    rng = np.random.default_rng(seed)
    n = max(1, int(phase.rps * phase.duration_s))
    gap = phase.duration_s / n
    results = {"ok": 0, "shed": 0, "deadline": 0, "failed": 0}
    lock = threading.Lock()
    sem = threading.Semaphore(64)

    def one(i: int) -> None:
        body = json.dumps(
            {"prompt": f"{phase.name} tenant{i % phase.tenants} "
                       f"req {i} " + "x" * phase.prompt_chars,
             "max_tokens": phase.max_tokens}).encode()
        status = -1
        with sem:
            try:
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req,
                                            timeout=timeout_s) as r:
                    status = r.status
                    r.read()
            except urllib.error.HTTPError as e:
                status = e.code
            except OSError:
                status = -1
        with lock:
            if status == 200:
                results["ok"] += 1
            elif status == 429:
                results["shed"] += 1
            elif status == 504:
                results["deadline"] += 1
            else:
                results["failed"] += 1

    threads = []
    t0 = time.monotonic()
    for i in range(n):
        th = threading.Thread(target=one, args=(i,), daemon=True,
                              name=f"scenario-{phase.name}-{i}")
        th.start()
        threads.append(th)
        if stop is not None and stop.is_set():
            break
        time.sleep(max(0.0, gap * float(rng.uniform(0.5, 1.5))))
    for th in threads:
        th.join(timeout=timeout_s + 5.0)
    with lock:
        out = dict(results)
    out["phase"] = phase.name
    out["requests"] = sum(results.values())
    out["rps_target"] = round(phase.rps, 2)
    out["wall_s"] = round(time.monotonic() - t0, 2)
    return out


def run_scenario(args) -> dict:
    """Scenario mode: drive the named shape at ``--target`` (a router
    or replica ``host:port``) and report per-phase outcomes."""
    url = args.target
    if not url.startswith("http"):
        url = f"http://{url}"
    url = url.rstrip("/") + "/v1/completions"
    phases = scenario_schedule(args.scenario, seed=args.seed,
                               time_scale=args.scenario_time_scale,
                               rps_scale=args.scenario_rps_scale)
    out = []
    for i, phase in enumerate(phases):
        out.append(drive_phase(url, phase,
                               seed=args.seed * 1000 + i))
    return {"scenario": args.scenario, "seed": args.seed,
            "target": args.target, "phases": out,
            "failed_total": sum(p["failed"] for p in out),
            "shed_total": sum(p["shed"] for p in out)}


def run_soak(args) -> dict:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serve_http

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="slo_soak_tr_")
    tracer = tracing.configure(trace_dir, who="soak",
                               sample_pct=0.0,
                               keep_slow_ms=args.trace_keep_slow_ms,
                               max_file_mb=args.trace_cap_mb)
    if args.slow_decode:
        fregistry.configure(
            specs=(f"serve.slow_decode@{args.slow_decode}",),
            seed=args.seed)
    else:
        fregistry.configure(seed=args.seed)
    plane = ReliabilityPlane(
        max_queue_depth=args.max_queue_depth,
        shed_ttft_s=args.shed_ttft,
        deadline_default_s=0.0,  # deadlines are per-request below
        slots=args.slots,
        monitor=TailLatencyMonitor(min_samples=8))
    batcher = FakeTokenBatcher(slots=args.slots,
                               step_delay_s=args.step_delay)
    service = serve_http.BatcherService(batcher, FakeByteTok(),
                                        plane=plane,
                                        orphan_grace_s=0.5)
    leaks0 = get_registry().get_value("serve_slot_leaks_total") or 0.0
    capdrops0 = get_registry().get_value(
        "trace_dropped_total", {"where": "file_cap"}) or 0.0
    counts = {"ok": 0, "shed": 0, "deadline": 0, "abandoned": 0,
              "cancelled": 0, "error": 0}
    lock = threading.Lock()

    def note(k):
        with lock:
            counts[k] += 1

    def client(ci: int):
        rng = np.random.default_rng(args.seed * 1000 + ci)
        for i in range(args.requests // args.clients):
            prompt = f"client {ci} req {i} " + "x" * int(rng.integers(1, 24))
            toks = int(rng.integers(4, 16))
            kind = ["plain", "plain", "stream", "abandon", "cancel",
                    "deadline"][int(rng.integers(0, 6))]
            # every request runs under a trace context (the soak is its
            # own client, so minting a root here is the sanctioned
            # path); the tail sampler decides retention at finish —
            # deadline-504s are flagged by the service and MUST retain
            ctx = tracing.start_trace()
            t_req = time.monotonic()
            try:
                with tracing.activate(ctx):
                    one_request(kind, prompt, toks, rng)
            finally:
                tracer.finish(ctx.trace_id,
                              dur_s=time.monotonic() - t_req)

    def one_request(kind, prompt, toks, rng):
        try:
            if kind == "plain":
                service.complete(prompt, toks, 0.0, timeout_s=30.0)
                note("ok")
            elif kind == "stream":
                _, _, chunks = service.stream(prompt, toks, 0.0,
                                              timeout_s=30.0)
                for _toks, c in chunks:
                    if c is not None:
                        break
                note("ok")
            elif kind == "abandon":
                uid, _, chunks = service.stream(prompt, toks, 0.0,
                                                timeout_s=30.0)
                next(chunks, None)  # consume at most one tick
                service.abandon_stream(uid)
                note("abandoned")
            elif kind == "cancel":
                uid, _, _chunks = service.stream(prompt, toks, 0.0,
                                                 timeout_s=30.0)
                service.cancel_stream(uid)
                note("cancelled")
            else:  # tight deadline: often expires mid-decode
                service.complete(
                    prompt, toks, 0.0, timeout_s=30.0,
                    deadline_s=float(rng.uniform(0.001, 0.05)))
                note("ok")
        except OverloadShed:
            note("shed")
            time.sleep(0.005)  # honor the back-off in spirit
        except DeadlineExceeded:
            note("deadline")
        except (TimeoutError, RuntimeError):
            note("error")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(args.clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    # drain: every slot must come back (the leak assertion's setup)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        acct = batcher.slot_accounting()
        if acct["active"] == 0 and acct["queued"] == 0:
            break
        time.sleep(0.02)
    time.sleep(2 * service._orphan_grace_s)  # let the orphan sweep run
    wall = time.monotonic() - t0
    leaks = (get_registry().get_value("serve_slot_leaks_total") or 0.0) \
        - leaks0
    acct = batcher.slot_accounting()
    slo = plane.slo.snapshot()
    service.shutdown()
    total = sum(counts.values())
    shed_rate = counts["shed"] / max(1, total)
    # ---- trace-retention accounting (fresh spill dir per soak run)
    trees = tracing.load_traces(trace_dir)
    deadline_ids = {t["trace_id"] for t in trees
                    if "deadline" in (t.get("flags")
                                      or [t.get("reason")])}
    trace_bytes = (os.path.getsize(tracer.path)
                   if tracer.path and os.path.exists(tracer.path) else 0)
    return {"wall_s": round(wall, 2), "counts": counts,
            "shed_rate": round(shed_rate, 4),
            "slot_leaks": int(leaks), "slots": acct,
            "ttft_p99_s": slo["ttft_s"]["p99"],
            "inter_token_p99_s": slo["inter_token_s"]["p99"],
            "scheduler_alive": service.error is None,
            "trace_dir": trace_dir,
            "trace_file_bytes": trace_bytes,
            "trace_cap_bytes": tracer.max_file_bytes,
            "trace_file_cap_drops": int((get_registry().get_value(
                "trace_dropped_total", {"where": "file_cap"}) or 0.0)
                - capdrops0),
            "deadline_504s": counts["deadline"],
            "deadline_traces_retained": len(deadline_ids)}


def run_hedge_phase(args) -> dict:
    """Router hedge phase: two in-process HTTP replicas over fake
    batchers — one slow by construction — behind a hedging Router.
    Every hedge the router fires flags its trace, so every hedged
    request must end retained in the (same) spill dir."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serve_http
    from http.server import ThreadingHTTPServer

    from pytorch_distributed_train_tpu.serving_plane.router import (
        HealthProber,
        ReplicaSet,
        Router,
    )

    fregistry.configure(seed=args.seed)  # no injected faults here
    reg = get_registry()
    hedges0 = reg.family_total("serve_hedges_total")

    def mk(delay):
        svc = serve_http.BatcherService(
            FakeTokenBatcher(slots=4, step_delay_s=delay), FakeByteTok())
        srv = ThreadingHTTPServer(("127.0.0.1", 0), None)
        srv.RequestHandlerClass = serve_http.make_handler(svc, None)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return svc, srv, f"127.0.0.1:{srv.server_address[1]}"

    boxes = [mk(args.hedge_slow_delay), mk(0.002)]
    rs = ReplicaSet(tuple(b[2] for b in boxes))
    prober = HealthProber(rs, interval_s=0.2)
    prober.start()
    router = Router(rs, timeout_s=30.0, hedge_after_s=args.hedge_after)
    sent = [0]
    fails = [0]

    def one(i):
        body = {"prompt": f"hedge probe {i}", "max_tokens": 5}
        status, _ = router.request("/v1/completions",
                                   json.dumps(body).encode(), body)
        sent[0] += 1
        fails[0] += status != 200
    # concurrent rounds so least-outstanding balancing actually spreads
    # traffic onto the slow replica (a serial client would pin to the
    # fastest) — run until at least two hedges fired or the cap
    deadline = time.monotonic() + 30.0
    i = 0
    while time.monotonic() < deadline:
        ts = [threading.Thread(target=one, args=(i + k,))
              for k in range(3)]
        i += 3
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        if reg.family_total("serve_hedges_total") - hedges0 >= 2 \
                or i >= args.hedge_requests:
            break
    prober.stop()
    for svc, srv, _addr in boxes:
        srv.shutdown()
        svc.shutdown()
    hedges = int(reg.family_total("serve_hedges_total") - hedges0)
    tracer = tracing.get_tracer()
    trees = tracing.load_traces(tracer.dir or "")
    hedged_ids = {t["trace_id"] for t in trees
                  if "hedged" in (t.get("flags")
                                  or [t.get("reason")])}
    return {"requests": sent[0], "failed": fails[0],
            "hedges_fired": hedges,
            "hedged_traces_retained": len(hedged_ids)}


def run_budget_phase(args) -> dict:
    """SLO error-budget phase (obs/tsdb.py + obs/slo_budget.py): a
    seeded TTFT storm followed by calm traffic, with every request's
    measured TTFT written through a history store as the SLI. Asserted
    downstream in main():

    - the error budget BURNS during the storm (the fast burn-rate pair
      crosses its factor and the rule fires),
    - the burn rate returns under threshold once the storm ends (the
      rule resolves, final actionable burn < factor),
    - the engine's fired/resolved totals match the journal's alert
      lifecycle records exactly (nothing fired unjournaled, nothing
      journaled that didn't fire).
    """
    import dataclasses
    import tempfile as _tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import serve_http

    from pytorch_distributed_train_tpu.obs import events as events_lib
    from pytorch_distributed_train_tpu.obs.alerts import (
        RULES,
        AlertEngine,
    )
    from pytorch_distributed_train_tpu.obs.slo_budget import (
        SLO_CATALOG,
        SLOBudgetTracker,
    )
    from pytorch_distributed_train_tpu.obs.tsdb import TimeSeriesStore

    store_dir = args.budget_store_dir or _tempfile.mkdtemp(
        prefix="slo_soak_tsdb_")
    events_dir = _tempfile.mkdtemp(prefix="slo_soak_ev_")
    store = TimeSeriesStore(store_dir)
    # phase-scaled objective: 50% of requests may be slow; storm makes
    # ~100% slow (burn ≈ 2×), calm ~0% (burn → 0) — factor 1.5 splits
    slo = dataclasses.replace(
        SLO_CATALOG["serve_ttft_p95"], threshold=args.budget_ttft,
        objective=0.5, window_s=8.0)
    tracker = SLOBudgetTracker(store, catalog={slo.name: slo})
    rules = {}
    for name in ("slo_serve_ttft_p95_burn_fast",
                 "slo_serve_ttft_p95_burn_slow"):
        short_s, long_s = ((0.8, 2.5) if name.endswith("fast")
                          else (1.6, 5.0))
        rules[name] = dataclasses.replace(
            RULES[name], short_s=short_s, long_s=long_s, factor=1.5,
            cooldown_s=0.05, profile=False)
    # journal swap: the phase's lifecycle records go to a fresh dir so
    # the totals comparison is exact, then the previous journal (a
    # surrounding pytest process may own one) is restored untouched
    j = events_lib.EventJournal(events_dir, who="soak")
    with events_lib._LOCK:
        prev_journal = events_lib._GLOBAL
        events_lib._GLOBAL = j

    class _Tgt:
        role, host, addr, gen = "serving", "soak", "", "0"
        gens = {"0"}
        series: dict = {}
        last_ok_mono = 0.0

        def state(self, now, stale):
            return "ok"

    class _Coll:
        targets = [_Tgt()]
        stale_after_s = 10.0

    engine = AlertEngine(rules=rules, slo_tracker=tracker)
    batcher = FakeTokenBatcher(slots=4, step_delay_s=0.002)
    service = serve_http.BatcherService(batcher, FakeByteTok(),
                                        orphan_grace_s=0.5)
    fired = {n: 0 for n in rules}
    resolved = {n: 0 for n in rules}
    burn_peak = 0.0

    def measure_one(i: int) -> None:
        # one streamed request; TTFT = start -> first decoded chunk
        t0 = time.monotonic()
        uid, _, chunks = service.stream(f"budget probe {i}", 6, 0.0,
                                        timeout_s=30.0)
        ttft = None
        for _toks, c in chunks:
            if c is not None:
                ttft = time.monotonic() - t0
                break
        service.abandon_stream(uid)
        store.append("serving@soak", "ttft_p95_s", time.time(),
                     ttft if ttft is not None else 10.0 * slo.threshold)

    def evaluate() -> None:
        nonlocal burn_peak
        for rec in engine.evaluate(_Coll()):
            if rec["event"] == "fired":
                fired[rec["rule"]] += 1
            else:
                resolved[rec["rule"]] += 1
        fast = rules["slo_serve_ttft_p95_burn_fast"]
        s = tracker.burn_rate(slo.name, "serving@soak", fast.short_s)
        lo = tracker.burn_rate(slo.name, "serving@soak", fast.long_s)
        if s is not None and lo is not None:
            burn_peak = max(burn_peak, min(s, lo))

    try:
        # ---- storm: every decode step stutters past the TTFT bound
        fregistry.configure(
            specs=(f"serve.slow_decode@p=1:count=1000000:delay="
                   f"{3.0 * args.budget_ttft}",), seed=args.seed)
        i = 0
        deadline = time.monotonic() + args.budget_storm_s
        while time.monotonic() < deadline:
            measure_one(i)
            i += 1
            evaluate()
        budget_after_storm = tracker.budget_remaining(
            slo.name, "serving@soak")
        # ---- calm: faults off, the short window must drain
        fregistry.configure(seed=args.seed)
        deadline = time.monotonic() + args.budget_calm_s
        while time.monotonic() < deadline:
            measure_one(i)
            i += 1
            evaluate()
        # final evaluations so resolves land even if the last loop
        # iteration fired
        for _ in range(3):
            time.sleep(0.05)
            evaluate()
        fast = rules["slo_serve_ttft_p95_burn_fast"]
        s = tracker.burn_rate(slo.name, "serving@soak", fast.short_s)
        lo = tracker.burn_rate(slo.name, "serving@soak", fast.long_s)
        burn_final = (min(s, lo) if s is not None and lo is not None
                      else None)
        budget_end = tracker.budget_remaining(slo.name, "serving@soak")
    finally:
        service.shutdown()
        fregistry.configure(seed=args.seed)
        with events_lib._LOCK:
            events_lib._GLOBAL = prev_journal
        j.close()
    journal = events_lib.load_events(events_dir)
    j_fired = sum(1 for e in journal if e.get("category") == "alert"
                  and e.get("name") == "fired")
    j_resolved = sum(1 for e in journal if e.get("category") == "alert"
                     and e.get("name") == "resolved")
    store.flush()
    return {"requests": i, "store_dir": store_dir,
            "burn_factor": 1.5,
            "burn_peak": round(burn_peak, 3),
            "burn_final": (None if burn_final is None
                           else round(burn_final, 3)),
            "budget_after_storm": (
                None if budget_after_storm is None
                else round(budget_after_storm, 3)),
            "budget_end": (None if budget_end is None
                           else round(budget_end, 3)),
            "alerts_fired": sum(fired.values()),
            "alerts_resolved": sum(resolved.values()),
            "journal_fired": j_fired,
            "journal_resolved": j_resolved}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--requests", type=int, default=120)
    p.add_argument("--clients", type=int, default=6)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--step-delay", type=float, default=0.002,
                   help="fake batcher seconds per decode step")
    p.add_argument("--max-queue-depth", type=int, default=16)
    p.add_argument("--shed-ttft", type=float, default=0.0)
    p.add_argument("--slow-decode",
                   default="p=0.05:count=1000000:delay=0.03",
                   help="serve.slow_decode spec clauses ('' = no "
                        "injection)")
    p.add_argument("--ttft-budget", type=float, default=2.0,
                   help="p99 TTFT bound in seconds")
    p.add_argument("--max-shed-rate", type=float, default=0.5)
    p.add_argument("--trace-dir", default="",
                   help="retained-trace spill dir (default: a fresh "
                        "temp dir, so the retention accounting is "
                        "exact)")
    p.add_argument("--trace-keep-slow-ms", type=float, default=250.0)
    p.add_argument("--trace-cap-mb", type=float, default=4.0,
                   help="spill-file size cap the soak asserts is "
                        "honored")
    p.add_argument("--hedge-requests", type=int, default=30,
                   help="max requests in the router hedge phase "
                        "(0 = skip the phase)")
    p.add_argument("--hedge-after", type=float, default=0.2,
                   help="router hedge delay in the hedge phase")
    p.add_argument("--hedge-slow-delay", type=float, default=0.1,
                   help="slow replica's per-step decode delay in the "
                        "hedge phase")
    p.add_argument("--budget-storm-s", type=float, default=1.2,
                   help="SLO budget phase: seeded-storm duration "
                        "(0 = skip the phase)")
    p.add_argument("--budget-calm-s", type=float, default=3.0,
                   help="SLO budget phase: calm recovery duration")
    p.add_argument("--budget-ttft", type=float, default=0.05,
                   help="SLO budget phase: per-request TTFT threshold "
                        "a sample must beat to count good")
    p.add_argument("--budget-store-dir", default="",
                   help="SLO budget phase: tsdb root (default: fresh "
                        "temp dir)")
    p.add_argument("--scenario", default="",
                   choices=["", "diurnal", "flash_crowd",
                            "long_prompt_storm", "mixed_tenant"],
                   help="scenario mode: drive this seeded traffic "
                        "shape at --target instead of the in-process "
                        "soak")
    p.add_argument("--target", default="",
                   help="scenario mode: router/replica host:port")
    p.add_argument("--scenario-time-scale", type=float, default=1.0,
                   help="scenario mode: phase-duration multiplier")
    p.add_argument("--scenario-rps-scale", type=float, default=1.0,
                   help="scenario mode: request-rate multiplier")
    args = p.parse_args(argv)

    if args.scenario:
        if not args.target:
            print("slo_soak: --scenario needs --target",
                  file=sys.stderr)
            return 2
        report = run_scenario(args)
        print("== slo_soak scenario report ==")
        print(json.dumps(report, indent=2, sort_keys=True))
        if report["failed_total"] != 0:
            print(f"FAIL: {report['failed_total']} hard-failed "
                  f"request(s)", file=sys.stderr)
            return 1
        return 0

    report = run_soak(args)
    if args.hedge_requests > 0:
        report["hedge_phase"] = run_hedge_phase(args)
    if args.budget_storm_s > 0:
        report["budget_phase"] = run_budget_phase(args)
    print("== slo_soak report ==")
    for k, v in report.items():
        print(f"  {k}: {v}")
    ok = True
    if not report["scheduler_alive"]:
        print("FAIL: scheduler died", file=sys.stderr)
        ok = False
    if report["slot_leaks"] != 0:
        print(f"FAIL: {report['slot_leaks']} slot leak(s)",
              file=sys.stderr)
        ok = False
    if (report["slots"]["active"] != 0 or report["slots"]["queued"] != 0):
        print(f"FAIL: slots not drained: {report['slots']}",
              file=sys.stderr)
        ok = False
    if report["shed_rate"] > args.max_shed_rate:
        print(f"FAIL: shed rate {report['shed_rate']} > "
              f"{args.max_shed_rate}", file=sys.stderr)
        ok = False
    if report["ttft_p99_s"] > args.ttft_budget:
        print(f"FAIL: p99 TTFT {report['ttft_p99_s']}s > "
              f"{args.ttft_budget}s", file=sys.stderr)
        ok = False
    # ---- tracing plane bounds (docs/observability.md)
    if report["trace_file_bytes"] > report["trace_cap_bytes"]:
        print(f"FAIL: trace JSONL {report['trace_file_bytes']}B over "
              f"the {report['trace_cap_bytes']}B cap", file=sys.stderr)
        ok = False
    # a long soak may legitimately saturate the spill cap — those drops
    # are counted, not silent, so the retention check credits them
    # instead of reporting a false regression at saturation
    if (report["deadline_traces_retained"]
            + report["trace_file_cap_drops"] < report["deadline_504s"]):
        print(f"FAIL: {report['deadline_504s']} deadline-504s but only "
              f"{report['deadline_traces_retained']} retained traces "
              f"(+{report['trace_file_cap_drops']} cap drops)",
              file=sys.stderr)
        ok = False
    hp = report.get("hedge_phase")
    if hp is not None:
        if hp["failed"]:
            print(f"FAIL: {hp['failed']} hedge-phase request(s) failed",
                  file=sys.stderr)
            ok = False
        if hp["hedges_fired"] == 0:
            print("FAIL: hedge phase fired no hedges", file=sys.stderr)
            ok = False
        if hp["hedged_traces_retained"] < min(hp["hedges_fired"], 1):
            print(f"FAIL: {hp['hedges_fired']} hedges but "
                  f"{hp['hedged_traces_retained']} retained hedged "
                  "trace(s)", file=sys.stderr)
            ok = False
    bp = report.get("budget_phase")
    if bp is not None:
        # the budget must BURN during the seeded storm...
        if bp["burn_peak"] < bp["burn_factor"]:
            print(f"FAIL: budget phase peak burn {bp['burn_peak']}x "
                  f"never crossed the {bp['burn_factor']}x factor",
                  file=sys.stderr)
            ok = False
        if bp["alerts_fired"] == 0:
            print("FAIL: budget phase fired no burn-rate alerts",
                  file=sys.stderr)
            ok = False
        if (bp["budget_after_storm"] is None
                or bp["budget_after_storm"] >= 1.0):
            print(f"FAIL: error budget did not burn during the storm "
                  f"(remaining {bp['budget_after_storm']})",
                  file=sys.stderr)
            ok = False
        # ...the burn rate must return under threshold after it...
        if bp["burn_final"] is None \
                or bp["burn_final"] >= bp["burn_factor"]:
            print(f"FAIL: burn rate still {bp['burn_final']}x >= "
                  f"{bp['burn_factor']}x after the calm phase",
                  file=sys.stderr)
            ok = False
        if bp["alerts_resolved"] != bp["alerts_fired"]:
            print(f"FAIL: {bp['alerts_fired']} burn alert(s) fired but "
                  f"{bp['alerts_resolved']} resolved", file=sys.stderr)
            ok = False
        # ...and the engine's totals must match the journal's alert
        # lifecycle exactly
        if (bp["journal_fired"] != bp["alerts_fired"]
                or bp["journal_resolved"] != bp["alerts_resolved"]):
            print(f"FAIL: journal lifecycle "
                  f"({bp['journal_fired']} fired/"
                  f"{bp['journal_resolved']} resolved) != engine "
                  f"({bp['alerts_fired']}/{bp['alerts_resolved']})",
                  file=sys.stderr)
            ok = False
    if syncdbg.active():
        syncdbg.check_teardown()
        summary = syncdbg.findings_summary()
        report["sanitizer_findings"] = summary
        print(f"  sanitizer_findings: {summary or 0}")
        if summary:
            for f in syncdbg.findings():
                print(f"FAIL: sanitizer {f.kind}: {f.message}",
                      file=sys.stderr)
            ok = False
    if ok:
        print("slo_soak: all bounds held")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
