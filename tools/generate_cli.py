#!/usr/bin/env python
"""Text generation CLI — the serving-side entrypoint.

Loads model weights from a torch-layout safetensors file (the bridge
format: `python train.py --export-safetensors model.st` writes one from
any checkpoint; HF torch files of the same architecture import too),
tokenizes prompts (local HF tokenizer dir, or the asset-free byte
tokenizer), and runs KV-cache decode (generate.py) — optionally with
weight-only int8 (quant.py) and/or tensor-parallel over the local chips.

    python tools/generate_cli.py --config llama2_7b \
        --safetensors model.st --tokenizer /models/llama2-tok \
        --prompt "The capital of France is" --max-new-tokens 64 \
        --temperature 0.8 --top-k 40 [--quantize int8] [--tp 4]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default="llama2_7b",
                   help="preset supplying the model architecture")
    p.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                   help="dotted config override (model.* mostly)")
    p.add_argument("--safetensors", required=True,
                   help="torch-layout safetensors weights (interop bridge)")
    p.add_argument("--tokenizer", default="",
                   help="local HF tokenizer dir; empty → byte tokenizer")
    p.add_argument("--prompt", action="append", default=[],
                   help="repeatable; '-' reads one prompt per stdin line")
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=0.0,
                   help="nucleus sampling threshold in (0,1); 0 -> off")
    p.add_argument("--min-p", type=float, default=0.0,
                   help="min-p sampling: keep tokens with prob >= min_p "
                        "x max prob (entropy-adaptive; 0 -> off)")
    p.add_argument("--num-beams", type=int, default=0,
                   help="beam-search decoding; overrides temperature/"
                        "top-k/top-p/min-p (beams expand the full "
                        "distribution); 0 → off")
    p.add_argument("--repetition-penalty", type=float, default=1.0,
                   help="HF CTRL rule over prompt+generated (>1 "
                        "discourages repeats; 1 = off)")
    p.add_argument("--presence-penalty", type=float, default=0.0,
                   help="OpenAI additive penalty for any seen token")
    p.add_argument("--frequency-penalty", type=float, default=0.0,
                   help="OpenAI additive penalty x occurrence count")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--quantize", default="", choices=["", "int8", "int4"])
    p.add_argument("--tp", type=int, default=0,
                   help="tensor-parallel ways over local devices (0 → off)")
    p.add_argument("--serve-slots", type=int, default=0, metavar="SLOTS",
                   help="continuous batching (serving.ContinuousBatcher): "
                        "run ALL prompts concurrently through this many "
                        "cache slots instead of one lockstep generate() "
                        "per prompt; completions print as they finish "
                        "(causal + t5 families; 0 → off)")
    args = p.parse_args(argv)

    prompts = []
    for item in args.prompt or ["-"]:
        if item == "-":
            prompts.extend(line.rstrip("\n") for line in sys.stdin
                           if line.strip())
        else:
            prompts.append(item)
    if not prompts:
        print("generate_cli: no prompts", file=sys.stderr)
        return 2

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_train_tpu.config import get_preset
    from pytorch_distributed_train_tpu.data.text import load_tokenizer
    from pytorch_distributed_train_tpu.generate import (
        build_decode_model,
        generate,
        shard_decode_params,
    )
    try:
        cfg = get_preset(args.config)
        cfg.apply_overrides(args.set)

        tok = load_tokenizer(args.tokenizer)
        encoded = [tok.encode(t) for t in prompts]
        if any(len(e) == 0 for e in encoded):
            raise ValueError("empty prompt after tokenization")

        model_cfg = cfg.model
        is_t5 = model_cfg.name.startswith("t5")
        # argument-compatibility refusals BEFORE the (potentially
        # tens-of-GB) weight load
        if is_t5 and args.tp > 1:
            raise ValueError(
                "--tp supports the causal-LM families; t5 serving is "
                "single-device for now")
        if args.num_beams >= 1 and args.tp > 1:
            raise ValueError(
                "--num-beams with --tp is unsupported (beam search "
                "drives the single-device step)")
        if args.serve_slots > 0 and args.num_beams >= 1:
            raise ValueError(
                "--serve-slots is continuous batching; it composes with "
                "sampling flags and --tp but not --num-beams")
        from pytorch_distributed_train_tpu.serving import (
            load_params_for_serving,
        )

        params = load_params_for_serving(cfg, args.safetensors,
                                         args.quantize)

        from pytorch_distributed_train_tpu.serving import trim_at_eos

        def emit(i, text, new):
            print(f"=== prompt {i}: {text!r}")
            print(tok.decode(trim_at_eos(new, tok.eos_id)))

        if is_t5:
            from pytorch_distributed_train_tpu.generate import (
                generate_seq2seq,
            )

            if args.serve_slots > 0:
                from pytorch_distributed_train_tpu.serving import (
                    Seq2SeqContinuousBatcher,
                )

                b = Seq2SeqContinuousBatcher(
                    model_cfg, cfg.precision, params,
                    slots=args.serve_slots, top_k=args.top_k,
                    top_p=args.top_p, min_p=args.min_p,
                    rng=jax.random.PRNGKey(args.seed))
                uid_to_i = {}
                for i, e in enumerate(encoded):
                    uid_to_i[b.submit(e, args.max_new_tokens,
                                      temperature=args.temperature,
                                      eos_id=tok.eos_id)] = i
                for c in b.run():
                    i = uid_to_i[c.uid]
                    emit(i, prompts[i], c.tokens)
                return 0

            for i, (text, e) in enumerate(zip(prompts, encoded)):
                ids = jnp.asarray(np.asarray(e, np.int32)[None, :])
                if args.num_beams >= 1:
                    from pytorch_distributed_train_tpu.generate import (
                        beam_search_seq2seq,
                    )

                    seqs, _ = beam_search_seq2seq(
                        model_cfg, cfg.precision, params, ids,
                        args.max_new_tokens, num_beams=args.num_beams,
                        eos_id=tok.eos_id)
                    out = np.asarray(seqs)
                else:
                    out = np.asarray(generate_seq2seq(
                        model_cfg, cfg.precision, params, ids,
                        args.max_new_tokens, temperature=args.temperature,
                        top_k=args.top_k, top_p=args.top_p,
                        min_p=args.min_p,
                        rng=jax.random.PRNGKey(args.seed + i),
                        eos_id=tok.eos_id))
                emit(i, text, out[0].tolist())
            return 0

        if args.serve_slots > 0:
            from pytorch_distributed_train_tpu.serving import (
                ContinuousBatcher,
            )

            serve_mesh = None
            if args.tp > 1:
                from pytorch_distributed_train_tpu.config import MeshConfig
                from pytorch_distributed_train_tpu.parallel.mesh import (
                    build_mesh,
                )

                serve_mesh = build_mesh(
                    MeshConfig(tensor=args.tp, data=1, fsdp=1))
                params = shard_decode_params(model_cfg.name, serve_mesh,
                                             params)
            b = ContinuousBatcher(
                model_cfg, cfg.precision, params,
                slots=args.serve_slots, top_k=args.top_k,
                top_p=args.top_p, min_p=args.min_p,
                rng=jax.random.PRNGKey(args.seed), mesh=serve_mesh)
            uid_to_i = {}
            for i, e in enumerate(encoded):
                uid_to_i[b.submit(
                    e, args.max_new_tokens,
                    temperature=args.temperature, eos_id=tok.eos_id,
                    repetition_penalty=args.repetition_penalty,
                    presence_penalty=args.presence_penalty,
                    frequency_penalty=args.frequency_penalty)] = i
            for c in b.run():
                i = uid_to_i[c.uid]
                emit(i, prompts[i], c.tokens)
            return 0

        model = build_decode_model(model_cfg, cfg.precision)
        mesh = None
        if args.tp > 1:
            from pytorch_distributed_train_tpu.config import MeshConfig
            from pytorch_distributed_train_tpu.parallel.mesh import build_mesh

            mesh = build_mesh(MeshConfig(tensor=args.tp, data=1, fsdp=1))
            params = shard_decode_params(model_cfg.name, mesh, params)

        # One generation per prompt: the decoder has no padding mask, so
        # batching mixed-length prompts with left-pad would let pad tokens
        # leak into attention (and shift RoPE positions). Equal-shape calls
        # reuse the same compiled executables.
        for i, (text, e) in enumerate(zip(prompts, encoded)):
            ids = jnp.asarray(np.asarray(e, np.int32)[None, :])
            if args.num_beams >= 1:  # 1 == greedy via the beam machinery
                from pytorch_distributed_train_tpu.generate import (
                    beam_search,
                )

                seqs, _ = beam_search(
                    model, params, ids, args.max_new_tokens,
                    num_beams=args.num_beams, eos_id=tok.eos_id)
                out = np.asarray(seqs)
            else:
                out = np.asarray(generate(
                    model, params, ids, args.max_new_tokens,
                    temperature=args.temperature, top_k=args.top_k,
                    top_p=args.top_p, min_p=args.min_p,
                    rng=jax.random.PRNGKey(args.seed + i),
                    eos_id=tok.eos_id, mesh=mesh,
                    repetition_penalty=args.repetition_penalty,
                    presence_penalty=args.presence_penalty,
                    frequency_penalty=args.frequency_penalty))
            emit(i, text, out[0, len(e):].tolist())
        return 0
    except (KeyError, ValueError, FileNotFoundError, OSError) as e:
        # User-input mistakes (unknown preset, typo'd --set, missing or
        # foreign weights file, prompt longer than max_seq_len, bad --tp)
        # print one clear line and exit 2 — same contract as train.py.
        print(f"generate_cli: error: {e.args[0] if e.args else e}",
              file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
