#!/usr/bin/env python
"""Cross-check the fault-point catalog in docs/fault_tolerance.md against
the live registry (faults/registry.py POINTS) — in BOTH directions.

The fault layer's whole value is legibility: an operator reads the doc's
catalog to write an injection schedule, and a point that exists in code
but not in the doc (or vice versa) is exactly the silent drift this
repo's "a schedule that silently does nothing is itself a silent fault"
stance forbids. Run standalone in CI::

    python tools/check_fault_points.py      # exit 0 = in sync

or as a test (tests/test_sentinel.py imports and asserts main() == 0).
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DOC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "docs", "fault_tolerance.md")

_ROW = re.compile(r"^\|\s*`([a-z_]+\.[a-z_]+)`\s*\|")


def documented_points(doc_path: str = DOC) -> set[str]:
    """Point names from the first column of the '## Fault-point catalog'
    table (only that section: the grammar examples and recovery matrix
    mention points too, but the catalog is the contract)."""
    points: set[str] = set()
    in_catalog = False
    with open(doc_path) as f:
        for line in f:
            if line.startswith("## "):
                in_catalog = line.strip().lower() == "## fault-point catalog"
                continue
            if not in_catalog:
                continue
            m = _ROW.match(line)
            if m:
                points.add(m.group(1))
    return points


def main(argv: list[str] | None = None) -> int:
    del argv
    from pytorch_distributed_train_tpu.faults.registry import POINTS

    doc = documented_points()
    code = set(POINTS)
    undocumented = sorted(code - doc)
    phantom = sorted(doc - code)
    if not doc:
        print(f"check_fault_points: FOUND NO catalog rows in {DOC} — "
              "was the table renamed?", file=sys.stderr)
        return 1
    ok = True
    if undocumented:
        print(f"check_fault_points: points in faults/registry.py but "
              f"MISSING from the doc catalog: {undocumented}",
              file=sys.stderr)
        ok = False
    if phantom:
        print(f"check_fault_points: points documented in the catalog but "
              f"ABSENT from faults/registry.py: {phantom}", file=sys.stderr)
        ok = False
    if ok:
        print(f"check_fault_points: {len(code)} fault points in sync "
              "between code and docs")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
