#!/usr/bin/env python
"""Cross-check the fault-point catalog in docs/fault_tolerance.md against
the live registry (faults/registry.py POINTS) — in BOTH directions.

Now a thin shim over the analyzer plugin
(``tools/analyze/passes/fault_catalog.py`` — run it with the rest of
the suite via ``python -m tools.analyze --only fault-catalog``); this
entry point keeps the documented CI command and the catalog-sync tests
working unchanged::

    python tools/check_fault_points.py      # exit 0 = in sync

or as a test (tests/test_sentinel.py imports and asserts main() == 0).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DOC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "docs", "fault_tolerance.md")


def documented_points(doc_path: str = DOC) -> set[str]:
    """Point names from the doc catalog (see the plugin for the rules)."""
    from tools.analyze.passes import fault_catalog

    return fault_catalog.documented_points(doc_path)


def main(argv: list[str] | None = None) -> int:
    del argv
    from tools.analyze.passes import fault_catalog

    code, doc = fault_catalog.sync_sets(DOC)
    undocumented = sorted(code - doc)
    phantom = sorted(doc - code)
    if not doc:
        print(f"check_fault_points: FOUND NO catalog rows in {DOC} — "
              "was the table renamed?", file=sys.stderr)
        return 1
    ok = True
    if undocumented:
        print(f"check_fault_points: points in faults/registry.py but "
              f"MISSING from the doc catalog: {undocumented}",
              file=sys.stderr)
        ok = False
    if phantom:
        print(f"check_fault_points: points documented in the catalog but "
              f"ABSENT from faults/registry.py: {phantom}", file=sys.stderr)
        ok = False
    if ok:
        print(f"check_fault_points: {len(code)} fault points in sync "
              "between code and docs")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
