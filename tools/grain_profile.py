"""Profile the grain-vs-threads loader gap (VERDICT r4 weak #4).

BASELINE.md: at native JPEG decode the grain loader does 340 img/s/core
against the threads loader's 445 (-24%), root-caused only as "grain
machinery overhead". This tool reproduces both arms on the same
synthetic tar shard and cProfiles the GRAIN run so the overhead has
names: per-record time in grain's iterator machinery, the batch-of-1
dict repack in the load transform, rng construction, and the final
np.asarray copies are separately attributable. Prints one JSON line
with both throughputs and the top grain-side cost centers.

Run: python tools/grain_profile.py [--n 1024] [--batch 128] [--image-size 224]
"""

from __future__ import annotations

import argparse
import cProfile
import io
import json
import os
import pstats
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _run_epoch(loader) -> tuple[int, float]:
    it = loader.epoch(0)
    if next(it, None) is None:  # warm
        raise SystemExit(
            "epoch yielded zero batches — shrink --batch or raise --n")
    t0 = time.perf_counter()
    seen = 0
    for b in it:
        seen += len(b["label"])
    return seen, time.perf_counter() - t0


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=1024)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--decoder", default="native",
                   choices=["native", "pil"])
    args = p.parse_args(argv)

    import numpy as np

    from pytorch_distributed_train_tpu.config import DataConfig
    from pytorch_distributed_train_tpu.data.datasets import (
        TarShardImageDataset,
        write_jpeg_tar_shard,
    )
    from pytorch_distributed_train_tpu.data.grain_pipeline import (
        GrainHostDataLoader,
    )
    from pytorch_distributed_train_tpu.data.pipeline import HostDataLoader

    tmp = tempfile.mkdtemp(prefix="grain-profile-")
    try:
        shard = os.path.join(tmp, "p-000000.tar")
        write_jpeg_tar_shard(shard, args.n, np.random.default_rng(0))
        ds = TarShardImageDataset(
            shard, args.image_size, train=True,
            native_decode=args.decoder == "native")
        cfg = DataConfig(batch_size=args.batch, num_workers=1)

        threads = HostDataLoader(ds, cfg, train=True, num_hosts=1,
                                 host_id=0)
        seen_t, wall_t = _run_epoch(threads)
        if seen_t == 0:
            raise SystemExit(
                f"--n {args.n} / --batch {args.batch} leaves nothing "
                "after the warm-up batch — need at least 2 batches per "
                "epoch")

        grain = GrainHostDataLoader(ds, cfg, train=True, num_hosts=1,
                                    host_id=0)
        # Throughput epoch runs UNPROFILED — cProfile adds per-call
        # overhead that would inflate exactly the gap this tool
        # quantifies; a second, profiled epoch supplies the cost
        # centers only.
        seen_g, wall_g = _run_epoch(grain)
        prof = cProfile.Profile()
        prof.enable()
        _run_epoch(grain)
        prof.disable()

        s = io.StringIO()
        stats = pstats.Stats(prof, stream=s).sort_stats("cumulative")
        stats.print_stats(30)
        report = s.getvalue()
        # keep the machine-readable top rows: drop pure-wait frames
        # (queue.get / threading waits / time.sleep — consumer
        # blocking is not grain overhead, and misattributing it would
        # recreate the exact confusion this tool resolves)
        WAIT = ("queue.py", "threading.py", "selectors.py",
                "{built-in method time.sleep}", "_wait")
        tops = []
        for line in report.splitlines():
            if "/" in line and "{" not in line and "pstats" not in line:
                if any(w in line for w in WAIT):
                    continue
                parts = line.split()
                if len(parts) >= 6 and parts[0][0].isdigit():
                    tops.append({"ncalls": parts[0],
                                 "cumtime_s": parts[3],
                                 "where": parts[5][-120:]})
            if len(tops) >= 14:
                break
        out = {
            "tool": "grain_profile",
            "decoder": args.decoder,
            "threads_img_s": round(seen_t / wall_t, 1),
            "grain_img_s": round(seen_g / wall_g, 1),
            "gap_pct": round(100 * (1 - (seen_g / wall_g)
                                    / (seen_t / wall_t)), 1),
            "grain_top_cost_centers": tops,
        }
        print(json.dumps(out))
        with open("/tmp/grain_profile_full.txt", "w") as f:
            f.write(report)
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
