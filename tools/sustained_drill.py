#!/usr/bin/env python
"""Sustained real-data training drill (VERDICT r2 #5; BASELINE.json:8).

Configs 2-5's acceptance is SUSTAINED throughput, not 4-step smokes: the
feed-ratio math (BASELINE.md: ~5.7 host cores per v5e chip with native
decode) predicts input-bound risk that only a long run exposes. This tool:

1. synthesizes a multi-GB WebDataset-style `imagenet_tar` set (photo-like
   JPEG entropy, 256-512 px, q85 — same generator as bench.py's decode
   arm) sized so the run cannot fit in page cache warm-up alone;
2. runs ResNet-50 training on it through the REAL trainer (native decode,
   HBM prefetch, the full step path) for ``--minutes`` of wall clock;
3. reports steady-state images/sec/chip and input_stall_pct (the
   trainer's per-log-window stall metric, data/pipeline.py::StallStats),
   acceptance: stall < 5%.

Run on the TPU:   python tools/sustained_drill.py --minutes 10
Host-only rehearsal (no chip): add --cpu (numbers are NOT comparable,
it validates the machinery).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _shard_ok(path: str, n: int) -> bool:
    """A reusable shard must hold exactly ``n`` images — a leftover from a
    smaller --images run would silently shrink the dataset below what the
    throughput accounting (and the larger-than-page-cache premise) assume."""
    import tarfile

    try:
        with tarfile.open(path) as tf:  # header scan only
            return sum(1 for x in tf.getnames() if x.endswith(".jpg")) == n
    except Exception:
        return False


def _write_shard(path: str, n: int, rng, start_key: int = 0) -> None:
    """One shard via the shared writer (atomic via rename; resumable only
    when the existing shard's size checks out)."""
    from pytorch_distributed_train_tpu.data.datasets import (
        write_jpeg_tar_shard,
    )

    if os.path.exists(path):
        if _shard_ok(path, n):
            return
        os.remove(path)  # stale partial/mis-sized shard from another run
    tmp = path + ".tmp"
    write_jpeg_tar_shard(tmp, n, rng, start_key=start_key)
    os.rename(tmp, path)


def synthesize_shards(root: str, n_images: int, shard_size: int = 2048,
                      seed: int = 0) -> None:
    import numpy as np

    os.makedirs(root, exist_ok=True)
    t0 = time.time()
    # One small val shard so epoch-boundary evals have data to read.
    _write_shard(os.path.join(root, "drill-val-000000.tar"),
                 512, np.random.default_rng(seed + 1))
    written = 0
    shard_i = 0
    while written < n_images:
        path = os.path.join(root, f"drill-train-{shard_i:06d}.tar")
        n = min(shard_size, n_images - written)
        _write_shard(path, n, np.random.default_rng((seed, shard_i)),
                     start_key=written)
        written += n
        shard_i += 1
        print(f"[drill] shard {shard_i} ready ({written}/{n_images} imgs, "
              f"{time.time() - t0:.0f}s)", flush=True)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--minutes", type=float, default=10.0)
    p.add_argument("--images", type=int, default=100_000,
                   help="synthetic dataset size (~0.5-1 GB per 20k imgs)")
    p.add_argument("--data-root", default="/tmp/drill_tar")
    p.add_argument("--batch-per-chip", type=int, default=128)
    p.add_argument("--cpu", action="store_true",
                   help="host-only rehearsal on the CPU backend")
    p.add_argument("--image-size", type=int, default=224,
                   help="train resolution (drop for CPU rehearsals — "
                        "full-shape ResNet-50 steps take minutes/core)")
    p.add_argument("--log-every", type=int, default=20,
                   help="steps per metric window (small for rehearsals "
                        "so short runs still capture windows)")
    p.add_argument("--log", default="/tmp/drill_metrics.jsonl")
    args = p.parse_args()

    if args.cpu:
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["JAX_PLATFORMS"] = "cpu"
    synthesize_shards(args.data_root, args.images)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    n_chips = jax.device_count()

    from pytorch_distributed_train_tpu.config import get_preset
    from pytorch_distributed_train_tpu.trainer import Trainer

    cfg = get_preset("resnet50_imagenet")
    cfg.data.dataset = "imagenet_tar"
    cfg.data.data_dir = os.path.join(args.data_root, "drill-{split}-*.tar")
    cfg.data.native_decode = True
    cfg.data.batch_size = args.batch_per_chip * n_chips
    cfg.data.randaugment_num_ops = 0  # jpeg-only shards, native-decode path
    cfg.model.image_size = args.image_size
    cfg.obs.log_every_steps = args.log_every
    cfg.obs.jsonl_path = args.log
    cfg.checkpoint.dir = "/tmp/drill_ckpt"
    cfg.checkpoint.save_every_steps = 10_000_000  # not under test here
    cfg.eval_every_steps = 0  # epoch-boundary evals only (tiny val shard)
    # Enough steps that wall-clock, not the step budget, ends the run.
    cfg.epochs = 0
    cfg.total_steps = 10_000_000

    if os.path.exists(args.log):
        os.remove(args.log)

    t = Trainer(cfg)

    orig_tick = t.meter.tick
    state = {"deadline": None}

    def tick_with_deadline():
        # Clock starts at the FIRST step (post-compile): the drill
        # measures sustained stepping, and compile time would otherwise
        # swallow short rehearsal budgets entirely.
        now = time.monotonic()
        if state["deadline"] is None:
            state["deadline"] = now + args.minutes * 60.0
        elif now >= state["deadline"]:
            raise KeyboardInterrupt  # unwind like a user stop; ckpt saves
        return orig_tick()

    t.meter.tick = tick_with_deadline
    t0 = time.time()
    try:
        t.fit()
    except KeyboardInterrupt:
        pass
    wall = time.time() - t0

    # Steady state: drop the first quarter of log windows (compile + cache
    # warm-up), report the rest.
    rows = []
    with open(args.log) as f:
        for line in f:
            r = json.loads(line)
            if r.get("tag") == "train":
                rows.append(r)
    tail = rows[len(rows) // 4:]
    if not tail:
        raise SystemExit("no steady-state windows captured — run longer")
    ips = [r["images_per_sec_per_chip"] for r in tail
           if "images_per_sec_per_chip" in r]
    stalls = [r["input_stall_pct"] for r in tail if "input_stall_pct" in r]
    result = {
        "metric": "sustained_resnet50_images_per_sec_per_chip",
        "value": round(sum(ips) / max(len(ips), 1), 1),
        "unit": "images/sec/chip (sustained)",
        "wall_minutes": round(wall / 60.0, 1),
        "windows": len(tail),
        "input_stall_pct_mean": round(sum(stalls) / max(len(stalls), 1), 2),
        "input_stall_pct_max": round(max(stalls), 2) if stalls else None,
        "stall_acceptance_lt_5pct":
            bool(stalls) and max(stalls) < 5.0,
        "n_chips": n_chips,
        "backend": "cpu" if args.cpu else "tpu",
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
