#!/usr/bin/env python
"""Compile every Pallas kernel variant FOR REAL TPU — no device needed.

Until round 5 the Pallas flash-attention kernels were validated in
interpret mode only: the axon tunnel hangs on RUNTIME Mosaic compiles
(BASELINE.md caveat), and four rounds of wedged lease meant the kernels
had never been through the actual Mosaic -> TPU pipeline. This tool
closes most of that gap deviceless: `jax.experimental.topologies` +
the local libtpu compile AOT against a v5e topology, so every kernel
variant below runs the REAL Mosaic lowering, Mosaic->LLO, vector
layout assignment, and XLA:TPU buffer assignment. Compile success +
cost analysis is not execution — numerics on hardware remain pending —
but it eliminates the entire class of "kernel won't build for TPU"
failures (unsupported ops, layout constraints, VMEM overflows,
misaligned block shapes) that interpret mode cannot see.

Writes MOSAIC_AOT.json: per-variant ok/error + cost/memory analysis.

Run:  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python tools/mosaic_aot_battery.py
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_train_tpu.utils.deviceless import (  # noqa: E402
    scrub_axon_identity,
)

scrub_axon_identity()


def _topology():
    from jax.experimental import topologies

    return topologies.get_topology_desc(topology_name="v5e:2x2x1",
                                        platform="tpu")


def _compile(fn, args, shardings=None) -> dict:
    import jax

    t0 = time.time()
    try:
        lowered = jax.jit(fn).lower(*args)
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        ma = compiled.memory_analysis()
        return {
            "ok": True,
            "compile_s": round(time.time() - t0, 1),
            "bytes_accessed_mib": round(
                float(ca.get("bytes accessed", 0.0)) / 2**20, 2),
            "temp_mib": round(
                getattr(ma, "temp_size_in_bytes", 0) / 2**20, 2),
        }
    except Exception as e:  # noqa: BLE001 — record, don't crash battery
        return {"ok": False, "compile_s": round(time.time() - t0, 1),
                "error": f"{type(e).__name__}: {str(e)[:300]}"}


def main(argv=None) -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    from pytorch_distributed_train_tpu.ops.flash_attention import (
        flash_attention,
        flash_attention_chunk,
    )

    topo = _topology()
    dev0 = topo.devices[0]
    sh1 = jax.sharding.SingleDeviceSharding(dev0)

    B, S, H, D = 1, 1024, 4, 64
    Hkv = 2  # GQA variants: 4 query heads over 2 KV heads

    def sds(shape, dtype=jnp.bfloat16):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sh1)

    q = sds((B, S, H, D))
    kv = sds((B, S, H, D))
    kv_g = sds((B, S, Hkv, D))

    out = {"tool": "mosaic_aot_battery", "topology": "v5e:2x2x1",
           "date": time.strftime("%Y-%m-%d"),
           "note": ("AOT Mosaic->TPU compile validation (deviceless); "
                    "proves the kernels build for real v5e — execution "
                    "numerics still pending a healthy lease"),
           "variants": {}}
    V = out["variants"]

    # ---- forward variants
    V["fwd.causal"] = _compile(
        functools.partial(flash_attention, causal=True), (q, kv, kv))
    V["fwd.full"] = _compile(
        functools.partial(flash_attention, causal=False), (q, kv, kv))
    V["fwd.causal.gqa"] = _compile(
        functools.partial(flash_attention, causal=True), (q, kv_g, kv_g))
    V["fwd.causal.window256"] = _compile(
        functools.partial(flash_attention, causal=True, window=256),
        (q, kv, kv))

    # ---- backward variants (grad through the custom VJP = both bwd
    # kernels: dq and the accumulating dkv)
    def loss(q_, k_, v_, **kw):
        return flash_attention(q_, k_, v_, **kw).astype(jnp.float32).sum()

    V["bwd.causal"] = _compile(
        jax.grad(functools.partial(loss, causal=True), argnums=(0, 1, 2)),
        (q, kv, kv))
    V["bwd.causal.gqa"] = _compile(
        jax.grad(functools.partial(loss, causal=True), argnums=(0, 1, 2)),
        (q, kv_g, kv_g))
    V["bwd.causal.window256"] = _compile(
        jax.grad(functools.partial(loss, causal=True, window=256),
                 argnums=(0, 1, 2)),
        (q, kv, kv))

    # ---- ring chunk kernel (traced global positions, GQA unexpanded)
    qpos = jax.ShapeDtypeStruct((256,), jnp.int32, sharding=sh1)
    kpos = jax.ShapeDtypeStruct((256,), jnp.int32, sharding=sh1)
    V["chunk.causal.gqa"] = _compile(
        functools.partial(flash_attention_chunk, causal=True),
        (sds((B, 256, H, D)), sds((B, 256, Hkv, D)),
         sds((B, 256, Hkv, D)), qpos, kpos))

    # ---- ring attention end-to-end: Mosaic INSIDE shard_map with
    # ppermute collectives over a real 4-device v5e mesh — the
    # long-context production path. ring_attention_local is called
    # directly with interpret=False (the public wrapper's impl gating
    # keys interpret on the RUNTIME backend, which is CPU here; the
    # point of this battery is the TPU lowering).
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from pytorch_distributed_train_tpu.ops.ring_attention import (
        ring_attention_local,
    )

    mesh = Mesh(np.asarray(topo.devices).reshape(4), ("context",))
    seq_spec = P(None, "context", None, None)
    seq_sh = NamedSharding(mesh, seq_spec)

    def ring_fn(q_, k_, v_):
        # deferred like every jax import here: scrub_axon_identity()
        # must run before anything touches jax (compat imports it)
        from pytorch_distributed_train_tpu.utils.compat import shard_map

        body = functools.partial(
            ring_attention_local, axis_name="context", axis_size=4,
            causal=True, chunk_impl="pallas", interpret=False)
        return shard_map(body, mesh=mesh,
                         in_specs=(seq_spec, seq_spec, seq_spec),
                         out_specs=seq_spec,
                         check_vma=False)(q_, k_, v_)

    V["ring.pallas.4dev"] = _compile(
        ring_fn,
        (jax.ShapeDtypeStruct((B, 2048, H, D), jnp.bfloat16,
                              sharding=seq_sh),
         jax.ShapeDtypeStruct((B, 2048, Hkv, D), jnp.bfloat16,
                              sharding=seq_sh),
         jax.ShapeDtypeStruct((B, 2048, Hkv, D), jnp.bfloat16,
                              sharding=seq_sh)))

    # ---- fused weight-dequant GEMV kernels (ops/quant_matmul.py):
    # the AOT_AB.json finding was that XLA materializes bf16 weights on
    # the weight-only decode path; these variants prove the fused
    # kernels (a) compile for v5e and (b) stream the QUANTIZED bytes —
    # compare against the unfused dequant@matmul at identical shapes.
    from pytorch_distributed_train_tpu import quant
    from pytorch_distributed_train_tpu.ops.quant_matmul import (
        quant_matmul,
    )

    Hq, Nq = 2048, 5504
    wq = jax.ShapeDtypeStruct((Hq, Nq), jnp.float32)
    q8 = jax.eval_shape(quant.quantize_leaf, wq)
    q4 = jax.eval_shape(quant.quantize_leaf_int4, wq)
    x1 = sds((1, Hq), jnp.bfloat16)
    s8 = {k: sds(v.shape, v.dtype) for k, v in q8.items()}
    s4 = {k: sds(v.shape, v.dtype) for k, v in q4.items()}
    V["w8.gemv.fused"] = _compile(quant_matmul, (x1, s8))
    V["w4.gemv.fused"] = _compile(quant_matmul, (x1, s4))
    V["w4.gemv.unfused"] = _compile(
        lambda x_, q_: x_ @ quant.dequantize_leaf(q_, jnp.bfloat16),
        (x1, s4))
    V["w8.gemv.unfused"] = _compile(
        lambda x_, q_: x_ @ quant.dequantize_leaf(q_, jnp.bfloat16),
        (x1, s8))

    n_ok = sum(1 for v in V.values() if v["ok"])
    out["summary"] = f"{n_ok}/{len(V)} variants compile for v5e"
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "MOSAIC_AOT.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"summary": out["summary"],
                      "failures": {k: v.get("error") for k, v in V.items()
                                   if not v["ok"]}}))
    return 0 if n_ok == len(V) else 1


if __name__ == "__main__":
    raise SystemExit(main())
