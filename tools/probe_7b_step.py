#!/usr/bin/env python
"""Execute a 7B-GEOMETRY training step on the local chip (VERDICT r3 #6).

docs/MEMFIT_7B.md grounds the llama2_7b fit claim in AOT compile
analysis, but its temps column is an extrapolation with a 15x spread
between estimate and upper bound — because no 7B-geometry step had ever
*executed*. This probe closes that: it trains a REDUCED-LAYER model
whose per-layer shapes are exactly Llama-2 7B's (hidden 4096, mlp 11008,
32 heads, vocab 32000, seq 4096) with the shipping memory levers (fused
chunked LM-head loss, remat, adafactor), measures

- actual per-device memory in use (device_memory_stats — the real
  resident footprint, not a CPU-backend proxy), at two depths so the
  per-layer increment is MEASURED, and
- step time at both depths, so the per-layer compute cost and a
  tokens/sec/chip extrapolation to the full 32 layers are slope-based
  (intercept absorbs the head/embed cost shared by all depths).

Writes one JSON line (the bench_sweep contract). The depths default to
(2, 4); HBM permitting the probe also tries the largest depth that fits
to tighten the extrapolation.

Run on the TPU sandbox:  python tools/probe_7b_step.py [--seq 4096]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _arm_watchdog, _disarm_watchdog, _touch, _wait_for_backend  # noqa: E402


def measure_depth(layers: int, seq: int, batch: int) -> dict:
    """One training run at 7B per-layer geometry with ``layers`` layers:
    returns step time and device memory stats."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.config import (
        MeshConfig,
        ModelConfig,
        OptimConfig,
        PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.losses import get_loss_fn
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.optim import make_optimizer
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh
    from pytorch_distributed_train_tpu.parallel.partition import (
        rules_for_model,
    )
    from pytorch_distributed_train_tpu.train_state import TrainState

    cfg = ModelConfig(
        name="llama", vocab_size=32000, hidden_size=4096, num_layers=layers,
        num_heads=32, num_kv_heads=32, mlp_dim=11008, max_seq_len=seq,
        remat=True, remat_policy="full", fused_lm_loss=True,
        attention_impl="chunked",
    )
    mesh = build_mesh(MeshConfig(data=-1))
    model = build_model(cfg, PrecisionConfig(compute_dtype="bfloat16"))
    tx, _ = make_optimizer(
        OptimConfig(name="adafactor", learning_rate=1e-3,
                    schedule="constant", warmup_steps=0), total_steps=100)
    rules = rules_for_model("llama")

    def init_state(rng):
        variables = model.init({"params": rng},
                               jnp.zeros((2, seq), jnp.int32), train=False)
        return TrainState.create(params=variables["params"], tx=tx)

    rng = jax.random.PRNGKey(0)
    shape = jax.eval_shape(init_state, rng)
    sharding = steps_lib.state_shardings(mesh, rules, shape)
    state = jax.jit(init_state, out_shardings=sharding)(rng)
    _touch()
    step = steps_lib.jit_train_step(
        steps_lib.make_train_step(
            model, get_loss_fn("fused_causal_lm_xent"), tx),
        mesh, sharding)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 32000, (batch, seq)), jnp.int32)
    batch_d = {"input_ids": ids}
    state, metrics = step(state, batch_d, rng)  # compile + warmup
    loss = float(metrics["loss"])
    assert np.isfinite(loss), loss
    _touch()
    n_steps = 5
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, batch_d, rng)
    loss = float(metrics["loss"])  # forces the donated-state chain
    wall = time.perf_counter() - t0
    mem = {}
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        mem = {k: int(v) for k, v in stats.items()
               if k in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit")}
    except Exception:
        pass
    del state, step, batch_d  # free HBM before the next depth
    return {"layers": layers, "step_s": wall / n_steps, "loss": loss,
            **mem}


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seq", type=int, default=4096)
    p.add_argument("--batch", type=int, default=1,
                   help="per-chip batch (7B preset trains bs1/chip x many "
                        "chips; the probe measures per-layer slopes, not "
                        "batch scaling)")
    p.add_argument("--depths", type=int, nargs="+", default=[2, 4])
    args = p.parse_args()

    _arm_watchdog(float(os.environ.get("BENCH_TIMEOUT_S", "1800")))
    _wait_for_backend()

    rows = []
    for d in sorted(args.depths):
        try:
            rows.append(measure_depth(d, args.seq, args.batch))
            print(f"# depth {d}: {rows[-1]}", file=sys.stderr, flush=True)
        except Exception as exc:  # OOM at a depth: record and stop going up
            print(f"# depth {d} failed: {type(exc).__name__}: "
                  f"{str(exc)[:300]}", file=sys.stderr, flush=True)
            rows.append({"layers": d, "error": type(exc).__name__})
            break
    _disarm_watchdog()
    ok = [r for r in rows if "step_s" in r]
    record: dict = {"metric": "llama7b_geometry_probe", "value": None,
                    "unit": "tokens/sec/chip (extrapolated to 32 layers)",
                    "vs_baseline": 1.0, "seq": args.seq,
                    "batch_per_chip": args.batch, "depths": rows}
    if len(ok) >= 2:
        lo, hi = ok[0], ok[-1]
        dl = hi["layers"] - lo["layers"]
        per_layer_s = (hi["step_s"] - lo["step_s"]) / dl
        base_s = lo["step_s"] - per_layer_s * lo["layers"]
        step32 = base_s + 32 * per_layer_s
        record["value"] = round(args.batch * args.seq / step32, 2)
        record["per_layer_ms"] = round(per_layer_s * 1e3, 2)
        record["overhead_ms"] = round(base_s * 1e3, 2)
        if "peak_bytes_in_use" in hi and "peak_bytes_in_use" in lo:
            per_layer_b = (hi["peak_bytes_in_use"]
                           - lo["peak_bytes_in_use"]) / dl
            record["per_layer_peak_gib"] = round(per_layer_b / 1024**3, 3)
            record["projected_32l_peak_gib"] = round(
                (lo["peak_bytes_in_use"] + per_layer_b
                 * (32 - lo["layers"])) / 1024**3, 2)
    print(json.dumps(record), flush=True)
    return 0 if record["value"] is not None else 4


if __name__ == "__main__":
    sys.exit(main())
