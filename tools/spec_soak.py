"""Speculative-serving host-loop soak: is proposal time flat in context?

The round-4 review flagged the prompt-lookup proposal path as a
potential host-side bottleneck: the original implementation rescanned
each row's full history every round (O(context) Python per row per
step), invisible in any stat. Round 5 replaced it with a per-row
incremental n-gram index (serving._ngram_build/_append/_propose,
O(1) per committed token) and exposed host_ms/device_ms in
ContinuousBatcher.stats.

This soak measures BOTH implementations' per-round proposal cost at
growing context lengths (slots x contexts of 512..8k tokens, the
shapes a 4k-context serving host actually sees) and prints one JSON
line. Pass/fail intuition: rescan cost grows ~linearly with context;
index cost must stay flat (sublinear) — the row's verdict field says
whether it did. Pure host benchmark: no device, no model, runs
anywhere in milliseconds.

Usage: python tools/spec_soak.py [--slots 16] [--k 4] [--ngram 3]
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")


def _mk_ctx(n: int, seed: int) -> list[int]:
    # zipf-ish token stream with enough repetition for real matches —
    # the regime prompt lookup exists for
    import random

    r = random.Random(seed)
    ctx: list[int] = []
    while len(ctx) < n:
        if ctx and r.random() < 0.4:  # echo an earlier span
            start = r.randrange(len(ctx))
            ctx.extend(ctx[start:start + r.randrange(2, 8)])
        else:
            ctx.append(r.randrange(256))
    return ctx[:n]


def main(argv=None) -> int:
    from pytorch_distributed_train_tpu.serving import (
        _ngram_append,
        _ngram_build,
        _ngram_propose,
    )
    from pytorch_distributed_train_tpu.speculative import (
        propose_from_context,
    )

    p = argparse.ArgumentParser()
    p.add_argument("--slots", type=int, default=16)
    p.add_argument("--k", type=int, default=4)
    p.add_argument("--ngram", type=int, default=3)
    p.add_argument("--rounds", type=int, default=200)
    args = p.parse_args(argv)

    lengths = [512, 1024, 2048, 4096, 8192]
    rows = []
    for n in lengths:
        ctxs = [_mk_ctx(n, s) for s in range(args.slots)]
        idxs = [_ngram_build(c, args.ngram) for c in ctxs]

        t0 = time.perf_counter()
        for _ in range(args.rounds):
            for c, ix in zip(ctxs, idxs):
                _ngram_propose(c, ix, args.ngram, args.k)
        idx_us = (time.perf_counter() - t0) * 1e6 / (
            args.rounds * args.slots)

        # amortized index maintenance: one commit per row per round
        t0 = time.perf_counter()
        for i in range(args.rounds):
            for c, ix in zip(ctxs, idxs):
                _ngram_append(c, ix, i % 256, args.ngram)
        app_us = (time.perf_counter() - t0) * 1e6 / (
            args.rounds * args.slots)

        scan_rounds = max(1, args.rounds // 10)  # rescan is slow; sample
        t0 = time.perf_counter()
        for _ in range(scan_rounds):
            for c in ctxs:
                propose_from_context(c, args.k, args.ngram)
        scan_us = (time.perf_counter() - t0) * 1e6 / (
            scan_rounds * args.slots)
        rows.append({"context": n, "index_us_per_row": round(idx_us, 2),
                     "append_us_per_row": round(app_us, 2),
                     "rescan_us_per_row": round(scan_us, 2)})

    # verdict: index cost at 8k vs 512 must not scale with context
    # (allow 3x noise headroom; the rescan typically scales ~16x)
    idx_ratio = rows[-1]["index_us_per_row"] / max(
        rows[0]["index_us_per_row"], 1e-9)
    scan_ratio = rows[-1]["rescan_us_per_row"] / max(
        rows[0]["rescan_us_per_row"], 1e-9)
    out = {
        "tool": "spec_soak",
        "slots": args.slots, "k": args.k, "ngram": args.ngram,
        "rows": rows,
        "index_8k_over_512": round(idx_ratio, 2),
        "rescan_8k_over_512": round(scan_ratio, 2),
        "index_sublinear": idx_ratio < 3.0,
    }
    print(json.dumps(out))
    return 0 if out["index_sublinear"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
