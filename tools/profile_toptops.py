#!/usr/bin/env python
"""Profile the north-star (or any vision/LM bench config) and report the
top-ops limiter breakdown (VERDICT r3 #3).

Runs a short profiled training window (jax.profiler.trace) on the
default bench shapes, then aggregates the XPlane dump with
utils/xplane: per-class ms (fusion / convolution / matmul / collective /
copy / infeed) and the top ops. This is the profiler-backed answer to
"what limits ResNet-50's MFU" — a JSON line the sweep captures, plus the
human-readable table on stderr.

Run on hardware:  python tools/profile_toptops.py [--model resnet50]
                  [--steps 10] [--keep-dump DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import _arm_watchdog, _disarm_watchdog, _touch, _wait_for_backend  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="resnet50",
                   help="resnet50|vit_b16|bert_base|llama")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch-per-chip", type=int, default=0)
    p.add_argument("--stem", default="conv",
                   choices=["conv", "space_to_depth"])
    p.add_argument("--keep-dump", default="",
                   help="persist the xplane dump here (default: tmp, "
                        "deleted)")
    p.add_argument("--top", type=int, default=12)
    args = p.parse_args()

    _arm_watchdog(float(os.environ.get("BENCH_TIMEOUT_S", "1800")))
    _wait_for_backend()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.config import (
        MeshConfig,
        ModelConfig,
        OptimConfig,
        PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.losses import get_loss_fn
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.optim import make_optimizer
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh
    from pytorch_distributed_train_tpu.parallel.partition import (
        rules_for_model,
    )
    from pytorch_distributed_train_tpu.train_state import TrainState
    from pytorch_distributed_train_tpu.utils import flops as flops_lib
    from pytorch_distributed_train_tpu.utils import xplane

    vision = args.model in ("resnet18", "resnet50", "vit_b16")
    if vision:
        cfg = ModelConfig(name=args.model, num_classes=1000, image_size=224,
                          stem=args.stem)
        loss_name = "softmax_xent"
        opt = OptimConfig(name="momentum", learning_rate=0.1,
                          schedule="constant", warmup_steps=0)
        bpc = args.batch_per_chip or 128
    elif args.model == "bert_base":
        cfg = ModelConfig(name="bert_base", vocab_size=30522,
                          hidden_size=768, num_layers=12, num_heads=12,
                          mlp_dim=3072, max_seq_len=512)
        loss_name = "mlm_xent"
        opt = OptimConfig(name="lamb", learning_rate=1e-3,
                          schedule="constant", warmup_steps=0)
        bpc = args.batch_per_chip or 32
    else:
        cfg = ModelConfig(name="llama", vocab_size=32000, hidden_size=2048,
                          num_layers=16, num_heads=16, num_kv_heads=16,
                          mlp_dim=5504, max_seq_len=2048, remat=True,
                          fused_lm_loss=True, attention_impl="auto")
        loss_name = "fused_causal_lm_xent"
        opt = OptimConfig(name="adafactor", learning_rate=1e-3,
                          schedule="constant", warmup_steps=0)
        bpc = args.batch_per_chip or 4

    mesh = build_mesh(MeshConfig(data=-1))
    model = build_model(cfg, PrecisionConfig(compute_dtype="bfloat16"))
    tx, _ = make_optimizer(opt, total_steps=1000)
    rules = rules_for_model(args.model)

    def init_state(rng):
        if vision:
            dummy = (jnp.zeros((2, 224, 224, 3)),)
        else:
            dummy = (jnp.zeros((2, cfg.max_seq_len), jnp.int32),)
        variables = model.init({"params": rng}, *dummy, train=False)
        return TrainState.create(params=variables["params"], tx=tx,
                                 batch_stats=variables.get("batch_stats",
                                                           {}))

    rng = jax.random.PRNGKey(0)
    shape = jax.eval_shape(init_state, rng)
    sharding = steps_lib.state_shardings(mesh, rules, shape)
    state = jax.jit(init_state, out_shardings=sharding)(rng)
    _touch()
    step = steps_lib.jit_train_step(
        steps_lib.make_train_step(model, get_loss_fn(loss_name), tx),
        mesh, sharding)

    n = bpc * jax.device_count()
    gen = np.random.default_rng(0)
    if vision:
        batch = {"image": jnp.asarray(
            gen.standard_normal((n, 224, 224, 3)), jnp.float32),
            "label": jnp.asarray(gen.integers(0, 1000, n), jnp.int32)}
        items = n
    elif args.model == "bert_base":
        from pytorch_distributed_train_tpu.data.datasets import (
            synthetic_mlm,
        )

        ds = synthetic_mlm(n, 512, cfg.vocab_size, mlm_prob=0.15)
        batch = {k: jnp.asarray(v) for k, v in
                 ds.get_batch(np.arange(n), gen, train=True).items()}
        items = n * 512
    else:
        batch = {"input_ids": jnp.asarray(
            gen.integers(0, cfg.vocab_size, (n, cfg.max_seq_len)),
            jnp.int32)}
        items = n * cfg.max_seq_len

    for _ in range(3):  # compile + warm
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])
    _disarm_watchdog()

    dump = args.keep_dump or tempfile.mkdtemp(prefix="toptops-")
    try:
        with jax.profiler.trace(dump):
            t0 = time.perf_counter()
            for _ in range(args.steps):
                state, metrics = step(state, batch, rng)
            loss = float(metrics["loss"])
            wall = time.perf_counter() - t0
        assert np.isfinite(loss)
        per_step = wall / args.steps
        per_chip = items / per_step / jax.device_count()

        files = xplane.find_xplane_files(dump)
        planes = []
        if files:
            planes = xplane.summarize_xspace(xplane.load_xspace(files[0]))
            print(xplane.report(dump, top=args.top), file=sys.stderr,
                  flush=True)
        by_class, top_ops = {}, []
        if planes:
            dev = planes[0]
            scale = 100.0 / max(dev["total_ms"], 1e-9)
            by_class = {c: round(ms * scale, 1)
                        for c, ms in dev["by_class"].items()}
            top_ops = [{"op": name[:120], "ms": round(ms, 2), "n": cnt}
                       for name, ms, cnt in dev["ops"][:args.top]]
        fpi = flops_lib.train_flops_per_item(
            cfg, None if vision else cfg.max_seq_len)
        mfu = flops_lib.mfu_pct(per_chip,
                                fpi, flops_lib.device_peak_flops())
        print(json.dumps({
            "metric": f"{args.model}_profile_step_ms",
            "value": round(per_step * 1e3, 2),
            "unit": "ms/step (profiled window)",
            "vs_baseline": 1.0,
            "items_per_sec_per_chip": round(per_chip, 2),
            "mfu_pct": round(mfu, 2) if mfu is not None else None,
            "by_class_pct": by_class,
            "top_ops": top_ops,
        }), flush=True)
    finally:
        if not args.keep_dump:
            shutil.rmtree(dump, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
