#!/bin/sh
# Watch the TPU lease; the moment a probe passes, run the full queued
# benchmark battery (tools/bench_sweep.py -> BENCH_SWEEP.json). The
# round-1/2/3 pattern is a lease wedged for hours that may heal at any
# time — a human-free capture path means a recovery window is never
# missed. Single-instance via pidfile; probe cadence 300 s.
cd "$(dirname "$0")/.." || exit 2
PIDFILE=/tmp/lease_watch.pid
if [ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
    echo "lease_watch already running (pid $(cat "$PIDFILE"))"
    exit 0
fi
echo $$ > "$PIDFILE"
trap 'rm -f "$PIDFILE"' EXIT
trap 'rm -f "$PIDFILE"; exit 1' INT TERM
echo "[lease_watch] $(date -u +%FT%TZ) watching (probe every 300s)"
while :; do
    if sh tools/tpu_probe.sh 90 >/dev/null 2>&1; then
        echo "[lease_watch] $(date -u +%FT%TZ) lease HEALTHY — running sweep"
        ${PYTHON:-python3} tools/bench_sweep.py --timeout 1500
        rc=$?
        echo "[lease_watch] $(date -u +%FT%TZ) sweep done rc=$rc"
        [ "$rc" -ne 3 ] && break   # rc 3 = lease re-wedged mid-sweep: keep watching
    fi
    sleep 300
done
