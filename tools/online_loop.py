#!/usr/bin/env python
"""online_loop — the online post-training plane, end to end.

One supervised loop closes the serving→training→serving cycle
(docs/online_training.md): a fake-backend serving fleet (subprocess
``serve_http --fake-backend --advertise`` replicas, the autoscale-drill
launcher pattern) answers rollout traffic; the harvested completions —
each stamped with the ``weight_version`` that generated it — convert to
a GRPO batch (online/rollouts.py) and feed an in-process tiny-gpt2
trainer; the updated params publish through the weight plane
(online/publisher.py, ckpt shard wire format over the launcher store);
and ``Router.weight_sync`` swaps every replica live, between scheduler
quanta, with ZERO failed client requests.

Each cycle runs under one forced trace: the collector's completion
requests, the replica-side swap handlers and the driver's own
rollout/train/publish spans all carry the same trace id, so
``tools/timeline_report.py --trace <id>`` renders the causal chain

    rollout batch (@ version V) → train steps → weight publish (V+1)
        → per-replica swap (V → V+1)

with the old/new ``weight_version`` correlation tags visible on both
the trainer and replica sides.

``--smoke`` is the tier-1 drill (tests/test_zonline_loop.py): 2
replicas, 2 cycles (= 2 fleet swaps), background traffic asserting the
zero-failed contract, a few seconds on CPU. The default run is the
slow acceptance drill with more cycles and heavier traffic.

Prints one JSON report line; exit 0 = pass.

Usage::

    python tools/online_loop.py --smoke
    python tools/online_loop.py [--replicas 2] [--cycles 3]
        [--steps-per-cycle 2] [--group-size 4] [--max-tokens 8]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_PROMPTS = (
    "the quick brown fox jumps over",
    "in a hole in the ground there",
    "it was the best of times it",
    "call me ishmael some years ago",
)


def _spawn_replica(idx: int, *, store_addr: str, events_dir: str,
                   trace_dir: str, slots: int, step_delay: float,
                   timeout_s: float = 30.0):
    """One ``serve_http --fake-backend --advertise`` subprocess.
    Distinct PROCESS_ID per replica: each gets its own trace/journal
    writer identity AND its own process-wide weight_version correlation
    tag (in-process replicas would fight over one tag set)."""
    env = dict(os.environ)
    env["TPUSTORE_ADDR"] = store_addr
    env["PDTT_EVENTS_DIR"] = events_dir
    env["PROCESS_ID"] = str(idx)
    env.setdefault("JAX_PLATFORMS", "cpu")
    here = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, os.path.join(here, "serve_http.py"),
           "--fake-backend", "--port", "0", "--advertise",
           "--slots", str(slots),
           "--fake-step-delay", str(step_delay),
           "--trace-dir", trace_dir,
           "--drain-grace", "5"]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    addr = None
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        line = proc.stdout.readline() if proc.stdout else ""
        if not line:
            if proc.poll() is not None:
                break
            continue
        if line.startswith("serving on http://"):
            addr = line.split("http://", 1)[1].split()[0].strip("/")
            break
    if addr is None:
        try:
            proc.kill()
        except OSError:
            pass
        return None, None

    def pump():
        try:
            for _line in proc.stdout:
                pass
        except (OSError, ValueError):
            pass

    threading.Thread(target=pump, daemon=True,
                     name=f"online-replica-pump-{idx}").start()
    return addr, proc


def _build_trainer(seq_len: int, steps_total: int):
    """Tiny-gpt2 GRPO trainer, the test_train_step construction path:
    real model registry, real partition rules, real jit train step —
    just small enough to live beside the serving fleet on CPU."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.config import (
        MeshConfig,
        ModelConfig,
        OptimConfig,
        PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.losses import make_grpo_loss
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.optim import make_optimizer
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh
    from pytorch_distributed_train_tpu.parallel.partition import (
        rules_for_model,
    )
    from pytorch_distributed_train_tpu.train_state import TrainState

    model_cfg = ModelConfig(name="gpt2", hidden_size=32, num_layers=1,
                            num_heads=2, mlp_dim=64, vocab_size=512,
                            max_seq_len=seq_len, dropout_rate=0.0)
    opt_cfg = OptimConfig(name="momentum", learning_rate=0.01,
                          schedule="constant", warmup_steps=0,
                          weight_decay=0.0)
    mesh = build_mesh(MeshConfig(data=1), jax.devices()[:1])
    model = build_model(model_cfg, PrecisionConfig())
    loss_fn = make_grpo_loss()
    tx, _ = make_optimizer(opt_cfg, total_steps=max(1, steps_total))
    rules = rules_for_model(model_cfg.name)

    def init_state(rng):
        variables = model.init({"params": rng},
                               jnp.zeros((1, 4), jnp.int32), train=False)
        return TrainState.create(params=variables["params"], tx=tx,
                                 batch_stats=variables.get(
                                     "batch_stats", {}))

    rng = jax.random.PRNGKey(0)
    shape = jax.eval_shape(init_state, rng)
    sharding = steps_lib.state_shardings(mesh, rules, shape)
    state = jax.jit(init_state, out_shardings=sharding)(rng)
    step = steps_lib.jit_train_step(
        steps_lib.make_train_step(model, loss_fn, tx,
                                  model_health=True),
        mesh, sharding, batch_axes=("data", "fsdp"))

    @jax.jit
    def behavior_logprobs(params, ids):
        # Per-token logprobs of `ids` under `params`, in the TRAINER's
        # tokenization. Serving returns logprobs in its own token
        # space, which need not align with the trainer's re-encoding —
        # so the behavior policy is recomputed here, against the
        # harvest-version weights, before any update applies. Column 0
        # is padding: the loss reads [:, 1:].
        logits, _, _ = steps_lib.apply_model(
            model, params, {}, {"input_ids": ids}, train=False,
            dropout_rng=None)
        lp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), -1)
        logp = jnp.take_along_axis(lp, ids[:, 1:, None], axis=-1)[..., 0]
        return jnp.pad(logp, ((0, 0), (1, 0)))

    return state, step, behavior_logprobs


def _encode(text: str) -> list[int]:
    # trainer-side byte tokenizer: ids 1..255 (0 stays the pad id);
    # only has to be consistent with ITSELF — to_grpo_batch re-encodes
    # prompt and completion with this same fn
    return [1 + (b % 255) for b in text.encode("utf-8")]


def _reward(prompt: str, completion: str) -> float:
    # deterministic toy reward with in-group variance: mean byte value
    # of the sampled completion. Fake-backend samples differ across the
    # n= group (tokens are a function of prompt AND uid), so distinct
    # completions score distinctly and group-relative advantages are
    # non-degenerate.
    data = completion.encode("utf-8")
    if not data:
        return 0.0
    return sum(data) / (255.0 * len(data))


def _traffic(router, stop: threading.Event, counts: dict,
             lock: threading.Lock, *, max_tokens: int,
             gap_s: float) -> None:
    """Background client load through the failover router for the
    zero-failed-requests contract: a 5xx or transport escape is a hard
    failure; 429/504 are honest admission answers (and should not
    appear at this load anyway)."""
    i = 0
    while not stop.is_set():
        body = {"prompt": f"background req {i} xxxx",
                "max_tokens": max_tokens}
        raw = json.dumps(body).encode()
        try:
            status, _ = router.request("/v1/completions", raw, body)
        except Exception:  # noqa: BLE001 — any escape is a failure
            status = -1
        with lock:
            if status == 200:
                counts["ok"] = counts.get("ok", 0) + 1
            elif status in (429, 504):
                counts["shed"] = counts.get("shed", 0) + 1
            else:
                counts["failed"] = counts.get("failed", 0) + 1
        i += 1
        time.sleep(gap_s)


def run_loop(*, replicas: int = 2, cycles: int = 2,
             steps_per_cycle: int = 2, group_size: int = 2,
             max_tokens: int = 8, seq_len: int = 48,
             n_prompts: int = 2, step_delay: float = 0.0,
             traffic_gap_s: float = 0.08, slots: int = 8) -> dict:
    import dataclasses as _dc

    from pytorch_distributed_train_tpu.elastic import discover_replicas
    from pytorch_distributed_train_tpu.faults.retry import retry_call
    from pytorch_distributed_train_tpu.native.store import (
        StoreClient,
        StoreServer,
    )
    from pytorch_distributed_train_tpu.obs import events as events_lib
    from pytorch_distributed_train_tpu.obs import spans as spans_lib
    from pytorch_distributed_train_tpu.obs import tracing
    from pytorch_distributed_train_tpu.obs.registry import get_registry
    from pytorch_distributed_train_tpu.online import (
        RolloutCollector,
        WeightPublisher,
        to_grpo_batch,
    )
    from pytorch_distributed_train_tpu.serving_plane.router import (
        HealthProber,
        ReplicaSet,
        Router,
        http_json,
    )

    report: dict = {"replicas": replicas, "cycles": cycles,
                    "steps_per_cycle": steps_per_cycle}
    events_dir = tempfile.mkdtemp(prefix="online-loop-events-")
    trace_dir = tempfile.mkdtemp(prefix="online-loop-traces-")
    report["events_dir"] = events_dir
    report["trace_dir"] = trace_dir
    os.environ["PDTT_EVENTS_DIR"] = events_dir
    events_lib.configure(events_dir, who="trainer")
    tracing.configure(trace_dir, who="trainer")
    spans_lib.set_correlation_tags(role="trainer", weight_version="0")

    server = StoreServer()
    store_addr = f"127.0.0.1:{server.port}"
    os.environ["TPUSTORE_ADDR"] = store_addr
    report["store"] = store_addr
    store = StoreClient("127.0.0.1", server.port)

    procs: list = []
    rset = ReplicaSet()
    prober = HealthProber(rset, interval_s=0.25, down_after=3,
                          refresh=lambda: discover_replicas(store))
    router = Router(rset, timeout_s=30.0)
    stop = threading.Event()
    counts: dict = {}
    lock = threading.Lock()
    cycle_log: list[dict] = []

    try:
        for i in range(replicas):
            addr, proc = _spawn_replica(
                i + 1, store_addr=store_addr, events_dir=events_dir,
                trace_dir=trace_dir, slots=slots,
                step_delay=step_delay)
            if addr is None:
                report["ok"] = False
                report["error"] = f"replica {i + 1} failed to start"
                return report
            procs.append(proc)
        prober.start()
        deadline = time.monotonic() + 20.0
        while (time.monotonic() < deadline
               and len([r for r in rset.snapshot()
                        if r["state"] == "up"]) < replicas):
            time.sleep(0.2)
        up = [r["addr"] for r in rset.snapshot() if r["state"] == "up"]
        if len(up) < replicas:
            report["ok"] = False
            report["error"] = f"only {len(up)}/{replicas} replicas up"
            return report

        state, step, behavior_fn = _build_trainer(
            seq_len, cycles * steps_per_cycle)
        publisher = WeightPublisher(store, cadence_steps=1)
        collectors = [RolloutCollector(f"http://{a}",
                                       group_size=group_size,
                                       max_tokens=max_tokens)
                      for a in up]
        prompts = list(_PROMPTS[:max(1, n_prompts)])

        bg = threading.Thread(
            target=_traffic, args=(router, stop, counts, lock),
            kwargs={"max_tokens": max_tokens, "gap_s": traffic_gap_s},
            daemon=True, name="online-loop-traffic")
        bg.start()

        import jax.numpy as jnp

        global_step = 0
        for c in range(cycles):
            # one forced trace per cycle: driver spans + the replicas'
            # completion/swap handler spans all share this id (the
            # sampled flag propagates via traceparent, so every side
            # retains its subtree)
            ctx = _dc.replace(tracing.start_trace(), sampled=True)
            t0 = time.monotonic()
            entry: dict = {"cycle": c, "trace": ctx.trace_id}
            with tracing.activate(ctx):
                with spans_lib.span("online.cycle", cycle=c):
                    child = tracing.current_child_context(sampled=True)
                    tp = tracing.format_traceparent(child)
                    with spans_lib.span("online.rollout"):
                        # rollouts rotate across replicas so every
                        # replica's completions feed training
                        coll = collectors[c % len(collectors)]
                        batch = retry_call(
                            lambda: coll.collect(prompts,
                                                 traceparent=tp),
                            point="rollout.fetch")
                    entry["rollout_versions"] = batch.versions()
                    grpo = to_grpo_batch(batch, _encode, _reward,
                                         seq_len=seq_len)
                    jbatch = {k: jnp.asarray(v)
                              for k, v in grpo.items()}
                    # behavior policy = the harvest-version weights,
                    # recomputed trainer-side (PPO clipped ratio +
                    # kl_behavior drift live from the first update on)
                    jbatch["behavior_logprobs"] = behavior_fn(
                        state.params, jbatch["input_ids"])
                    import jax as _jax

                    rng = _jax.random.PRNGKey(100 + c)
                    losses = []
                    with spans_lib.span("online.train",
                                        steps=steps_per_cycle,
                                        rollout_version=(
                                            batch.weight_version)):
                        for _k in range(steps_per_cycle):
                            state, metrics = step(state, jbatch, rng)
                            losses.append(float(metrics["loss"]))
                            global_step += 1
                            # mirror onto the scrape surface, the
                            # trainer-process MetricLogger convention
                            get_registry().set_from_mapping(
                                {k: float(v)
                                 for k, v in metrics.items()},
                                prefix="train")
                    entry["losses"] = losses
                    entry["kl_behavior"] = get_registry().get_value(
                        "train_kl_behavior")
                    with spans_lib.span("online.publish"):
                        version = publisher.publish(
                            {"params": state.params},
                            step=global_step)
                    spans_lib.set_correlation_tags(
                        weight_version=str(version))
                    entry["published_version"] = version
                    child = tracing.current_child_context(sampled=True)
                    sync = router.weight_sync(
                        version=version,
                        traceparent=tracing.format_traceparent(child))
                    entry["sync"] = sync
                    entry["swapped"] = sum(
                        1 for e in sync
                        if e.get("status") == "swapped")
            tracing.get_tracer().finish(ctx.trace_id,
                                        time.monotonic() - t0)
            cycle_log.append(entry)

        # the fleet must end on the last published version — read it
        # back off every replica's /healthz weight state
        final = str(publisher.version)
        versions = {}
        for a in up:
            try:
                _code, raw = http_json(a, "/healthz", None, 5.0)
                versions[a] = json.loads(raw).get(
                    "weights", {}).get("version")
            except (OSError, ValueError) as e:
                versions[a] = f"error: {e}"
        report["final_versions"] = versions
        report["converged"] = all(v == final
                                  for v in versions.values())
        # the model-health plane's rollout/KL gauges, read back off the
        # same registry the /metrics scrape surface renders
        reg = get_registry()
        report["health_gauges"] = {
            name: reg.get_value(name)
            for name in ("rollout_reward_mean", "rollout_reward_std",
                         "rollout_advantage_mean",
                         "rollout_advantage_std",
                         "rollout_mixed_versions",
                         "train_kl_behavior", "train_token_entropy")}
    finally:
        stop.set()
        prober.stop()
        for proc in procs:
            try:
                proc.terminate()
                proc.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired):
                try:
                    proc.kill()
                except OSError:
                    pass
        try:
            server.stop()
        except OSError:
            pass

    report["cycle_log"] = cycle_log
    report["traffic"] = counts
    swaps_ok = all(e.get("swapped") == replicas for e in cycle_log)
    trained = all(len(e.get("losses", [])) == steps_per_cycle
                  for e in cycle_log)
    versioned = all(e.get("rollout_versions") for e in cycle_log)
    report["ok"] = bool(
        len(cycle_log) == cycles and swaps_ok and trained
        and versioned and report.get("converged")
        and counts.get("failed", 0) == 0
        and counts.get("ok", 0) > 0)
    if not report["ok"]:
        report["why"] = {"cycles_done": len(cycle_log),
                         "swaps_ok": swaps_ok, "trained": trained,
                         "versioned": versioned,
                         "converged": report.get("converged"),
                         "traffic": counts}
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--cycles", type=int, default=3)
    p.add_argument("--steps-per-cycle", type=int, default=2)
    p.add_argument("--group-size", type=int, default=4)
    p.add_argument("--max-tokens", type=int, default=8)
    p.add_argument("--seq-len", type=int, default=48)
    p.add_argument("--prompts", type=int, default=4,
                   help="prompts per rollout batch (each fans out to "
                        "--group-size sampled completions)")
    p.add_argument("--smoke", action="store_true",
                   help="the tier-1 drill: 2 replicas, 2 cycles "
                        "(2 fleet swaps), light traffic, seconds on "
                        "CPU")
    args = p.parse_args(argv)
    if args.smoke:
        report = run_loop(replicas=2, cycles=2, steps_per_cycle=2,
                          group_size=2, max_tokens=4, seq_len=48,
                          n_prompts=2, traffic_gap_s=0.1)
    else:
        report = run_loop(replicas=args.replicas, cycles=args.cycles,
                          steps_per_cycle=args.steps_per_cycle,
                          group_size=args.group_size,
                          max_tokens=args.max_tokens,
                          seq_len=args.seq_len,
                          n_prompts=args.prompts,
                          traffic_gap_s=0.04)
    print(json.dumps(report))
    return 0 if report.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
