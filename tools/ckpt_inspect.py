#!/usr/bin/env python
"""Inspect a checkpoint directory across every tier of the checkpoint
plane (docs/checkpointing.md): persistent Orbax steps with their
integrity-manifest verdicts, per-host hot-disk snapshots with their
seal/CRC status, and what the retention policy would (not) evict.

    python tools/ckpt_inspect.py --dir runs/exp1/ckpt
    python tools/ckpt_inspect.py --dir runs/exp1/ckpt --hot-keep 2 --keep-every 1000

Read-only: nothing is deleted, verified-on-read only (the same checks a
restore performs). Exit 0 when the directory parses — an operator
answering "what would a restore land on right now?" should not need a
Python REPL.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024.0
    return f"{n:.1f} GiB"


def _dir_bytes(path: str) -> int:
    total = 0
    for dirpath, _, names in os.walk(path):
        for name in names:
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                pass
    return total


def inspect_dir(root: str, *, hot_keep: int = 2, keep_every: int = 0,
                out=sys.stdout) -> dict:
    """Gather + print the report; returns the structured form (tests)."""
    from pytorch_distributed_train_tpu.ckpt import hot_tier, retention
    from pytorch_distributed_train_tpu.faults import integrity

    report: dict = {"dir": root, "persistent": [], "hot": {}}
    print(f"checkpoint dir: {root}", file=out)

    # ---- persistent tier (Orbax step dirs + manifests)
    steps = []
    if os.path.isdir(root):
        for name in os.listdir(root):
            if name.isdigit() and os.path.isdir(os.path.join(root, name)):
                steps.append(int(name))
    newest_verified = None
    print(f"\npersistent tier ({len(steps)} steps):", file=out)
    for s in sorted(steps):
        ok, reason = integrity.verify_step(root, s)
        verdict = ("verified" if ok else
                   "trusted (pre-manifest)" if ok is None else
                   f"CORRUPT: {reason}")
        if ok:
            newest_verified = s
        size = _dir_bytes(integrity.step_dir(root, s))
        report["persistent"].append(
            {"step": s, "verdict": verdict, "bytes": size})
        print(f"  step {s:>10}  {_fmt_bytes(size):>10}  {verdict}",
              file=out)
    if not steps:
        print("  (none)", file=out)

    # ---- hot disk tier(s): <root>/hot/host_<n>
    hot_root = os.path.join(root, "hot")
    hosts = []
    if os.path.isdir(hot_root):
        hosts = sorted(n for n in os.listdir(hot_root)
                       if n.startswith("host_"))
    for host in hosts:
        tier = hot_tier.DiskTier(os.path.join(hot_root, host))
        rows = []
        print(f"\nhot disk tier [{host}] "
              f"({len(tier.steps())} steps):", file=out)
        pins = set()
        if newest_verified is not None:
            pins.add(newest_verified)
        sealed = tier.sealed_steps()
        if sealed:
            pins.add(sealed[-1])
        evict = set(retention.plan_evictions(
            tier.steps(), keep_last=hot_keep, keep_every=keep_every,
            pinned=pins))
        for s in tier.steps():
            ok = tier.load(s) is not None  # CRC-verified read
            header = tier.header(s) or {}
            status = ("sealed+verified" if ok else
                      "sealed but CORRUPT" if header.get("sealed") else
                      "unsealed")
            pin = ("PINNED" if s in pins else
                   "evictable" if s in evict else "kept")
            size = tier.step_nbytes(s)
            rows.append({"step": s, "status": status, "gc": pin,
                         "bytes": size})
            print(f"  step {s:>10}  {_fmt_bytes(size):>10}  "
                  f"{status:<20} gc={pin}", file=out)
        if not tier.steps():
            print("  (none)", file=out)
        report["hot"][host] = rows
    if not hosts:
        print("\nhot disk tier: (none)", file=out)

    # ---- the answer an operator actually wants
    hot_best = max((r["step"] for rows in report["hot"].values()
                    for r in rows if r["status"] == "sealed+verified"),
                   default=None)
    cands = [c for c in (newest_verified, hot_best) if c is not None]
    landing = max(cands) if cands else None
    report["newest_verified_persistent"] = newest_verified
    report["newest_sealed_hot"] = hot_best
    report["restore_would_land_on"] = landing
    print(f"\nnewest verified persistent step: {newest_verified}",
          file=out)
    print(f"newest sealed hot step:          {hot_best}", file=out)
    print(f"a restore now would land on:     {landing}", file=out)
    return report


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="Inspect checkpoint tiers, manifest verdicts, and "
                    "retention-pin status.")
    p.add_argument("--dir", required=True, help="checkpoint directory")
    p.add_argument("--hot-keep", type=int, default=2,
                   help="retention keep-last-N to evaluate pins against")
    p.add_argument("--keep-every", type=int, default=0,
                   help="retention keep-every-K to evaluate pins against")
    args = p.parse_args(argv)
    if not os.path.isdir(args.dir):
        print(f"ckpt_inspect: no such directory: {args.dir}",
              file=sys.stderr)
        return 1
    inspect_dir(args.dir, hot_keep=args.hot_keep,
                keep_every=args.keep_every)
    return 0


if __name__ == "__main__":
    sys.exit(main())
