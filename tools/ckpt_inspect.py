#!/usr/bin/env python
"""Inspect a checkpoint directory across every tier of the checkpoint
plane (docs/checkpointing.md): persistent Orbax steps with their
integrity-manifest verdicts, per-host hot-disk snapshots with their
seal/CRC status, and what the retention policy would (not) evict.

    python tools/ckpt_inspect.py --dir runs/exp1/ckpt
    python tools/ckpt_inspect.py --dir runs/exp1/ckpt --hot-keep 2 --keep-every 1000
    python tools/ckpt_inspect.py --dir runs/exp1/ckpt --mesh data=2,fsdp=3

``--mesh AXIS=N[,AXIS=N...]`` answers the elastic-reshard feasibility
question (docs/elastic.md): can each tier restore onto THAT mesh? The
newest verified persistent step's leaves are checked against the
partition rules of the checkpoint's own saved config (dims the mesh
cannot divide are listed as replication fallbacks — restore still
works, those dims just replicate); hot snapshots are host-side global
leaves, mesh-agnostic by construction; and the report names the tier a
reshard-restore would land on.

Read-only: nothing is deleted, verified-on-read only (the same checks a
restore performs). Exit 0 when the directory parses — an operator
answering "what would a restore land on right now?" should not need a
Python REPL.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024.0
    return f"{n:.1f} GiB"


def _dir_bytes(path: str) -> int:
    total = 0
    for dirpath, _, names in os.walk(path):
        for name in names:
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                pass
    return total


def inspect_dir(root: str, *, hot_keep: int = 2, keep_every: int = 0,
                out=None) -> dict:
    """Gather + print the report; returns the structured form (tests).
    ``out`` resolves to sys.stdout at CALL time — an import-time default
    would freeze whatever stream happened to be installed when the
    module loaded (pytest's per-test capture, a redirect)."""
    out = out if out is not None else sys.stdout
    from pytorch_distributed_train_tpu.ckpt import hot_tier, retention
    from pytorch_distributed_train_tpu.faults import integrity

    report: dict = {"dir": root, "persistent": [], "hot": {}}
    print(f"checkpoint dir: {root}", file=out)

    # ---- persistent tier (Orbax step dirs + manifests)
    steps = []
    if os.path.isdir(root):
        for name in os.listdir(root):
            if name.isdigit() and os.path.isdir(os.path.join(root, name)):
                steps.append(int(name))
    newest_verified = None
    print(f"\npersistent tier ({len(steps)} steps):", file=out)
    for s in sorted(steps):
        ok, reason = integrity.verify_step(root, s)
        verdict = ("verified" if ok else
                   "trusted (pre-manifest)" if ok is None else
                   f"CORRUPT: {reason}")
        if ok:
            newest_verified = s
        size = _dir_bytes(integrity.step_dir(root, s))
        report["persistent"].append(
            {"step": s, "verdict": verdict, "bytes": size})
        print(f"  step {s:>10}  {_fmt_bytes(size):>10}  {verdict}",
              file=out)
    if not steps:
        print("  (none)", file=out)

    # ---- hot disk tier(s): <root>/hot/host_<n>
    hot_root = os.path.join(root, "hot")
    hosts = []
    if os.path.isdir(hot_root):
        hosts = sorted(n for n in os.listdir(hot_root)
                       if n.startswith("host_"))
    for host in hosts:
        tier = hot_tier.DiskTier(os.path.join(hot_root, host))
        rows = []
        print(f"\nhot disk tier [{host}] "
              f"({len(tier.steps())} steps):", file=out)
        pins = set()
        if newest_verified is not None:
            pins.add(newest_verified)
        sealed = tier.sealed_steps()
        if sealed:
            pins.add(sealed[-1])
        evict = set(retention.plan_evictions(
            tier.steps(), keep_last=hot_keep, keep_every=keep_every,
            pinned=pins))
        for s in tier.steps():
            ok = tier.load(s) is not None  # CRC-verified read
            header = tier.header(s) or {}
            status = ("sealed+verified" if ok else
                      "sealed but CORRUPT" if header.get("sealed") else
                      "unsealed")
            pin = ("PINNED" if s in pins else
                   "evictable" if s in evict else "kept")
            size = tier.step_nbytes(s)
            rows.append({"step": s, "status": status, "gc": pin,
                         "bytes": size})
            print(f"  step {s:>10}  {_fmt_bytes(size):>10}  "
                  f"{status:<20} gc={pin}", file=out)
        if not tier.steps():
            print("  (none)", file=out)
        report["hot"][host] = rows
    if not hosts:
        print("\nhot disk tier: (none)", file=out)

    # ---- the answer an operator actually wants
    hot_best = max((r["step"] for rows in report["hot"].values()
                    for r in rows if r["status"] == "sealed+verified"),
                   default=None)
    cands = [c for c in (newest_verified, hot_best) if c is not None]
    landing = max(cands) if cands else None
    report["newest_verified_persistent"] = newest_verified
    report["newest_sealed_hot"] = hot_best
    report["restore_would_land_on"] = landing
    print(f"\nnewest verified persistent step: {newest_verified}",
          file=out)
    print(f"newest sealed hot step:          {hot_best}", file=out)
    print(f"a restore now would land on:     {landing}", file=out)
    return report


def parse_mesh(text: str) -> dict[str, int]:
    """``"data=2,fsdp=3"`` → axis-size dict (unnamed axes default 1)."""
    from pytorch_distributed_train_tpu.parallel.mesh import MESH_AXES

    sizes: dict[str, int] = {}
    for part in filter(None, (p.strip() for p in text.split(","))):
        if "=" not in part:
            raise ValueError(
                f"--mesh clause {part!r}: expected AXIS=N "
                f"(axes: {list(MESH_AXES)})")
        ax, _, val = part.partition("=")
        ax = ax.strip()
        if ax not in MESH_AXES:
            raise ValueError(
                f"--mesh names unknown axis {ax!r} (axes: {list(MESH_AXES)})")
        n = int(val)
        if n < 1:
            raise ValueError(f"--mesh {ax}={n}: sizes must be >= 1")
        sizes[ax] = n
    if not sizes:
        raise ValueError("--mesh needs at least one AXIS=N clause")
    return sizes


def mesh_feasibility(root: str, sizes: dict[str, int], *,
                     step: int | None = None, out=None) -> dict:
    """Can each tier of ``root`` restore onto a mesh of ``sizes``?

    Persistent tier: leaf-by-leaf divisibility against the partition
    rules of the checkpoint's OWN saved config (the same
    rules_for_model + validate_spec path a resharded restore takes —
    parallel/partition.py). Hot tiers: host-side global leaves,
    mesh-agnostic. Returns the structured report (tests). ``out``
    resolves to sys.stdout at CALL time (see inspect_dir)."""
    out = out if out is not None else sys.stdout
    from pytorch_distributed_train_tpu import checkpoint as checkpoint_lib
    from pytorch_distributed_train_tpu.config import (
        CheckpointConfig,
        TrainConfig,
    )
    from pytorch_distributed_train_tpu.parallel import partition

    report: dict = {"mesh": dict(sizes), "feasible": None, "leaves": 0,
                    "fallback_leaves": [], "notes": []}
    print(f"\nreshard feasibility onto mesh {sizes}:", file=out)
    mgr = checkpoint_lib.CheckpointManager(
        CheckpointConfig(dir=root, resume="none"), "")
    try:
        if step is None:
            step = mgr.latest_good_step()
        if step is None:
            print("  persistent tier: no verified step — nothing to "
                  "reshard", file=out)
            report["notes"].append("no verified persistent step")
            return report
        report["step"] = int(step)
        meta = mgr.read_meta(step)
        try:
            model_name = TrainConfig.from_json(meta.get("config") or
                                               "{}").model.name
        except Exception:
            model_name = ""
        rules = partition.rules_for_model(model_name or "dense")
        import jax.tree_util as jtu
        import orbax.checkpoint as ocp

        from pytorch_distributed_train_tpu.utils import compat

        # metadata SHAPE differs per orbax version (utils/compat.py) —
        # the raw object would flatten as one shapeless leaf on modern
        # orbax and every divisibility check would silently vanish
        try:
            state_meta = compat.pytree_metadata_tree(
                ocp, os.path.join(root, str(step), "state"))
            flat, _ = jtu.tree_flatten_with_path(state_meta)
        except Exception as e:
            # read-only operator tool: an unreadable step is a report
            # line ("exit 0 when the directory parses"), not a crash
            print(f"  persistent step {step}: state metadata unreadable "
                  f"({type(e).__name__}: {e}) — leaf divisibility "
                  "unknown", file=out)
            report["notes"].append("state metadata unreadable")
            flat = None
        fallbacks = []
        n_leaves = 0
        for path, leaf in flat or []:
            shape = tuple(getattr(leaf, "shape", ()) or ())
            name = partition.path_name(path)
            n_leaves += 1
            try:
                spec = rules.spec_for(name, shape)
            except ValueError:
                continue  # no rule matched: restores replicated
            dims = partition.replication_fallback_dims(spec, shape, sizes)
            if dims:
                fallbacks.append({"leaf": name, "shape": list(shape),
                                  "spec": str(spec), "dims": dims})
        report["leaves"] = n_leaves
        report["fallback_leaves"] = fallbacks
        # validate_spec replicates instead of failing, so a readable
        # step is always feasible; unreadable metadata stays None
        report["feasible"] = True if flat is not None else None
        world = meta.get("world")
        gb = meta.get("global_batch")
        print(f"  persistent step {step} (model {model_name or '?'}, "
              f"written on world {world}): {n_leaves} leaves, "
              f"{len(fallbacks)} would fall back to replication", file=out)
        for fb in fallbacks[:10]:
            print(f"    {fb['leaf']} shape {tuple(fb['shape'])} spec "
                  f"{fb['spec']}: dims {fb['dims']} not divisible",
                  file=out)
        if len(fallbacks) > 10:
            print(f"    ... and {len(fallbacks) - 10} more", file=out)
        if gb:
            shards = 1
            for ax in ("data", "fsdp"):
                shards *= sizes.get(ax, 1)
            ok = int(gb) % shards == 0
            report["batch_divisible"] = ok
            print(f"  global batch {gb} over {shards} batch shards "
                  f"(data x fsdp): {'OK' if ok else 'NOT DIVISIBLE'}",
                  file=out)
        # hot tiers: inventory of host-side GLOBAL leaves — a restore
        # device_puts them into whatever shardings the new mesh derives
        hot_root = os.path.join(root, "hot")
        hosts = (sorted(n for n in os.listdir(hot_root)
                        if n.startswith("host_"))
                 if os.path.isdir(hot_root) else [])
        sealed_hot = None
        for host in hosts:
            from pytorch_distributed_train_tpu.ckpt import hot_tier

            tier = hot_tier.DiskTier(os.path.join(hot_root, host))
            good = tier.sealed_steps()
            if good:
                sealed_hot = max(sealed_hot or 0, good[-1])
        if sealed_hot is not None:
            print(f"  hot tier: sealed step {sealed_hot} holds host-side "
                  "global leaves — restorable onto ANY mesh shape "
                  "(device_put reshards at placement)", file=out)
        report["newest_sealed_hot"] = sealed_hot
        landing = max([s for s in (step, sealed_hot) if s is not None])
        report["reshard_would_land_on"] = landing
        tier_name = ("hot" if sealed_hot is not None and sealed_hot >= step
                     else "orbax (reshard-on-restore)")
        print(f"  a reshard-restore would land on step {landing} via the "
              f"{tier_name} tier (peer tier lives on the LIVE launcher "
              "store — not visible to this offline inspection; a running "
              "gang may land on a newer peer-advertised step)", file=out)
        return report
    finally:
        mgr.close()


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="Inspect checkpoint tiers, manifest verdicts, and "
                    "retention-pin status.")
    p.add_argument("--dir", required=True, help="checkpoint directory")
    p.add_argument("--hot-keep", type=int, default=2,
                   help="retention keep-last-N to evaluate pins against")
    p.add_argument("--keep-every", type=int, default=0,
                   help="retention keep-every-K to evaluate pins against")
    p.add_argument("--mesh", default="",
                   help="AXIS=N[,AXIS=N...] — report whether each tier "
                        "can restore onto that mesh (reshard "
                        "feasibility; docs/elastic.md)")
    args = p.parse_args(argv)
    if not os.path.isdir(args.dir):
        print(f"ckpt_inspect: no such directory: {args.dir}",
              file=sys.stderr)
        return 1
    inspect_dir(args.dir, hot_keep=args.hot_keep,
                keep_every=args.keep_every)
    if args.mesh:
        try:
            sizes = parse_mesh(args.mesh)
        except ValueError as e:
            print(f"ckpt_inspect: {e}", file=sys.stderr)
            return 2
        mesh_feasibility(args.dir, sizes)
    return 0


if __name__ == "__main__":
    sys.exit(main())
