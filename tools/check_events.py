#!/usr/bin/env python
"""Cross-check the event-category catalog in docs/observability.md
against the live catalog (obs/events.py CATEGORIES) AND the emitters —
in every direction.

Same stance as tools/check_fault_points.py: the journal's whole value
is legibility, and a category that exists in code but not in the doc
(or is documented but never emitted, or emitted but undeclared) is
silent drift. Checks:

1. doc table rows == CATEGORIES (both ways);
2. every ``emit("<category>", ...)`` literal in the source names a
   declared category (an undeclared one would raise at runtime — catch
   it in CI instead);
3. every declared category has at least one emitter call site (a
   category nothing can produce is a dead doc row).

Run standalone in CI::

    python tools/check_events.py      # exit 0 = in sync

or as a test (tests/test_timeline_profiler.py asserts main() == 0).
"""

from __future__ import annotations

import glob
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DOC = os.path.join(REPO, "docs", "observability.md")

_ROW = re.compile(r"^\|\s*`([a-z_]+)`\s*\|")
# events_lib.emit("cat", ...) / evl.emit("cat", ...) / journal.emit(...)
# — any attribute-call named emit with a string-literal first argument
_EMIT = re.compile(r"\bemit\(\s*\n?\s*\"([a-z_]+)\"")


def documented_categories(doc_path: str = DOC) -> set[str]:
    """Category names from the first column of the '## Event categories'
    table (only that section)."""
    cats: set[str] = set()
    in_table = False
    with open(doc_path) as f:
        for line in f:
            if line.startswith("## "):
                in_table = line.strip().lower() == "## event categories"
                continue
            if in_table:
                m = _ROW.match(line)
                if m:
                    cats.add(m.group(1))
    return cats


def emitted_categories() -> set[str]:
    """Category literals at every emit() call site in the package and
    tools (excluding obs/events.py itself — the definition, not a use)."""
    cats: set[str] = set()
    roots = (os.path.join(REPO, "pytorch_distributed_train_tpu"),
             os.path.join(REPO, "tools"))
    skip = (os.path.join("obs", "events.py"),  # the definition
            "check_events.py")                 # this checker's own docs
    for root in roots:
        for path in glob.glob(os.path.join(root, "**", "*.py"),
                              recursive=True):
            if path.endswith(skip):
                continue
            try:
                with open(path) as f:
                    cats.update(_EMIT.findall(f.read()))
            except OSError:
                continue
    return cats


def main(argv: list[str] | None = None) -> int:
    del argv
    from pytorch_distributed_train_tpu.obs.events import CATEGORIES

    code = set(CATEGORIES)
    doc = documented_categories()
    used = emitted_categories()
    ok = True
    if not doc:
        print(f"check_events: FOUND NO catalog rows in {DOC} — was the "
              "'## Event categories' table renamed?", file=sys.stderr)
        return 1
    undocumented = sorted(code - doc)
    phantom = sorted(doc - code)
    undeclared = sorted(used - code)
    unemitted = sorted(code - used)
    if undocumented:
        print(f"check_events: categories in obs/events.py but MISSING "
              f"from the doc catalog: {undocumented}", file=sys.stderr)
        ok = False
    if phantom:
        print(f"check_events: categories documented but ABSENT from "
              f"obs/events.py: {phantom}", file=sys.stderr)
        ok = False
    if undeclared:
        print(f"check_events: emit() call sites using UNDECLARED "
              f"categories (would raise at runtime): {undeclared}",
              file=sys.stderr)
        ok = False
    if unemitted:
        print(f"check_events: declared categories with NO emitter call "
              f"site: {unemitted}", file=sys.stderr)
        ok = False
    if ok:
        print(f"check_events: {len(code)} event categories in sync "
              "between code, docs and emitters")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
