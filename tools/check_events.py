#!/usr/bin/env python
"""Cross-check the event-category catalog in docs/observability.md
against the live catalog (obs/events.py CATEGORIES) AND the emitters —
in every direction.

Now a thin shim over the analyzer plugin
(``tools/analyze/passes/event_catalog.py`` — run it with the rest of
the suite via ``python -m tools.analyze --only event-catalog``); this
entry point keeps the documented CI command and the catalog-sync tests
working unchanged. Checks:

1. doc table rows == CATEGORIES (both ways);
2. every ``emit("<category>", ...)`` literal in the source names a
   declared category (an undeclared one would raise at runtime — catch
   it in CI instead);
3. every declared category has at least one emitter call site (a
   category nothing can produce is a dead doc row).

Run standalone in CI::

    python tools/check_events.py      # exit 0 = in sync

or as a test (tests/test_timeline_profiler.py asserts main() == 0).
"""

from __future__ import annotations

import ast
import glob
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DOC = os.path.join(REPO, "docs", "observability.md")


def documented_categories(doc_path: str = DOC) -> set[str]:
    """Category names from the doc table (see the plugin for the rules)."""
    from tools.analyze.passes import event_catalog

    return event_catalog.documented_categories(doc_path)


def emitted_categories() -> set[str]:
    """Category literals at every emit() call site in the package and
    tools (excluding obs/events.py itself — the definition, not a use —
    and the analyzer's seeded fixtures)."""
    from tools.analyze.passes import event_catalog

    cats: set[str] = set()
    roots = (os.path.join(REPO, "pytorch_distributed_train_tpu"),
             os.path.join(REPO, "tools"))
    skip = event_catalog.SKIP_SUFFIXES
    fixtures = os.path.join("tools", "analyze", "fixtures") + os.sep
    for root in roots:
        for path in glob.glob(os.path.join(root, "**", "*.py"),
                              recursive=True):
            if path.endswith(skip) or fixtures in path:
                continue
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read())
            except (OSError, SyntaxError):
                continue
            cats.update(c for c, _ in event_catalog.emit_sites(tree))
    return cats


def main(argv: list[str] | None = None) -> int:
    del argv
    from tools.analyze.passes import event_catalog

    code = event_catalog.declared_categories()
    doc = documented_categories()
    used = emitted_categories()
    ok = True
    if not doc:
        print(f"check_events: FOUND NO catalog rows in {DOC} — was the "
              "'## Event categories' table renamed?", file=sys.stderr)
        return 1
    undocumented = sorted(code - doc)
    phantom = sorted(doc - code)
    undeclared = sorted(used - code)
    unemitted = sorted(code - used)
    if undocumented:
        print(f"check_events: categories in obs/events.py but MISSING "
              f"from the doc catalog: {undocumented}", file=sys.stderr)
        ok = False
    if phantom:
        print(f"check_events: categories documented but ABSENT from "
              f"obs/events.py: {phantom}", file=sys.stderr)
        ok = False
    if undeclared:
        print(f"check_events: emit() call sites using UNDECLARED "
              f"categories (would raise at runtime): {undeclared}",
              file=sys.stderr)
        ok = False
    if unemitted:
        print(f"check_events: declared categories with NO emitter call "
              f"site: {unemitted}", file=sys.stderr)
        ok = False
    if ok:
        print(f"check_events: {len(code)} event categories in sync "
              "between code, docs and emitters")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
