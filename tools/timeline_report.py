#!/usr/bin/env python
"""Unified cross-host run timeline from the event journals.

    python tools/timeline_report.py --run-dir checkpoints/
    python tools/timeline_report.py --run-dir checkpoints/ --out run_trace.json
    python tools/timeline_report.py --run-dir checkpoints/ --trace 4f2a1c
    python tools/timeline_report.py --traces checkpoints/traces \
        --trace 4f2a1c --out one_trace.json

Merges every host's (and the launcher agent's) append-only event
journal (``<run>/events/events_*.jsonl``, obs/events.py) with the
goodput summary from ``metrics.jsonl`` and the host span trace
(``trace.json``) into:

- a ONE-SCREEN text timeline, chronological across hosts, restarts and
  generations — restarts, rewinds, fault fires and profiler captures
  marked so "what happened to this run" is one read, not archaeology;
- causal chains: every journaled anomaly paired with the capture it
  opened and the recovery that followed (sentinel rewind / elastic
  restart / preemption) — the anomaly→capture→recovery story;
- optionally (``--out``) a Chrome/Perfetto ``trace.json``: the span
  ring's complete events merged with one instant event per journal
  record, one process row per host, loadable in ui.perfetto.dev;
- with ``--trace <id>``: ONE distributed trace (obs/tracing.py),
  merged across every writer's retained-trace JSONL (router + N
  replicas + trainer) into a parent/child text tree — and, with
  ``--out``, a Perfetto trace whose rows are one process per host with
  depth-packed lanes, so the cross-process request tree renders with
  correct nesting. ``<id>`` may be any unique prefix of the trace id.

Pure stdlib + the repo's obs package; no jax import — safe on a login
host against a run directory on shared storage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_train_tpu.obs.events import load_events  # noqa: E402

# categories whose events headline the timeline (the rest still show,
# but these carry the run's SHAPE)
_MARKS = {
    "fault": "FAULT",
    "anomaly": "ANOMALY",
    "profile": "PROFILE",
    "sentinel": "SENTINEL",
    "elastic": "ELASTIC",
    "preempt": "PREEMPT",
    "serve": "SERVE",
    "perf": "PERF",
    "alert": "ALERT",
    "action": "ACTION",
    "store": "STORE",
    "lifecycle": "",
    "ckpt": "",
}

# event (category, name) pairs that count as RECOVERY for chain-building
_RECOVERIES = {
    ("sentinel", "rewind"),
    ("elastic", "restart"),
    ("ckpt", "restore"),
    ("ckpt", "restore_tier"),
    ("preempt", "sigterm"),
    # serving-plane recoveries (docs/serving_reliability.md): a hedge or
    # failover answered the incident on another replica; a drain walked
    # the afflicted replica out of rotation
    ("serve", "hedge"),
    ("serve", "failover"),
    ("serve", "drain_begin"),
}

# (category, name) pairs eliding must never drop: the run's SHAPE —
# restarts, reshard lifecycle (docs/elastic.md), rewinds, preemption —
# stays one read even when thousands of routine events surround it
_LANDMARKS = _RECOVERIES | {
    # a perf-ledger gate failure is run-shaping news (obs/perf.py):
    # the round where throughput/MFU regressed must survive eliding
    ("anomaly", "perf_regression"),
    ("elastic", "reshard"),
    ("elastic", "rendezvous_degraded"),
    ("elastic", "budget_exhausted"),
    ("sentinel", "hang_blamed"),
    ("serve", "replica_down"),
    ("serve", "rolling_drain"),
    ("serve", "tail_latency"),
    # fleet alert-rule transitions (obs/alerts.py): a rule firing or
    # resolving is exactly the run-shape news the timeline exists for
    ("alert", "fired"),
    ("alert", "resolved"),
    # fleet-controller actuation (fleet/controller.py): what the
    # closed loop DID about an incident — and its latch transitions —
    # must survive eliding alongside the alerts that triggered it
    ("action", "requested"),
    ("action", "effective"),
    ("action", "failed"),
    ("action", "rolled_back"),
    ("action", "mode"),
    # launcher-store health arc (store_plane.py): a control-plane
    # outage and its recovery — plus the liveness blame suspension it
    # forces — ARE the run's shape while they last
    ("store", "degraded"),
    ("store", "down"),
    ("store", "recovered"),
    ("store", "blame_suspended"),
    ("store", "blame_resumed"),
}


def _fmt_detail(detail: dict, limit: int = 72) -> str:
    if not detail:
        return ""
    parts = []
    for k, v in detail.items():
        if k == "summary":
            continue  # multi-line xplane text: referenced, not inlined
        parts.append(f"{k}={v}")
    text = " ".join(parts)
    return text if len(text) <= limit else text[: limit - 3] + "..."


def timeline_lines(events: list[dict], width: int = 48) -> list[str]:
    """Chronological one-line-per-event view; the middle is elided past
    ``width`` lines (first/last matter most — init and the outage)."""
    if not events:
        return ["timeline: no journaled events (obs.events off, or a "
                "pre-journal run)"]
    t0 = events[0].get("ts", 0.0)
    rows = []
    for e in events:
        mark = _MARKS.get(e.get("category", ""), "")
        step = e.get("step")
        rows.append(
            f"  +{e.get('ts', 0.0) - t0:9.3f}s {e.get('host', '?'):>8} "
            f"g{e.get('gen', '?')} {('step ' + str(step)) if step is not None else '':>9} "
            f"{(mark or e.get('category', '')):>8} "
            f"{e.get('name', '')} {_fmt_detail(e.get('detail') or {})}".rstrip())
    out = [f"timeline ({len(events)} events, "
           f"{len({e.get('host') for e in events})} writers):"]
    if len(rows) <= width:
        out.extend(rows)
        return out
    # Elide the middle — but landmark events (restarts, reshards,
    # rewinds) survive it in chronological place: they are what the
    # reader opened the timeline to find.
    half = width // 2
    out.extend(rows[:half])
    elided = 0
    for e, row in list(zip(events, rows))[half:len(rows) - half]:
        if (e.get("category"), e.get("name")) in _LANDMARKS:
            if elided:
                out.append(f"  ... {elided} events elided ...")
                elided = 0
            out.append(row)
        else:
            elided += 1
    if elided:
        out.append(f"  ... {elided} events elided ...")
    out.extend(rows[-half:])
    return out


def causal_chains(events: list[dict]) -> list[str]:
    """Pair each anomaly with the capture it opened and the recovery
    that followed — the journal's whole reason to exist, as text."""
    anomalies = [e for e in events if e.get("category") == "anomaly"]
    if not anomalies:
        return ["chains: no anomalies journaled"]
    out = [f"anomaly chains ({len(anomalies)}):"]
    for a in anomalies:
        ts = a.get("ts", 0.0)
        host = a.get("host")

        def _capture(name, a=a, ts=ts, host=host):
            # Only a capture the anomaly actually OPENED counts as its
            # capture: the reason journaled at capture time carries the
            # trigger kind, so an unrelated cadence window that happens
            # to close right after the anomaly is not claimed for it.
            return next(
                (e for e in events
                 if e.get("category") == "profile"
                 and e.get("name") == name
                 and e.get("host") == host and e.get("ts", 0.0) >= ts
                 and (e.get("detail") or {}).get("reason")
                 == a.get("name")), None)

        capture = _capture("capture_end") or _capture("capture_start")
        recovery = next(
            (e for e in events
             if (e.get("category"), e.get("name")) in _RECOVERIES
             and e.get("ts", 0.0) >= ts), None)
        line = (f"  {a.get('name')}@step {a.get('step')} [{host}] "
                f"{_fmt_detail(a.get('detail') or {}, 40)}")
        if capture is not None:
            d = capture.get("detail") or {}
            line += (f" -> capture {os.path.basename(str(d.get('dir', '?')))}"
                     f" ({capture.get('name')})")
        else:
            line += " -> no capture (profile_on_anomaly off / cooldown)"
        if recovery is not None:
            line += (f" -> {recovery.get('category')}.{recovery.get('name')}"
                     f"@step {recovery.get('step')} "
                     f"{_fmt_detail(recovery.get('detail') or {}, 32)}")
        else:
            line += " -> no recovery event"
        out.append(line)
    return out


def alert_chains(events: list[dict]) -> list[str]:
    """The fleet-plane analogue of ``causal_chains``: each journaled
    alert FIRE paired with the capture it requested on the offending
    target (``alert``/``profile_requested``, obs/alerts.py
    profile_on_alert) and the RESOLVE that closed it — the
    alert→capture→resolve story of an incident. Empty-journal quiet."""
    fires = [e for e in events if e.get("category") == "alert"
             and e.get("name") == "fired"]
    if not fires:
        return []
    out = [f"alert chains ({len(fires)}):"]
    for a in fires:
        d = a.get("detail") or {}
        rule, host = d.get("rule"), d.get("host")
        ts = a.get("ts", 0.0)

        def _next(name, a_d=d, ts=ts):
            return next(
                (e for e in events
                 if e.get("category") == "alert" and e.get("name") == name
                 and (e.get("detail") or {}).get("rule") == a_d.get("rule")
                 and (e.get("detail") or {}).get("host") == a_d.get("host")
                 and e.get("ts", 0.0) >= ts), None)

        line = f"  {rule} FIRED on {host} (value={d.get('value')})"
        capture = _next("profile_requested")
        if capture is not None:
            line += (" -> capture requested (status "
                     f"{(capture.get('detail') or {}).get('status')})")
        resolved = _next("resolved")
        if resolved is not None:
            rd = resolved.get("detail") or {}
            line += f" -> resolved after {rd.get('after_s')}s"
        else:
            line += " -> still firing at journal end"
        out.append(line)
    return out


def action_chains(events: list[dict]) -> list[str]:
    """The closed-loop story: each journaled controller action grouped
    by its durable action id (``act-<action>-...``), shown as
    ``alert fired → action requested → terminal outcome → alert
    resolved`` when the action carries a triggering incident id — the
    what-the-controller-DID companion to ``alert_chains``. Quiet when
    no ``action`` events are journaled."""
    by_id: dict[str, dict] = {}
    order: list[str] = []
    for e in events:
        if e.get("category") != "action":
            continue
        d = e.get("detail") or {}
        aid = d.get("id")
        if not aid:
            continue  # mode latches render via the timeline landmarks
        slot = by_id.setdefault(aid, {"events": [], "detail": d})
        if aid not in order:
            order.append(aid)
        slot["events"].append(e)
        slot["detail"] = {**slot["detail"], **d}
    if not by_id:
        return []
    resolved_by_id = {
        (e.get("detail") or {}).get("id"): e for e in events
        if e.get("category") == "alert" and e.get("name") == "resolved"}
    out = [f"action chains ({len(by_id)}):"]
    for aid in order:
        slot = by_id[aid]
        d = slot["detail"]
        names = [e.get("name") for e in slot["events"]]
        terminal = next(
            (n for n in reversed(names)
             if n in ("effective", "failed", "rolled_back", "skipped")),
            names[-1] if names else "?")
        trigger = d.get("trigger", "?")
        alert_id = d.get("alert_id")
        line = f"  {d.get('action', '?')} [{aid}]"
        if alert_id:
            line += f" <- alert {alert_id}"
        else:
            line += f" <- {trigger}"
        line += f" -> {' -> '.join(names)}"
        if terminal == "failed" and d.get("error"):
            line += f" ({str(d.get('error'))[:48]})"
        if terminal == "skipped" and d.get("reason"):
            line += f" ({d.get('reason')})"
        if alert_id and alert_id in resolved_by_id:
            rd = resolved_by_id[alert_id].get("detail") or {}
            line += f" -> alert resolved after {rd.get('after_s')}s"
        out.append(line)
    return out


def counts_section(events: list[dict]) -> list[str]:
    by_cat: dict[str, int] = {}
    for e in events:
        by_cat[e.get("category", "?")] = by_cat.get(
            e.get("category", "?"), 0) + 1
    gens = sorted({str(e.get("gen")) for e in events})
    out = [f"event counts (generations seen: {', '.join(gens) or '-'}):"]
    for cat in sorted(by_cat, key=lambda c: -by_cat[c]):
        out.append(f"  {cat:<10} {by_cat[cat]:>6}")
    return out


def goodput_line(jsonl_path: str) -> list[str]:
    if not jsonl_path or not os.path.exists(jsonl_path):
        return ["goodput: no metrics.jsonl"]
    last = None
    try:
        with open(jsonl_path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                if "goodput_pct" in r:
                    last = r
    except OSError:
        return ["goodput: unreadable metrics.jsonl"]
    if last is None:
        return ["goodput: no goodput records"]
    return [f"goodput: {last['goodput_pct']:.1f}% productive "
            f"(tag={last.get('tag')}, step={last.get('step')}; full "
            "breakdown in tools/obs_report.py)"]


# ------------------------------------------------------- one trace (--trace)
def _trace_children(spans: list[dict]) -> tuple[list[dict], dict]:
    """(roots, children-by-parent) for one merged trace. A span whose
    parent id is unknown (its parent span was never retained — e.g. a
    subtree whose root lived in an unretained process) is treated as a
    root so nothing silently disappears."""
    ids = {s.get("span_id") for s in spans if s.get("span_id")}
    kids: dict[str, list[dict]] = {}
    roots: list[dict] = []
    for s in spans:
        p = s.get("parent_id")
        if p and p in ids:
            kids.setdefault(p, []).append(s)
        else:
            roots.append(s)
    for v in kids.values():
        v.sort(key=lambda s: s.get("t0", 0.0))
    roots.sort(key=lambda s: s.get("t0", 0.0))
    return roots, kids


def _fmt_span_args(s: dict) -> str:
    parts = [f"{k}={v}" for k, v in (s.get("args") or {}).items()]
    return (" " + " ".join(parts)) if parts else ""


def trace_report(trees: list[dict], trace_id: str) -> str:
    """Text tree of one merged cross-process trace: every span nested
    under its parent, host + duration + args per line, the per-writer
    retention reasons and correlation tags up top."""
    from pytorch_distributed_train_tpu.obs.tracing import merge_trace

    spans = merge_trace(trees, trace_id)
    if not spans:
        return f"trace {trace_id}: not retained (no matching tree in " \
               f"any traces_*.jsonl)"
    full_id = next(t["trace_id"] for t in trees
                   if t["trace_id"].startswith(trace_id))
    writers: dict[str, dict] = {}
    for t in trees:
        if t["trace_id"].startswith(trace_id):
            w = writers.setdefault(t.get("host", "?"),
                                   {"reason": t.get("reason"),
                                    "tags": t.get("tags") or {}})
            w["reason"] = w["reason"] or t.get("reason")
    t0 = min(s.get("t0", 0.0) for s in spans)
    lines = [f"== trace {full_id} ==",
             f"{len(spans)} span(s) across {len(writers)} process(es)"]
    for host, w in sorted(writers.items()):
        tags = " ".join(f"{k}={v}" for k, v in w["tags"].items())
        lines.append(f"  [{host}] kept: {w['reason']}"
                     + (f"  tags: {tags}" if tags else ""))
    roots, kids = _trace_children(spans)

    def _walk(s, depth):
        lines.append(
            f"  +{s.get('t0', 0.0) - t0:8.3f}s {'  ' * depth}"
            f"{s.get('name')} {s.get('dur_s', 0.0) * 1e3:.1f}ms "
            f"[{s.get('host')}]" + _fmt_span_args(s))
        for c in kids.get(s.get("span_id"), []):
            _walk(c, depth + 1)

    for r in roots:
        _walk(r, 0)
    return "\n".join(lines)


def trace_perfetto(trees: list[dict], trace_id: str) -> dict:
    """One merged trace as Chrome/Perfetto JSON: one process row per
    host; within a host, spans pack into depth-based lanes (a child's
    lane is below its parent's; temporally overlapping same-depth
    siblings — a hedge racing its primary — spread to separate lanes so
    Perfetto's containment nesting never lies about parentage). Args
    carry the explicit span/parent ids for programmatic checks."""
    from pytorch_distributed_train_tpu.obs.tracing import merge_trace

    spans = merge_trace(trees, trace_id)
    # args must carry the FULL id, not the user's prefix — scripts
    # correlate the export back against traces_*.jsonl by it
    full_id = next((t["trace_id"] for t in trees
                    if t["trace_id"].startswith(trace_id)), trace_id)
    roots, kids = _trace_children(spans)
    hosts = sorted({s.get("host", "?") for s in spans})
    pid_of = {h: i + 1 for i, h in enumerate(hosts)}
    out: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid, "args": {"name": h}}
        for h, pid in pid_of.items()]
    # lane occupancy per (host, lane): list of (t0, t1) intervals
    busy: dict[tuple, list] = {}

    def _lane(host, min_lane, t0, t1):
        lane = min_lane
        while any(a < t1 and t0 < b for a, b in busy.get((host, lane),
                                                         ())):
            lane += 1
        busy.setdefault((host, lane), []).append((t0, t1))
        return lane

    def _emit(s, min_lane):
        host = s.get("host", "?")
        t0 = float(s.get("t0", 0.0))
        t1 = t0 + float(s.get("dur_s", 0.0))
        lane = _lane(host, min_lane, t0, t1)
        args = dict(s.get("args") or {})
        args.update({"trace_id": full_id,
                     "span_id": s.get("span_id"),
                     "parent_id": s.get("parent_id"),
                     "host": host})
        if s.get("tags"):
            args["tags"] = s["tags"]
        out.append({"name": s.get("name"), "ph": "X", "ts": t0 * 1e6,
                    "dur": max(1.0, (t1 - t0) * 1e6),
                    "pid": pid_of.get(host, 0), "tid": lane,
                    "args": args})
        for c in kids.get(s.get("span_id"), []):
            # depth lanes are per host: a child living in another
            # process starts at that host's top lane
            _emit(c, lane + 1 if c.get("host", "?") == host else 0)

    for r in roots:
        _emit(r, 0)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ------------------------------------------------------------ perfetto out
def perfetto_trace(events: list[dict], trace_path: str = "") -> dict:
    """Spans (complete events, pass-through) + journal instants, one
    process row per host so Perfetto lays the cluster out side by side."""
    trace_events: list[dict] = []
    if trace_path and os.path.exists(trace_path):
        try:
            with open(trace_path) as f:
                trace_events = list(json.load(f).get("traceEvents", []))
        except (ValueError, OSError):
            pass
    hosts = sorted({e.get("host", "?") for e in events})
    # Journal rows get pids ABOVE every pid the span trace already uses
    # (spans carry real os.getpid() values — often 1 in a container):
    # a collision would rename the span process and fold two writers'
    # rows together.
    used = {int(e["pid"]) for e in trace_events
            if isinstance(e.get("pid"), (int, float))}
    base = max(used, default=0) + 1
    pid_of = {h: base + i for i, h in enumerate(hosts)}
    for h, pid in pid_of.items():
        trace_events.append({"name": "process_name", "ph": "M", "pid": pid,
                             "args": {"name": str(h)}})
    for e in events:
        ev = {
            "name": f"{e.get('category')}.{e.get('name')}",
            "ph": "i",
            "s": "g",  # global scope: the instant line spans all rows
            "ts": e.get("ts", 0.0) * 1e6,
            "pid": pid_of.get(e.get("host", "?"), 0),
            "tid": e.get("category", "event"),
        }
        args = {k: v for k, v in (e.get("detail") or {}).items()
                if k != "summary"}
        if e.get("step") is not None:
            args["step"] = e["step"]
        args["gen"] = e.get("gen")
        ev["args"] = args
        trace_events.append(ev)
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def report(events_dir: str, jsonl_path: str = "",
           trace_path: str = "") -> str:
    events = load_events(events_dir)
    lines = [f"== run timeline: {events_dir} =="]
    for section in (counts_section(events), goodput_line(jsonl_path),
                    timeline_lines(events), causal_chains(events),
                    alert_chains(events), action_chains(events)):
        if not section:
            continue
        lines.append("")
        lines.extend(section)
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--run-dir", default="",
                   help="run directory (events/ + metrics.jsonl + "
                        "trace.json underneath)")
    p.add_argument("--events", default="",
                   help="explicit events directory (default "
                        "<run-dir>/events)")
    p.add_argument("--jsonl", default="", help="explicit metrics.jsonl")
    p.add_argument("--span-trace", default="",
                   help="explicit span trace.json (the ring export)")
    p.add_argument("--trace", default="", metavar="TRACE_ID",
                   help="report ONE distributed trace (id or unique "
                        "prefix) merged across every retained-trace "
                        "file; --out then writes its Perfetto tree")
    p.add_argument("--traces", default="",
                   help="retained-traces directory (default "
                        "<run-dir>/traces)")
    p.add_argument("--out", default="",
                   help="also write a merged Chrome/Perfetto trace.json "
                        "(spans + journal instants; with --trace: the "
                        "one request tree) to this path")
    args = p.parse_args(argv)
    if args.trace:
        from pytorch_distributed_train_tpu.obs.tracing import load_traces

        traces_dir = args.traces or (os.path.join(args.run_dir, "traces")
                                     if args.run_dir else "")
        if not traces_dir or not os.path.isdir(traces_dir):
            print(f"timeline_report: no traces directory at "
                  f"{traces_dir!r} (--run-dir or --traces)",
                  file=sys.stderr)
            return 2
        trees = load_traces(traces_dir)
        try:
            print(trace_report(trees, args.trace))
        except ValueError as e:  # ambiguous prefix
            print(f"timeline_report: {e}", file=sys.stderr)
            return 2
        if args.out:
            merged = trace_perfetto(trees, args.trace)
            with open(args.out, "w") as f:
                json.dump(merged, f)
            print(f"\nwrote Perfetto trace tree: {args.out} "
                  f"({len(merged['traceEvents'])} events)")
        return 0
    events_dir = args.events or (os.path.join(args.run_dir, "events")
                                 if args.run_dir else "")
    if not events_dir or not os.path.isdir(events_dir):
        print(f"timeline_report: no events directory at {events_dir!r} "
              "(--run-dir or --events)", file=sys.stderr)
        return 2
    jsonl = args.jsonl or (os.path.join(args.run_dir, "metrics.jsonl")
                           if args.run_dir else "")
    trace = args.span_trace or (os.path.join(args.run_dir, "trace.json")
                                if args.run_dir else "")
    print(report(events_dir, jsonl, trace))
    if args.out:
        merged = perfetto_trace(load_events(events_dir), trace)
        with open(args.out, "w") as f:
            json.dump(merged, f)
        print(f"\nwrote merged Perfetto trace: {args.out} "
              f"({len(merged['traceEvents'])} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
