#!/usr/bin/env python
"""Unified cross-host run timeline from the event journals.

    python tools/timeline_report.py --run-dir checkpoints/
    python tools/timeline_report.py --run-dir checkpoints/ --out run_trace.json

Merges every host's (and the launcher agent's) append-only event
journal (``<run>/events/events_*.jsonl``, obs/events.py) with the
goodput summary from ``metrics.jsonl`` and the host span trace
(``trace.json``) into:

- a ONE-SCREEN text timeline, chronological across hosts, restarts and
  generations — restarts, rewinds, fault fires and profiler captures
  marked so "what happened to this run" is one read, not archaeology;
- causal chains: every journaled anomaly paired with the capture it
  opened and the recovery that followed (sentinel rewind / elastic
  restart / preemption) — the anomaly→capture→recovery story;
- optionally (``--out``) a Chrome/Perfetto ``trace.json``: the span
  ring's complete events merged with one instant event per journal
  record, one process row per host, loadable in ui.perfetto.dev.

Pure stdlib + the repo's obs package; no jax import — safe on a login
host against a run directory on shared storage.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_train_tpu.obs.events import load_events  # noqa: E402

# categories whose events headline the timeline (the rest still show,
# but these carry the run's SHAPE)
_MARKS = {
    "fault": "FAULT",
    "anomaly": "ANOMALY",
    "profile": "PROFILE",
    "sentinel": "SENTINEL",
    "elastic": "ELASTIC",
    "preempt": "PREEMPT",
    "serve": "SERVE",
    "perf": "PERF",
    "lifecycle": "",
    "ckpt": "",
}

# event (category, name) pairs that count as RECOVERY for chain-building
_RECOVERIES = {
    ("sentinel", "rewind"),
    ("elastic", "restart"),
    ("ckpt", "restore"),
    ("ckpt", "restore_tier"),
    ("preempt", "sigterm"),
    # serving-plane recoveries (docs/serving_reliability.md): a hedge or
    # failover answered the incident on another replica; a drain walked
    # the afflicted replica out of rotation
    ("serve", "hedge"),
    ("serve", "failover"),
    ("serve", "drain_begin"),
}

# (category, name) pairs eliding must never drop: the run's SHAPE —
# restarts, reshard lifecycle (docs/elastic.md), rewinds, preemption —
# stays one read even when thousands of routine events surround it
_LANDMARKS = _RECOVERIES | {
    # a perf-ledger gate failure is run-shaping news (obs/perf.py):
    # the round where throughput/MFU regressed must survive eliding
    ("anomaly", "perf_regression"),
    ("elastic", "reshard"),
    ("elastic", "rendezvous_degraded"),
    ("elastic", "budget_exhausted"),
    ("sentinel", "hang_blamed"),
    ("serve", "replica_down"),
    ("serve", "rolling_drain"),
    ("serve", "tail_latency"),
}


def _fmt_detail(detail: dict, limit: int = 72) -> str:
    if not detail:
        return ""
    parts = []
    for k, v in detail.items():
        if k == "summary":
            continue  # multi-line xplane text: referenced, not inlined
        parts.append(f"{k}={v}")
    text = " ".join(parts)
    return text if len(text) <= limit else text[: limit - 3] + "..."


def timeline_lines(events: list[dict], width: int = 48) -> list[str]:
    """Chronological one-line-per-event view; the middle is elided past
    ``width`` lines (first/last matter most — init and the outage)."""
    if not events:
        return ["timeline: no journaled events (obs.events off, or a "
                "pre-journal run)"]
    t0 = events[0].get("ts", 0.0)
    rows = []
    for e in events:
        mark = _MARKS.get(e.get("category", ""), "")
        step = e.get("step")
        rows.append(
            f"  +{e.get('ts', 0.0) - t0:9.3f}s {e.get('host', '?'):>8} "
            f"g{e.get('gen', '?')} {('step ' + str(step)) if step is not None else '':>9} "
            f"{(mark or e.get('category', '')):>8} "
            f"{e.get('name', '')} {_fmt_detail(e.get('detail') or {})}".rstrip())
    out = [f"timeline ({len(events)} events, "
           f"{len({e.get('host') for e in events})} writers):"]
    if len(rows) <= width:
        out.extend(rows)
        return out
    # Elide the middle — but landmark events (restarts, reshards,
    # rewinds) survive it in chronological place: they are what the
    # reader opened the timeline to find.
    half = width // 2
    out.extend(rows[:half])
    elided = 0
    for e, row in list(zip(events, rows))[half:len(rows) - half]:
        if (e.get("category"), e.get("name")) in _LANDMARKS:
            if elided:
                out.append(f"  ... {elided} events elided ...")
                elided = 0
            out.append(row)
        else:
            elided += 1
    if elided:
        out.append(f"  ... {elided} events elided ...")
    out.extend(rows[-half:])
    return out


def causal_chains(events: list[dict]) -> list[str]:
    """Pair each anomaly with the capture it opened and the recovery
    that followed — the journal's whole reason to exist, as text."""
    anomalies = [e for e in events if e.get("category") == "anomaly"]
    if not anomalies:
        return ["chains: no anomalies journaled"]
    out = [f"anomaly chains ({len(anomalies)}):"]
    for a in anomalies:
        ts = a.get("ts", 0.0)
        host = a.get("host")

        def _capture(name, a=a, ts=ts, host=host):
            # Only a capture the anomaly actually OPENED counts as its
            # capture: the reason journaled at capture time carries the
            # trigger kind, so an unrelated cadence window that happens
            # to close right after the anomaly is not claimed for it.
            return next(
                (e for e in events
                 if e.get("category") == "profile"
                 and e.get("name") == name
                 and e.get("host") == host and e.get("ts", 0.0) >= ts
                 and (e.get("detail") or {}).get("reason")
                 == a.get("name")), None)

        capture = _capture("capture_end") or _capture("capture_start")
        recovery = next(
            (e for e in events
             if (e.get("category"), e.get("name")) in _RECOVERIES
             and e.get("ts", 0.0) >= ts), None)
        line = (f"  {a.get('name')}@step {a.get('step')} [{host}] "
                f"{_fmt_detail(a.get('detail') or {}, 40)}")
        if capture is not None:
            d = capture.get("detail") or {}
            line += (f" -> capture {os.path.basename(str(d.get('dir', '?')))}"
                     f" ({capture.get('name')})")
        else:
            line += " -> no capture (profile_on_anomaly off / cooldown)"
        if recovery is not None:
            line += (f" -> {recovery.get('category')}.{recovery.get('name')}"
                     f"@step {recovery.get('step')} "
                     f"{_fmt_detail(recovery.get('detail') or {}, 32)}")
        else:
            line += " -> no recovery event"
        out.append(line)
    return out


def counts_section(events: list[dict]) -> list[str]:
    by_cat: dict[str, int] = {}
    for e in events:
        by_cat[e.get("category", "?")] = by_cat.get(
            e.get("category", "?"), 0) + 1
    gens = sorted({str(e.get("gen")) for e in events})
    out = [f"event counts (generations seen: {', '.join(gens) or '-'}):"]
    for cat in sorted(by_cat, key=lambda c: -by_cat[c]):
        out.append(f"  {cat:<10} {by_cat[cat]:>6}")
    return out


def goodput_line(jsonl_path: str) -> list[str]:
    if not jsonl_path or not os.path.exists(jsonl_path):
        return ["goodput: no metrics.jsonl"]
    last = None
    try:
        with open(jsonl_path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                if "goodput_pct" in r:
                    last = r
    except OSError:
        return ["goodput: unreadable metrics.jsonl"]
    if last is None:
        return ["goodput: no goodput records"]
    return [f"goodput: {last['goodput_pct']:.1f}% productive "
            f"(tag={last.get('tag')}, step={last.get('step')}; full "
            "breakdown in tools/obs_report.py)"]


# ------------------------------------------------------------ perfetto out
def perfetto_trace(events: list[dict], trace_path: str = "") -> dict:
    """Spans (complete events, pass-through) + journal instants, one
    process row per host so Perfetto lays the cluster out side by side."""
    trace_events: list[dict] = []
    if trace_path and os.path.exists(trace_path):
        try:
            with open(trace_path) as f:
                trace_events = list(json.load(f).get("traceEvents", []))
        except (ValueError, OSError):
            pass
    hosts = sorted({e.get("host", "?") for e in events})
    # Journal rows get pids ABOVE every pid the span trace already uses
    # (spans carry real os.getpid() values — often 1 in a container):
    # a collision would rename the span process and fold two writers'
    # rows together.
    used = {int(e["pid"]) for e in trace_events
            if isinstance(e.get("pid"), (int, float))}
    base = max(used, default=0) + 1
    pid_of = {h: base + i for i, h in enumerate(hosts)}
    for h, pid in pid_of.items():
        trace_events.append({"name": "process_name", "ph": "M", "pid": pid,
                             "args": {"name": str(h)}})
    for e in events:
        ev = {
            "name": f"{e.get('category')}.{e.get('name')}",
            "ph": "i",
            "s": "g",  # global scope: the instant line spans all rows
            "ts": e.get("ts", 0.0) * 1e6,
            "pid": pid_of.get(e.get("host", "?"), 0),
            "tid": e.get("category", "event"),
        }
        args = {k: v for k, v in (e.get("detail") or {}).items()
                if k != "summary"}
        if e.get("step") is not None:
            args["step"] = e["step"]
        args["gen"] = e.get("gen")
        ev["args"] = args
        trace_events.append(ev)
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def report(events_dir: str, jsonl_path: str = "",
           trace_path: str = "") -> str:
    events = load_events(events_dir)
    lines = [f"== run timeline: {events_dir} =="]
    for section in (counts_section(events), goodput_line(jsonl_path),
                    timeline_lines(events), causal_chains(events)):
        lines.append("")
        lines.extend(section)
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--run-dir", default="",
                   help="run directory (events/ + metrics.jsonl + "
                        "trace.json underneath)")
    p.add_argument("--events", default="",
                   help="explicit events directory (default "
                        "<run-dir>/events)")
    p.add_argument("--jsonl", default="", help="explicit metrics.jsonl")
    p.add_argument("--trace", default="", help="explicit trace.json")
    p.add_argument("--out", default="",
                   help="also write a merged Chrome/Perfetto trace.json "
                        "(spans + journal instants) to this path")
    args = p.parse_args(argv)
    events_dir = args.events or (os.path.join(args.run_dir, "events")
                                 if args.run_dir else "")
    if not events_dir or not os.path.isdir(events_dir):
        print(f"timeline_report: no events directory at {events_dir!r} "
              "(--run-dir or --events)", file=sys.stderr)
        return 2
    jsonl = args.jsonl or (os.path.join(args.run_dir, "metrics.jsonl")
                           if args.run_dir else "")
    trace = args.trace or (os.path.join(args.run_dir, "trace.json")
                           if args.run_dir else "")
    print(report(events_dir, jsonl, trace))
    if args.out:
        merged = perfetto_trace(load_events(events_dir), trace)
        with open(args.out, "w") as f:
            json.dump(merged, f)
        print(f"\nwrote merged Perfetto trace: {args.out} "
              f"({len(merged['traceEvents'])} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
