#!/usr/bin/env python
"""HTTP serving endpoint over the continuous batcher — the end-user
service surface (torch-ecosystem analogue: TGI / vLLM's OpenAI-style
server, scoped to stdlib http.server: zero extra dependencies).

    python tools/serve_http.py --config llama2_7b \
        --safetensors model.st --tokenizer /models/llama2-tok \
        --port 8000 --slots 8 [--quantize int8]

    curl -s localhost:8000/v1/completions -d '{
        "prompt": "The capital of France is",
        "max_tokens": 32, "temperature": 0.7}'

API (JSON over POST, one object per request):
- ``POST /v1/completions``: {prompt, max_tokens?, temperature?, keep?,
  session?} → {text, finish_reason, session,
  usage:{prompt_tokens, completion_tokens}}. ``keep: true`` parks the
  request's KV cache and returns a ``session`` id; posting that id as
  ``session`` continues the conversation from the resident cache (the
  prompt is then just the NEW turn — no resend of history). Sessions
  evict LRU under slot pressure (a resume then 404s in-band with
  finish_reason "session_evicted").
  ``top_k``/``top_p`` are SERVER-wide flags (static jit args — per-request
  values would recompile; temperature is the per-request knob).
- ``POST /v1/preload``: {prompt} → {session} — prefill a shared prefix
  (system prompt) once and park it; completions posted with
  ``prefix: <session>`` FORK it (the template survives, so one preload
  serves any number of requests).
- ``GET /healthz``: {status, stats} — liveness + batcher counters.

Threading model: request handler threads (ThreadingHTTPServer) enqueue
into the batcher under a lock and wait on a per-request event; ONE
scheduler thread drives ``batcher.step()`` — all device work stays on a
single thread, handlers only block on Python events. Requests admit into
free slots mid-stream, so concurrent callers batch together on the chip
without knowing about each other.
"""

from __future__ import annotations

import argparse
import json
import os
import queue as queue_mod
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class BatcherService:
    """Thread-safe facade over a (seq2seq-aware) continuous batcher: a
    single scheduler thread steps the device; callers submit and wait."""

    def __init__(self, batcher, tokenizer, *, idle_sleep_s: float = 0.005,
                 max_new_default: int = 64):
        self.batcher = batcher
        self.tok = tokenizer
        self.max_new_default = max_new_default
        self._lock = threading.Lock()
        self._done: dict[int, object] = {}
        self._events: dict[int, threading.Event] = {}
        self._streams: dict[int, queue_mod.Queue] = {}  # uid -> chunk queue
        self._stream_seen: dict[int, int] = {}  # tokens already pushed
        self._abandoned: set[int] = set()  # timed-out uids: discard results
        self.error: str | None = None  # scheduler-death reason (terminal)
        self._idle_sleep_s = idle_sleep_s
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop:
            try:
                with self._lock:
                    busy = bool(self.batcher.queue
                                or self.batcher.active_slots)
                    finished = self.batcher.step() if busy else []
                    # push newly generated tokens to streaming waiters
                    fresh = self.batcher.new_tokens_since(self._stream_seen)
                    for uid, toks in fresh.items():
                        self._streams[uid].put(("tokens", toks))
                        self._stream_seen[uid] += len(toks)
                    for c in finished:
                        if c.uid in self._abandoned:
                            self._abandoned.discard(c.uid)
                            self._streams.pop(c.uid, None)
                            self._stream_seen.pop(c.uid, None)
                            continue  # waiter gave up; drop, don't leak
                        q = self._streams.pop(c.uid, None)
                        if q is not None:
                            seen = self._stream_seen.pop(c.uid, 0)
                            if len(c.tokens) > seen:
                                q.put(("tokens", c.tokens[seen:]))
                            q.put(("done", c))
                            continue  # streamed: never stored in _done
                        self._done[c.uid] = c
                        ev = self._events.pop(c.uid, None)
                        if ev is not None:
                            ev.set()
            except Exception as e:  # noqa: BLE001 — must not die silently
                # Device/compile errors are terminal for the only decode
                # thread: record the reason (healthz flips to error), fail
                # every waiter immediately instead of letting them time out.
                self.error = f"{type(e).__name__}: {e}"
                with self._lock:
                    for ev in self._events.values():
                        ev.set()
                    self._events.clear()
                    for q in self._streams.values():
                        q.put(("error", self.error))
                    self._streams.clear()
                    self._stream_seen.clear()
                return
            if not busy:
                time.sleep(self._idle_sleep_s)

    def healthy(self) -> bool:
        return self.error is None and self._thread.is_alive()

    def preload(self, prompt: str) -> int:
        """Park a shared-prefix template; returns its session id."""
        ids = self.tok.encode(prompt)
        if not ids:
            raise ValueError("empty prompt after tokenization")
        with self._lock:
            if self.error is not None:
                raise RuntimeError(f"scheduler dead: {self.error}")
            return self.batcher.preload(ids)

    def complete(self, prompt: str, max_tokens: int, temperature: float,
                 timeout_s: float = 600.0, *, keep: bool = False,
                 session: int | None = None,
                 prefix: int | None = None) -> dict:
        ids = self.tok.encode(prompt)
        if not ids:
            raise ValueError("empty prompt after tokenization")
        ev = threading.Event()
        with self._lock:
            # Checked UNDER the lock: the scheduler's death path clears
            # _events under this lock, so registering after a pre-lock
            # check could enqueue an event nothing will ever set.
            if self.error is not None:
                raise RuntimeError(f"scheduler dead: {self.error}")
            uid = self.batcher.submit(ids, max_tokens,
                                      temperature=temperature,
                                      eos_id=self.tok.eos_id,
                                      keep=keep, session=session,
                                      prefix=prefix)
            self._events[uid] = ev
        timed_out = not ev.wait(timeout_s)
        with self._lock:
            # The completion may have landed in the wait→lock window even
            # on the timeout path — prefer returning it over abandoning
            # (which would leak the stored result forever: uids never
            # repeat, so nothing else would pop it).
            c = self._done.pop(uid, None)
            if timed_out and c is None:
                self._events.pop(uid, None)
                self._abandoned.add(uid)
        if c is None:
            if timed_out:
                raise TimeoutError(
                    f"request {uid} timed out after {timeout_s}s")
            raise RuntimeError(f"scheduler dead: {self.error}")
        new = c.tokens
        if self.tok.eos_id in new:
            new = new[: new.index(self.tok.eos_id)]
        return {
            "text": self.tok.decode(new),
            "finish_reason": c.finish_reason,
            "session": c.session,
            "usage": {"prompt_tokens": len(ids),
                      "completion_tokens": len(c.tokens)},
        }

    def stream(self, prompt: str, max_tokens: int, temperature: float,
               timeout_s: float = 600.0, *, keep: bool = False,
               session: int | None = None, prefix: int | None = None):
        """Returns (uid, chunk iterator). Validation and submission run
        EAGERLY (so callers can reject before committing to a response);
        the iterator yields (new_token_ids, completion_or_None) chunks as
        the batched decode produces them, ending with the Completion.
        ``timeout_s`` bounds the wait for EACH chunk. A caller that stops
        consuming must call ``abandon_stream(uid)``."""
        ids = self.tok.encode(prompt)
        if not ids:
            raise ValueError("empty prompt after tokenization")
        q: queue_mod.Queue = queue_mod.Queue()
        with self._lock:
            if self.error is not None:
                raise RuntimeError(f"scheduler dead: {self.error}")
            uid = self.batcher.submit(ids, max_tokens,
                                      temperature=temperature,
                                      eos_id=self.tok.eos_id,
                                      keep=keep, session=session,
                                      prefix=prefix)
            self._streams[uid] = q
            self._stream_seen[uid] = 0

        def chunks():
            while True:
                try:
                    kind, payload = q.get(timeout=timeout_s)
                except queue_mod.Empty:
                    self.abandon_stream(uid)
                    raise TimeoutError(
                        f"request {uid} produced no chunk for {timeout_s}s")
                if kind == "tokens":
                    yield payload, None
                elif kind == "done":
                    yield [], payload
                    return
                else:  # "error"
                    raise RuntimeError(f"scheduler dead: {payload}")

        return uid, chunks()

    def abandon_stream(self, uid: int) -> None:
        """Stop tracking a streaming request whose consumer went away
        (client disconnect, chunk timeout): its eventual completion is
        discarded instead of queueing chunks nobody reads. A no-op once
        the request already finished (the scheduler popped its stream) —
        marking it abandoned then would leak the set entry forever, since
        its uid never appears in a finished list again."""
        with self._lock:
            if self._streams.pop(uid, None) is None:
                return
            self._stream_seen.pop(uid, None)
            self._abandoned.add(uid)

    def stats(self) -> dict:
        # Snapshot WITHOUT the step lock: the counters are plain ints
        # mutated only by the scheduler thread, and a liveness probe must
        # not block behind a minutes-long first-compile step quantum.
        return dict(self.batcher.stats)

    def shutdown(self):
        self._stop = True
        self._thread.join(timeout=5)


def make_handler(service: BatcherService):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _send(self, code: int, obj: dict):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                if service.healthy():
                    self._send(200, {"status": "ok",
                                     "stats": service.stats()})
                else:
                    self._send(503, {"status": "error",
                                     "error": service.error,
                                     "stats": service.stats()})
            else:
                self._send(404, {"error": "unknown path"})

        def do_POST(self):
            if self.path not in ("/v1/completions", "/v1/preload"):
                self._send(404, {"error": "unknown path"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                prompt = str(req["prompt"])
                if self.path == "/v1/preload":
                    self._send(200, {"session": service.preload(prompt)})
                    return
                max_tokens = int(req.get("max_tokens",
                                         service.max_new_default))
                temperature = float(req.get("temperature", 0.0))
                keep = bool(req.get("keep", False))
                session = req.get("session")
                session = int(session) if session is not None else None
                prefix = req.get("prefix")
                prefix = int(prefix) if prefix is not None else None
                if req.get("stream"):
                    # eager submit: validation errors raise BEFORE any
                    # headers go out, so they get a clean 400/503
                    uid, chunks = service.stream(prompt, max_tokens,
                                                 temperature, keep=keep,
                                                 session=session,
                                                 prefix=prefix)
                    self._stream_sse(uid, chunks)
                    return
                out = service.complete(prompt, max_tokens, temperature,
                                       keep=keep, session=session,
                                       prefix=prefix)
                self._send(200, out)
            except (KeyError, ValueError, TypeError) as e:
                self._send(400, {"error": f"{e.args[0] if e.args else e}"})
            except (TimeoutError, RuntimeError) as e:
                # RuntimeError: scheduler dead OR no slot for preload
                self._send(503, {"error": str(e)})

        def _stream_sse(self, uid, chunks):
            """Server-sent events: one `data:` chunk per decode tick with
            the TEXT DELTA. Deltas come from re-decoding ALL tokens so
            far and holding back trailing replacement chars (an
            incomplete multi-byte sequence decodes to U+FFFD until its
            continuation bytes arrive — emitting it early would corrupt
            the stream); held-back chars flush at completion, when
            genuinely-invalid bytes are known to be final. Ends with a
            finish_reason chunk then `data: [DONE]`. Mid-stream errors
            become an SSE `error` event (the 200 already went out);
            client disconnects abandon the request in the batcher.
            """
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()  # close-delimited body (HTTP/1.0 default)

            def emit(obj):
                self.wfile.write(f"data: {json.dumps(obj)}\n\n".encode())
                self.wfile.flush()

            acc: list[int] = []
            sent_text = ""
            stopped = False
            try:
                for toks, comp in chunks:
                    if not stopped and toks:
                        acc.extend(toks)
                        if service.tok.eos_id in acc:
                            acc = acc[: acc.index(service.tok.eos_id)]
                            stopped = True
                        text = service.tok.decode(acc)
                        stable = (text if stopped
                                  else text.rstrip("\ufffd"))
                        if len(stable) > len(sent_text):
                            emit({"delta": stable[len(sent_text):]})
                            sent_text = stable
                    if comp is not None:
                        final = service.tok.decode(acc)
                        tail = final[len(sent_text):]
                        emit({"delta": tail,
                              "finish_reason": comp.finish_reason,
                              "session": comp.session,
                              "usage": {
                                  "prompt_tokens": len(comp.prompt),
                                  "completion_tokens": len(comp.tokens)}})
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
            except OSError:  # client went away mid-stream
                service.abandon_stream(uid)
            except (TimeoutError, RuntimeError) as e:
                try:
                    emit({"error": str(e)})
                except OSError:
                    service.abandon_stream(uid)

    return Handler


def build_service(args) -> BatcherService:
    import jax

    from pytorch_distributed_train_tpu.config import get_preset
    from pytorch_distributed_train_tpu.data.text import load_tokenizer
    from pytorch_distributed_train_tpu.serving import (
        ContinuousBatcher,
        Seq2SeqContinuousBatcher,
        load_params_for_serving,
    )

    cfg = get_preset(args.config)
    cfg.apply_overrides(args.set)
    tok = load_tokenizer(args.tokenizer)
    params = load_params_for_serving(cfg, args.safetensors, args.quantize)
    cls = (Seq2SeqContinuousBatcher if cfg.model.name.startswith("t5")
           else ContinuousBatcher)
    batcher = cls(cfg.model, cfg.precision, params, slots=args.slots,
                  top_k=args.top_k, top_p=args.top_p,
                  rng=jax.random.PRNGKey(args.seed))
    return BatcherService(batcher, tok,
                          max_new_default=args.max_new_default)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default="llama2_7b")
    p.add_argument("--set", action="append", default=[], metavar="KEY=VALUE")
    p.add_argument("--safetensors", required=True)
    p.add_argument("--tokenizer", default="",
                   help="local HF tokenizer dir; empty → byte tokenizer")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-new-default", type=int, default=64)
    p.add_argument("--quantize", default="", choices=["", "int8"])
    args = p.parse_args(argv)

    try:
        service = build_service(args)
    except (KeyError, ValueError, FileNotFoundError, OSError) as e:
        print(f"serve_http: error: {e.args[0] if e.args else e}",
              file=sys.stderr)
        return 2
    server = ThreadingHTTPServer((args.host, args.port),
                                 make_handler(service))
    print(f"serving on http://{args.host}:{server.server_address[1]} "
          f"(slots={args.slots})", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        service.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
