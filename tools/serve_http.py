#!/usr/bin/env python
"""HTTP serving endpoint over the continuous batcher — the end-user
service surface (torch-ecosystem analogue: TGI / vLLM's OpenAI-style
server, scoped to stdlib http.server: zero extra dependencies).

    python tools/serve_http.py --config llama2_7b \
        --safetensors model.st --tokenizer /models/llama2-tok \
        --port 8000 --slots 8 [--quantize int8]

    curl -s localhost:8000/v1/completions -d '{
        "prompt": "The capital of France is",
        "max_tokens": 32, "temperature": 0.7}'

API (JSON over POST, one object per request):
- ``POST /v1/completions``: {prompt, max_tokens?, temperature?, keep?,
  session?, stop?} → {text, finish_reason, session,
  usage:{prompt_tokens, completion_tokens}}. ``stop`` is a list of
  strings: generation CANCELS at the first occurrence (the match is
  excluded from the text, finish_reason "stop"); streamed responses
  hold back any tail that could still become a stop match. stop+keep
  is refused (a canceled request parks no session). ``keep: true`` parks the
  request's KV cache and returns a ``session`` id; posting that id as
  ``session`` continues the conversation from the resident cache (the
  prompt is then just the NEW turn — no resend of history). Sessions
  evict LRU under slot pressure (a resume then 404s in-band with
  finish_reason "session_evicted").
  ``top_p``/``min_p`` are PER-REQUEST (traced per-row operands — the
  OpenAI fields; out-of-range disables; server flags give the default);
  ``top_k`` stays a SERVER-wide flag (a static jit arg — per-request
  values would recompile). ``seed`` (OpenAI field) makes a sampled
  request REPRODUCIBLE independent of batch composition: seeded rows
  draw from their own fold_in(PRNGKey(seed), n_generated) chain, so the
  same request returns the same tokens no matter what else is in
  flight.
  ``logprobs: true`` adds each generated token's log-probability under
  the raw model distribution. ``n: k`` returns k INDEPENDENT sampled
  completions as ``choices`` (the prompt prefills once — a temporary
  prefix template forks k ways — so extra completions cost decode
  only); requires temperature > 0 (greedy duplicates are refused) and
  composes with logprobs but not stream/keep/session/stop.
- ``POST /v1/preload``: {prompt} → {session} — prefill a shared prefix
  (system prompt) once and park it; completions posted with
  ``prefix: <session>`` FORK it (the template survives, so one preload
  serves any number of requests). With ``--auto-prefix-min N`` the
  server forks AUTOMATICALLY whenever a prompt starts with a preloaded
  template of >= N tokens (longest match wins; explicit
  ``prefix``/``session`` always take precedence) — preload once, then
  every client that resends the system prompt verbatim gets the cached
  prefill without knowing the feature exists.
- ``POST /v1/chat/completions``: OpenAI chat schema — {messages:
  [{role, content}...], max_tokens?, temperature?, n?, stop?, stream?,
  logprobs?, penalties, logit_bias?} → {object: "chat.completion",
  choices: [{index, message: {role, content}, finish_reason}], usage}.
  Messages render through the tokenizer's own chat template when it
  ships one (HF ``apply_chat_template`` with the generation prompt),
  else a ChatML-ish `<|role|>` fallback. Streaming emits OpenAI
  ``chat.completion.chunk`` deltas. Stateless by definition (full
  history per call) — keep/session/prefix are refused here; resident-KV
  conversations live on ``/v1/completions``.
- ``GET /healthz``: {status, reliability, stats, weights} — liveness +
  batcher counters + the reliability section (queue depth, slot
  occupancy, admission state ``ok|shedding|draining``, SLO snapshot)
  the router's probe and balancing read, plus the MUTABLE weight state
  (current version/step, lag vs the trainer's newest published step,
  swap count) the fleet console's weight-sync panel reads.
- ``POST /admin/drain``: trigger the graceful drain over HTTP (same
  path as SIGTERM; what the router's rolling restart walks).
- ``POST /admin/weights``: live weight swap (online/,
  docs/online_training.md) — {version?} fetches that sealed version
  (default newest) from the launcher store, CRC-verifies + places it,
  and the scheduler flips params BETWEEN decode quanta: in-flight
  requests finish at the version they were admitted under (responses
  carry ``weight_version``, so stale completions are observable, never
  errors). Any fetch/verify/placement failure rejects the swap and the
  replica keeps serving its current version.

Reliability plane (serving_plane/, docs/serving_reliability.md):
per-request deadlines (``deadline_s`` field or ``--deadline-default``;
expiry cancels in the batcher — the KV slot frees NOW — and answers
504), admission control (``--max-queue-depth`` / ``--shed-ttft`` →
429 + ``Retry-After``), SLO metrics (TTFT / inter-token / queue-wait
percentiles through /healthz and the obs registry), a goodput split of
the scheduler loop (prefill/decode/stalled/idle), and a median+MAD
tail-latency detector that journals ``serve`` events and can fire the
managed profiler (``--profile-on-tail``).

Distributed tracing (obs/tracing.py, docs/observability.md): every
request continues the router's inbound ``traceparent`` (or roots a new
trace), the SLO phases — admission, queue wait, prefill, each decode
quantum, stream delivery — become spans in its tree, and a tail-based
sampler retains slow/failed/hedged/shed trees (plus a random baseline)
to per-host JSONL beside the event journal
(``--trace-dir`` / ``--trace-sample-pct`` / ``--trace-keep-slow-ms``;
``tools/timeline_report.py --trace <id>`` merges the cross-process
tree). Spans carry the replica's ``--weight-version`` correlation tag.

Threading model: request handler threads (ThreadingHTTPServer) enqueue
into the batcher under a lock and wait on a per-request event; ONE
scheduler thread drives ``batcher.step()`` — all device work stays on a
single thread, handlers only block on Python events. Requests admit into
free slots mid-stream, so concurrent callers batch together on the chip
without knowing about each other.
"""

from __future__ import annotations

import argparse
import json
import os
import queue as queue_mod
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# PDTT_SANITIZE=1: patch threading BEFORE the plane imports below run —
# they create module-global locks (events._LOCK, this file's
# _PROFILER_LOCK) at import time, and an activation from main() would
# leave those singletons unsanitized/invisible to the runtime graph.
from pytorch_distributed_train_tpu.utils import syncdbg  # noqa: E402

syncdbg.maybe_activate()

from pytorch_distributed_train_tpu.obs import events as events_lib  # noqa: E402
from pytorch_distributed_train_tpu.obs import spans as spans_lib  # noqa: E402
from pytorch_distributed_train_tpu.obs import tracing  # noqa: E402
from pytorch_distributed_train_tpu.obs.exposition import (  # noqa: E402
    CONTENT_TYPE as _METRICS_CONTENT_TYPE,
    render_metrics,
)
from pytorch_distributed_train_tpu.faults import (  # noqa: E402
    InjectedFault,
    maybe_fire as _maybe_fire_fault,
)
from pytorch_distributed_train_tpu.obs.registry import get_registry  # noqa: E402
from pytorch_distributed_train_tpu.obs.spans import span  # noqa: E402
from pytorch_distributed_train_tpu.online.swap import (  # noqa: E402
    PendingSwap,
    WeightState,
)
from pytorch_distributed_train_tpu.serving import trim_at_eos  # noqa: E402
from pytorch_distributed_train_tpu.serving_plane import (  # noqa: E402
    DeadlineExceeded,
    OverloadShed,
    ReliabilityPlane,
    TailLatencyMonitor,
)

_PROFILER = None
_PROFILER_LOCK = threading.Lock()

# _done marker for a request cancelled at its deadline: the waiter maps
# it to DeadlineExceeded (504), never to a Completion
_DEADLINE = object()


def _serving_profiler():
    """Lazy managed-profiler instance for the serving process (the
    ``POST /profile`` route + tail-latency anomaly captures): ad-hoc
    time-bounded captures into ``./profiles`` (or PDTT_PROFILE_DIR),
    ring-retained and xplane-summarized like the trainer's.
    ``PDTT_PROFILE_BACKEND=fake`` swaps in the marker-file backend
    (serving_plane/testing.py) so subprocess drills can assert a
    capture fired without a real jax trace session."""
    global _PROFILER
    with _PROFILER_LOCK:
        if _PROFILER is None:
            from pytorch_distributed_train_tpu.config import ObsConfig
            from pytorch_distributed_train_tpu.obs.profiler import (
                ManagedProfiler,
            )

            backend = None
            if os.environ.get("PDTT_PROFILE_BACKEND") == "fake":
                from pytorch_distributed_train_tpu.serving_plane.testing \
                    import FakeCaptureBackend

                backend = FakeCaptureBackend()
            cfg = ObsConfig(profile_dir=os.environ.get(
                "PDTT_PROFILE_DIR", "profiles"))
            _PROFILER = ManagedProfiler(cfg, run_dir=".", backend=backend)
        return _PROFILER



def render_chat(messages, tok) -> str:
    """OpenAI ``messages`` → prompt text. When the tokenizer ships a chat
    template (HF tokenizers: ``chat_template``), rendering is the model's
    own (apply_chat_template with the generation prompt appended) — an
    OpenAI client pointed here gets the model's canonical formatting.
    Otherwise a ChatML-ish fallback keeps the endpoint usable with the
    byte tokenizer / template-less tokenizers (documented divergence:
    role markers are `<|role|>` lines, not model-specific tokens)."""
    msgs = []
    for m in messages:
        role, content = str(m["role"]), str(m["content"])
        if role not in ("system", "user", "assistant", "tool"):
            raise ValueError(f"unknown chat role {role!r}")
        msgs.append({"role": role, "content": content})
    if not msgs:
        raise ValueError("messages must be non-empty")
    inner = getattr(tok, "_tok", None)
    if inner is not None and getattr(inner, "chat_template", None):
        return inner.apply_chat_template(msgs, tokenize=False,
                                         add_generation_prompt=True)
    return "".join(f"<|{m['role']}|>\n{m['content']}\n" for m in msgs) \
        + "<|assistant|>\n"


def _chat_response(out: dict) -> dict:
    """Completion-shaped service result → OpenAI chat.completion shape."""
    if "choices" in out:  # complete_n already returns choices
        choices = [{"index": i,
                    "message": {"role": "assistant",
                                "content": c["text"]},
                    "finish_reason": c.get("finish_reason"),
                    **({"logprobs": c["logprobs"]} if "logprobs" in c
                       else {})}
                   for i, c in enumerate(out["choices"])]
    else:
        choices = [{"index": 0,
                    "message": {"role": "assistant",
                                "content": out["text"]},
                    "finish_reason": out.get("finish_reason"),
                    **({"logprobs": out["logprobs"]}
                       if "logprobs" in out else {})}]
    return {"object": "chat.completion", "choices": choices,
            "usage": out.get("usage", {})}


def _find_stop(text: str, stops: list[str]):
    """Index of the earliest stop-string occurrence in ``text``, or
    None. (Only the cut position matters — the match itself is always
    excluded from the output.)"""
    best = None
    for st in stops:
        i = text.find(st)
        if i >= 0 and (best is None or i < best):
            best = i
    return best


def _stop_holdback(text: str, stops: list[str]) -> int:
    """Length of the longest text SUFFIX that is a proper prefix of some
    stop string — the tail a streamer must hold back because the next
    tokens could complete a stop match."""
    h = 0
    for st in stops:
        for k in range(min(len(st) - 1, len(text)), 0, -1):
            if text.endswith(st[:k]):
                h = max(h, k)
                break
    return h


class BatcherService:
    """Thread-safe facade over a (seq2seq-aware) continuous batcher: a
    single scheduler thread steps the device; callers submit and wait."""

    def __init__(self, batcher, tokenizer, *, idle_sleep_s: float = 0.005,
                 max_new_default: int = 64,
                 plane: ReliabilityPlane | None = None,
                 orphan_grace_s: float = 5.0):
        self.batcher = batcher
        self.tok = tokenizer
        self.max_new_default = max_new_default
        # Reliability plane (serving_plane/): SLO tracking always on;
        # admission control and deadlines engage only when its knobs
        # are set, so a default-constructed service behaves as before.
        self.plane = plane if plane is not None else ReliabilityPlane(
            slots=getattr(batcher, "slots", 1))
        self._lock = threading.Lock()
        self._done: dict[int, object] = {}
        self._done_ts: dict[int, float] = {}  # landing time (leak sweep)
        self._events: dict[int, threading.Event] = {}
        self._streams: dict[int, queue_mod.Queue] = {}  # uid -> chunk queue
        self._stream_seen: dict[int, int] = {}  # tokens already pushed
        # uid -> (chunk queue, landing ts) for streams whose keep=True
        # completion LANDED (scheduler popped _streams) but whose waiter
        # has not consumed the "done" yet: keeps the parked session
        # reachable if the waiter dies in that window (leak sweep GC)
        self._landed: dict[int, tuple] = {}
        self._token_seen: dict[int, int] = {}  # SLO tap over EVERY request
        # uid -> distributed-trace bookkeeping (obs/tracing.py): the
        # submitting handler's context + phase timestamps, so the
        # scheduler can record the request's queue / prefill / per-
        # quantum decode / stream spans into ITS tree. Mutated only
        # under self._lock.
        self._trace: dict[int, dict] = {}
        self._spans = spans_lib.get_recorder()
        # Online weight plane (online/swap.py): the mutable weight
        # version + staged-swap slot. main() reseeds it from
        # --weight-version; `weight_applier` (set for real backends) is
        # `(leaves, header) -> zero-arg apply fn | None` — it prepares
        # placed params in the HANDLER thread, the scheduler flips them
        # between quanta via weights.apply_pending() in _loop.
        self.weights = WeightState()
        self.weight_applier = None
        self._orphan_grace_s = orphan_grace_s
        self.error: str | None = None  # scheduler-death reason (terminal)
        self._idle_sleep_s = idle_sleep_s
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop:
            try:
                # Staged weight swap, applied BETWEEN decode quanta:
                # this is the only thread that runs batcher.step(), so
                # flipping params here can never land mid-forward, and
                # doing it outside the service lock keeps intake live
                # through the flip (handlers never read params).
                self.weights.apply_pending()
                with self._lock:
                    busy = bool(self.batcher.queue
                                or self.batcher.active_slots)
                    stall_s = 0.0
                    q_t0w = time.time()  # quantum start, wall clock
                    if busy:
                        # `serve.slow_decode` fault point: an injected
                        # delay in the decode quantum — the tail-latency
                        # spike the TTFT/inter-token detectors exist to
                        # catch; its sleep lands in the 'stalled' bucket
                        # but still counts into the CADENCE sample below
                        # (the user-visible inter-token gap includes it)
                        t_stall = time.perf_counter()
                        if _maybe_fire_fault("serve.slow_decode"):
                            stall_s = time.perf_counter() - t_stall
                            self.plane.goodput.account("stalled", stall_s)
                    queued_before = {q.uid for q in self.batcher.queue
                                     if hasattr(q, "uid")}
                    admit0 = self.batcher.stats.get("admit_ms", 0.0)
                    t_step = time.perf_counter()
                    finished = self.batcher.step() if busy else []
                    step_dt = time.perf_counter() - t_step
                    now = time.monotonic()
                    if busy:
                        # goodput split of the quantum: the batcher's own
                        # admit_ms meter is the prefill share, the rest
                        # is the batched decode
                        prefill_s = max(0.0, (self.batcher.stats.get(
                            "admit_ms", 0.0) - admit0) / 1e3)
                        self.plane.goodput.account("prefill", prefill_s)
                        self.plane.goodput.account(
                            "decode", max(0.0, step_dt - prefill_s))
                        queued_after = {q.uid for q in self.batcher.queue
                                        if hasattr(q, "uid")}
                        for uid in queued_before - queued_after:
                            self.plane.on_admitted(uid, now=now)
                            tr = self._trace.get(uid)
                            if tr is not None and "t_admit_m" not in tr:
                                tr["t_admit_m"] = now
                                tr["t_admit_w"] = time.time()
                                # the queue-wait SLO phase as a span
                                self._trace_span_locked(
                                    uid, "serve.queue", tr["tw"],
                                    now - tr["tm"])
                    # one scan feeds both consumers: _token_seen covers
                    # EVERY live request (streams included — the two
                    # cursors advance in lockstep from submit), so the
                    # SLO tap and the stream push share its fresh map
                    total_new = 0
                    if self._token_seen:
                        for uid, toks in self.batcher.new_tokens_since(
                                self._token_seen).items():
                            self._token_seen[uid] += len(toks)
                            total_new += len(toks)
                            if self.plane.on_tokens(uid, len(toks),
                                                    now=now):
                                # THIS request's TTFT tripped the tail
                                # detector: retain its trace — the
                                # anomalous sample itself, not just the
                                # journal record
                                tr = self._trace.get(uid)
                                if tr is not None:
                                    tracing.flag(tr["tid"],
                                                 "tail_latency")
                            tr = self._trace.get(uid)
                            if tr is not None:
                                if "t_first_m" not in tr:
                                    tr["t_first_m"] = now
                                    tr["t_first_w"] = time.time()
                                    # fallbacks pair: a request never
                                    # seen leaving the queue spans
                                    # submit -> first token (covers its
                                    # unobserved queue wait too)
                                    self._trace_span_locked(
                                        uid, "serve.prefill",
                                        tr.get("t_admit_w", tr["tw"]),
                                        now - tr.get("t_admit_m",
                                                     tr["tm"]),
                                        tokens=len(toks))
                                else:
                                    # one span per decode quantum that
                                    # surfaced tokens for this request
                                    self._trace_span_locked(
                                        uid, "serve.decode", q_t0w,
                                        stall_s + step_dt,
                                        tokens=len(toks))
                            q = self._streams.get(uid)
                            if q is not None:
                                q.put(("tokens", toks))
                                self._stream_seen[uid] += len(toks)
                    if busy and total_new:
                        # decode cadence: quantum / tokens surfaced — the
                        # inter-token series the tail detector watches
                        # (stall included: it is user-visible latency)
                        self.plane.on_inter_token(
                            (stall_s + step_dt) / total_new, now=now)
                    for c in finished:
                        seen = self._token_seen.pop(c.uid, None)
                        if seen is not None:
                            if len(c.tokens) > seen and self.plane.\
                                    on_tokens(c.uid, len(c.tokens) - seen,
                                              now=now):
                                # same contract as the token-scan path:
                                # the request whose TTFT tripped the
                                # tail detector retains its trace, even
                                # when its first tokens only surface in
                                # this finished-completion flush
                                tr = self._trace.get(c.uid)
                                if tr is not None:
                                    tracing.flag(tr["tid"],
                                                 "tail_latency")
                            self.plane.on_finish(
                                c.uid,
                                "ok" if c.finish_reason in ("eos", "length")
                                else c.finish_reason, now=now)
                        # after the flag above: this pops the trace entry
                        self._trace_finish_locked(
                            c.uid, now,
                            outcome="ok" if c.finish_reason
                            in ("eos", "length") else c.finish_reason)
                        q = self._streams.pop(c.uid, None)
                        if q is not None:
                            seen_s = self._stream_seen.pop(c.uid, 0)
                            if len(c.tokens) > seen_s:
                                q.put(("tokens", c.tokens[seen_s:]))
                            q.put(("done", c))
                            if getattr(c, "session", None) is not None:
                                # parked session in flight to the waiter:
                                # stay reachable until it is consumed
                                self._landed[c.uid] = (q, now)
                            continue  # streamed: never stored in _done
                        self._done[c.uid] = c
                        self._done_ts[c.uid] = now
                        # NOT popped: the _events entry is the waiter's
                        # liveness marker — the waiter removes it when it
                        # collects, so the orphan sweep can tell "waiter
                        # slow to wake" from "waiter gone" exactly
                        ev = self._events.get(c.uid)
                        if ev is not None:
                            ev.set()
                    self._sweep_locked(now)
            except Exception as e:  # noqa: BLE001 — must not die silently
                # Device/compile errors are terminal for the only decode
                # thread: record the reason (healthz flips to error), fail
                # every waiter immediately instead of letting them time out.
                with self._lock:
                    self.error = f"{type(e).__name__}: {e}"
                    for ev in self._events.values():
                        ev.set()
                    self._events.clear()
                    for q in self._streams.values():
                        q.put(("error", self.error))
                    self._streams.clear()
                    self._stream_seen.clear()
                    self._token_seen.clear()
                    self._trace.clear()
                    self._landed.clear()
                return
            if not busy:
                time.sleep(self._idle_sleep_s)
            else:
                # Fairness gap: python locks are unfair — released and
                # immediately re-acquired by this loop, a busy scheduler
                # can starve handler threads (submit, cancel, SHED) for
                # the whole busy period. One zero-sleep yields the GIL
                # so a waiting handler actually wins the lock; intake
                # must stay responsive exactly when the server is busy.
                time.sleep(0)

    # -------------------------------------------- reliability plane hooks
    def _register_locked(self, uid: int, deadline_ts: float | None) -> None:
        """Track a freshly submitted request (SLO record + token tap).
        Runs in the same lock block as the submit, so the leak sweep
        can never see a slot-holding uid it does not know. The handler
        thread's active trace context (if any) is captured here: the
        scheduler parents the request's phase spans to it."""
        self._token_seen[uid] = 0
        tr = spans_lib.current_trace()
        if tr is not None:
            self._trace[uid] = {"tid": tr[0], "parent": tr[1],
                                "tw": time.time(),
                                "tm": time.monotonic()}
        self.plane.on_submit(uid, deadline_ts)

    def _trace_span_locked(self, uid: int, name: str, t0_wall: float,
                           dur_s: float, **args) -> None:
        tr = self._trace.get(uid)
        if tr is not None:
            self._spans.record(name, t0_wall, max(0.0, dur_s),
                               trace=(tr["tid"], tr["parent"]), **args)

    def _trace_finish_locked(self, uid: int, now: float,
                             outcome: str = "ok") -> None:
        """Close a request's trace bookkeeping: record the stream-
        delivery phase (first token -> finish) and drop the entry. The
        retention DECISION stays with whoever owns the trace root (the
        HTTP handler / router) — the scheduler only contributes spans."""
        tr = self._trace.pop(uid, None)
        if tr is None:
            return
        if "t_first_m" in tr:
            self._spans.record("serve.stream", tr["t_first_w"],
                               max(0.0, now - tr["t_first_m"]),
                               trace=(tr["tid"], tr["parent"]),
                               outcome=outcome)

    def _forget_locked(self, uid: int, outcome: str) -> None:
        """Close a request's SLO record from a cancel path. A no-op for
        requests the scheduler already finished (their record closed at
        completion) — outcomes never double-count."""
        if self._token_seen.pop(uid, None) is not None:
            self.plane.on_finish(uid, outcome)
        tr = self._trace.get(uid)
        if tr is not None and outcome == "timeout":
            tracing.flag(tr["tid"], "timeout")
        self._trace_finish_locked(uid, time.monotonic(), outcome=outcome)

    def _record_admission(self, t0_wall: float, t0_mono: float) -> None:
        """The admission-gate SLO phase as a span (handler thread, only
        when the caller carries a trace — a plane-less fake service
        records nothing new)."""
        if spans_lib.current_trace() is not None:
            self._spans.record("serve.admission", t0_wall,
                               max(0.0, time.monotonic() - t0_mono))

    def _release_dead_queue_session(self, q) -> None:
        """A cancel raced its request's completion: the Completion is in
        the (now unread) chunk queue. If it parked a session, release it
        — otherwise the sid is known to nobody and squats a slot until
        LRU pressure (the exactly-once half of the slot-leak fix)."""
        try:
            while True:
                kind, payload = q.get_nowait()
                if kind == "done" and getattr(payload, "session",
                                              None) is not None:
                    self.batcher.release(payload.session)
        except queue_mod.Empty:
            pass

    def _expire_locked(self, uid: int, now: float) -> None:
        """Deadline expiry: cancel in the batcher (queued or active —
        the slot/KV frees NOW, not at natural completion) and fail the
        waiter with the 504 marker."""
        self.batcher.cancel(uid)
        self._token_seen.pop(uid, None)
        self.plane.on_finish(uid, "deadline", now=now)
        tr = self._trace.get(uid)
        if tr is not None:
            # a 504 is a tail by definition: retain its trace, and let
            # the journal record cross-link to it
            tracing.flag(tr["tid"], "deadline")
        self._trace_finish_locked(uid, now, outcome="deadline")
        events_lib.emit("serve", "deadline_expired", uid=uid,
                        trace=tr["tid"] if tr is not None else None)
        q = self._streams.pop(uid, None)
        if q is not None:
            self._stream_seen.pop(uid, None)
            q.put(("expired", f"request {uid} exceeded its deadline"))
        ev = self._events.pop(uid, None)
        if ev is not None:
            self._done[uid] = _DEADLINE
            self._done_ts[uid] = now
            ev.set()

    def _sweep_locked(self, now: float) -> None:
        """Between-steps reliability sweep (scheduler thread, under the
        service lock): (1) deadline expiries → cancel + 504; (2) slot
        leaks — any slot-holding request with no live waiter is
        reclaimed and counted (`serve_slot_leaks_total`), and a landed
        completion nobody will ever collect has its parked session
        released after a grace window."""
        for uid in self.plane.take_expired(now=now):
            self._expire_locked(uid, now)
        active_uids = getattr(self.batcher, "active_uids", None)
        if active_uids is None:
            return  # minimal fake batchers (tests): no slot surface
        waiters = set(self._events) | set(self._streams)
        for uid in active_uids():
            if uid in waiters or uid in self._done:
                continue
            self.batcher.cancel(uid)
            self._token_seen.pop(uid, None)
            tr = self._trace.get(uid)
            if tr is not None:
                tracing.flag(tr["tid"], "leak")
            self._trace_finish_locked(uid, now, outcome="leak")
            self.plane.note_leak(uid, "active_slot")
        for uid, t_done in list(self._done_ts.items()):
            if uid in self._events or now - t_done < self._orphan_grace_s:
                continue
            c = self._done.pop(uid, None)
            self._done_ts.pop(uid, None)
            if c is None or c is _DEADLINE:
                continue
            if getattr(c, "session", None) is not None:
                self.batcher.release(c.session)
            self.plane.note_leak(uid, "orphan_done")
        for uid, (q, t_land) in list(self._landed.items()):
            # landed "done" (with a parked session) nobody consumed and
            # nobody abandoned — a waiter thread that died without its
            # except path running. Release after the same grace.
            if now - t_land < self._orphan_grace_s:
                continue
            self._landed.pop(uid, None)
            self._release_dead_queue_session(q)
            self.plane.note_leak(uid, "orphan_stream")

    def healthy(self) -> bool:
        return self.error is None and self._thread.is_alive()

    def preload(self, prompt: str) -> int:
        """Park a shared-prefix template; returns its session id."""
        ids = self.tok.encode(prompt)
        if not ids:
            raise ValueError("empty prompt after tokenization")
        with self._lock:
            if self.error is not None:
                raise RuntimeError(f"scheduler dead: {self.error}")
            return self.batcher.preload(ids)

    def complete_n(self, prompt: str, max_tokens: int,
                   temperature: float, n: int,
                   timeout_s: float = 600.0, *,
                   logprobs: bool = False,
                   penalties: dict | None = None,
                   deadline_s: float | None = None) -> dict:
        """k independent sampled completions of one prompt. The prompt
        minus its last token prefills ONCE into a temporary prefix
        template; each of the k forks ingests just that final token (a
        fork must ingest something to have logits to sample from) and
        decodes its own continuation — the forks batch together in the
        decode step, so extra completions cost decode only. The template
        is released when all k land."""
        if n < 2:
            raise ValueError("n must be >= 2 (plain complete() covers 1)")
        if temperature <= 0.0:
            raise ValueError(
                "n > 1 with temperature 0 would return n identical "
                "greedy completions — set a temperature")
        ids = self.tok.encode(prompt)
        if not ids:
            raise ValueError("empty prompt after tokenization")
        events: dict[int, threading.Event] = {}
        sid = None
        # Repetition-penalized n>1 requests always prefill the FULL
        # prompt per fork: the shared-prefix template would leave only
        # the final token in each fork's repetition context, making the
        # distribution depend on slot availability (template admitted or
        # not). Deterministic semantics beat the saved prefills.
        # Only repetition_penalty scores the prompt — presence/frequency
        # count generated tokens only (OpenAI semantics) and logit_bias
        # is context-independent, so neither disables the shared-prefix
        # optimization; and EFFECTIVE values gate, not key presence (a
        # client sending the explicit OpenAI defaults must not lose the
        # optimization).
        force_full_prompt = (
            float((penalties or {}).get("repetition_penalty", 1.0)) != 1.0)
        # the shared-prefill trick needs session support (causal
        # batchers) and a >= 2-token prompt; otherwise n plain submits
        # still serve the request — just paying n prefills
        share = (getattr(self.batcher, "supports_sessions", False)
                 and len(ids) >= 2 and not force_full_prompt)

        def _cleanup_locked():
            """Release the template and withdraw every fork: cancel the
            unfinished (they then never complete — no abandon marker
            needed), drop any already-landed results (the lock excludes
            the scheduler, so cancel-vs-finish cannot race)."""
            nonlocal sid
            if sid is not None:
                self.batcher.release(sid)
                sid = None
            for uid in events:
                if not self.batcher.cancel(uid):
                    self._done.pop(uid, None)
                    self._done_ts.pop(uid, None)
                self._events.pop(uid, None)
                self._forget_locked(uid, "cancelled")

        deadline_ts = self.plane.resolve_deadline(deadline_s)
        adm_w, adm_m = time.time(), time.monotonic()
        with self._lock:
            if self.error is not None:
                raise RuntimeError(f"scheduler dead: {self.error}")
            self.plane.admit_or_raise(len(self.batcher.queue))
            try:
                if share and self.batcher.can_preload(len(ids) - 1):
                    # (a pure capacity check, not except RuntimeError: a
                    # broad catch would also swallow device errors from
                    # the synchronous template prefill)
                    sid = self.batcher.preload(ids[:-1])
                # else: every slot busy right now — a template can't
                # queue, but plain submits can; fall back to n
                # independent prefills rather than 503ing a request
                # that only needs to wait its turn
                for _ in range(n):
                    uid = self.batcher.submit(
                        ids[-1:] if sid is not None else ids, max_tokens,
                        temperature=temperature, eos_id=self.tok.eos_id,
                        prefix=sid, **(penalties or {}))
                    events[uid] = threading.Event()
                    self._events[uid] = events[uid]
                    self._register_locked(uid, deadline_ts)
            except (ValueError, RuntimeError):
                _cleanup_locked()
                raise
        self._record_admission(adm_w, adm_m)
        try:
            choices = []
            total_generated = 0
            # One timeout budget for the whole request, not timeout_s per
            # fork: waits are sequential, so each gets what remains.
            deadline = time.monotonic() + timeout_s
            for uid, ev in events.items():
                if not ev.wait(max(0.0, deadline - time.monotonic())):
                    raise TimeoutError(f"completion {uid} timed out")
                with self._lock:
                    c = self._done.pop(uid, None)
                    self._done_ts.pop(uid, None)
                    self._events.pop(uid, None)
                if c is _DEADLINE:
                    raise DeadlineExceeded(
                        f"request {uid} exceeded its deadline; "
                        "slot reclaimed")
                if c is None:
                    raise RuntimeError(f"scheduler dead: {self.error}")
                total_generated += len(c.tokens)
                new = trim_at_eos(c.tokens, self.tok.eos_id)
                choice = {"text": self.tok.decode(new),
                          "finish_reason": c.finish_reason}
                if logprobs:
                    choice["logprobs"] = [round(v, 6)
                                          for v in c.logprobs[: len(new)]]
                choices.append(choice)
            with self._lock:
                if sid is not None:
                    self.batcher.release(sid)
                    sid = None
        except BaseException:
            with self._lock:
                _cleanup_locked()
            raise
        return {"choices": choices, "session": None,
                "usage": {"prompt_tokens": len(ids),
                          "completion_tokens": total_generated}}

    def complete(self, prompt: str, max_tokens: int, temperature: float,
                 timeout_s: float = 600.0, *, keep: bool = False,
                 session: int | None = None, prefix: int | None = None,
                 stop: list[str] | None = None,
                 logprobs: bool = False,
                 penalties: dict | None = None,
                 deadline_s: float | None = None) -> dict:
        if stop:
            if keep:
                raise ValueError(
                    "stop with keep is unsupported (a stop-canceled "
                    "request parks no session)")
            return self._complete_with_stop(
                prompt, max_tokens, temperature, timeout_s,
                session=session, prefix=prefix, stop=stop,
                logprobs=logprobs, penalties=penalties,
                deadline_s=deadline_s)
        ids = self.tok.encode(prompt)
        if not ids:
            raise ValueError("empty prompt after tokenization")
        deadline_ts = self.plane.resolve_deadline(deadline_s)
        ev = threading.Event()
        adm_w, adm_m = time.time(), time.monotonic()
        with self._lock:
            # Checked UNDER the lock: the scheduler's death path clears
            # _events under this lock, so registering after a pre-lock
            # check could enqueue an event nothing will ever set.
            if self.error is not None:
                raise RuntimeError(f"scheduler dead: {self.error}")
            self.plane.admit_or_raise(len(self.batcher.queue))
            uid = self.batcher.submit(ids, max_tokens,
                                      temperature=temperature,
                                      eos_id=self.tok.eos_id,
                                      keep=keep, session=session,
                                      prefix=prefix, **(penalties or {}))
            self._events[uid] = ev
            self._register_locked(uid, deadline_ts)
        self._record_admission(adm_w, adm_m)
        # the scheduler's deadline sweep answers expiry (504 + slot
        # reclaim); the local wait only needs to outlast it slightly
        wait_s = timeout_s if deadline_ts is None else min(
            timeout_s, max(0.0, deadline_ts - time.monotonic()) + 2.0)
        timed_out = not ev.wait(wait_s)
        with self._lock:
            # The completion may have landed in the wait→lock window even
            # on the timeout path — prefer returning it over withdrawing.
            c = self._done.pop(uid, None)
            self._done_ts.pop(uid, None)
            self._events.pop(uid, None)  # this waiter is done waiting
            if timed_out and c is None:
                # Withdraw NOW (the slot-leak fix, non-streamed flavor):
                # a dead waiter's request must not decode on — and hold
                # its KV slot — until natural completion.
                self.batcher.cancel(uid)
                self._forget_locked(uid, "timeout")
        if c is _DEADLINE:
            raise DeadlineExceeded(
                f"request {uid} exceeded its deadline; slot reclaimed")
        if c is None:
            if timed_out:
                raise TimeoutError(
                    f"request {uid} timed out after {timeout_s}s")
            raise RuntimeError(f"scheduler dead: {self.error}")
        new = trim_at_eos(c.tokens, self.tok.eos_id)
        out = {
            "text": self.tok.decode(new),
            "finish_reason": c.finish_reason,
            "session": c.session,
            "usage": {"prompt_tokens": len(ids),
                      "completion_tokens": len(c.tokens)},
        }
        if logprobs:
            out["logprobs"] = [round(v, 6)
                               for v in c.logprobs[: len(new)]]
        return out

    def _complete_with_stop(self, prompt, max_tokens, temperature,
                            timeout_s, *, session, prefix, stop,
                            logprobs: bool = False,
                            penalties: dict | None = None,
                            deadline_s: float | None = None) -> dict:
        """Stop-sequence completions ride the streaming tap: decode the
        accumulated text each tick, CANCEL the request at the first stop
        match (it stops consuming decode steps), trim the match out."""
        uid, n_prompt, chunks = self.stream(prompt, max_tokens,
                                            temperature, timeout_s,
                                            session=session,
                                            prefix=prefix,
                                            penalties=penalties,
                                            deadline_s=deadline_s)
        acc: list[int] = []
        comp = None
        for toks, c in chunks:
            acc.extend(toks)
            if c is not None:
                comp = c
                break
            kept = trim_at_eos(acc, self.tok.eos_id)
            text = self.tok.decode(kept)
            hit = _find_stop(text, stop)
            if hit is not None:
                self.cancel_stream(uid)
                out = {"text": text[: hit], "finish_reason": "stop",
                       "session": None,
                       "usage": {"prompt_tokens": n_prompt,
                                 "completion_tokens": len(acc)}}
                if logprobs:
                    # the streaming tap carries token ids only; a
                    # stop-canceled request has no Completion to read
                    # per-token logprobs from — explicit null, not absent
                    out["logprobs"] = None
                return out
        # finished naturally — the final flush may still contain a stop
        kept = trim_at_eos(comp.tokens, self.tok.eos_id)
        text = self.tok.decode(kept)
        hit = _find_stop(text, stop)
        reason = comp.finish_reason
        if hit is not None:
            text, reason = text[: hit], "stop"
        out = {"text": text, "finish_reason": reason, "session": None,
               "usage": {"prompt_tokens": n_prompt,
                         "completion_tokens": len(comp.tokens)}}
        if logprobs:
            out["logprobs"] = [round(v, 6)
                               for v in comp.logprobs[: len(kept)]]
        return out

    def stream(self, prompt: str, max_tokens: int, temperature: float,
               timeout_s: float = 600.0, *, keep: bool = False,
               session: int | None = None, prefix: int | None = None,
               penalties: dict | None = None,
               deadline_s: float | None = None):
        """Returns (uid, chunk iterator). Validation and submission run
        EAGERLY (so callers can reject before committing to a response);
        the iterator yields (new_token_ids, completion_or_None) chunks as
        the batched decode produces them, ending with the Completion.
        Returns (uid, prompt_token_count, iterator); ``timeout_s`` bounds
        the wait for EACH chunk (a deadline tightens it — a stalled
        stream expires at the deadline, not at the generic timeout). A
        caller that stops consuming must call ``abandon_stream(uid)``
        (or ``cancel_stream`` to also stop the decode)."""
        ids = self.tok.encode(prompt)
        if not ids:
            raise ValueError("empty prompt after tokenization")
        deadline_ts = self.plane.resolve_deadline(deadline_s)
        q: queue_mod.Queue = queue_mod.Queue()
        adm_w, adm_m = time.time(), time.monotonic()
        with self._lock:
            if self.error is not None:
                raise RuntimeError(f"scheduler dead: {self.error}")
            self.plane.admit_or_raise(len(self.batcher.queue))
            uid = self.batcher.submit(ids, max_tokens,
                                      temperature=temperature,
                                      eos_id=self.tok.eos_id,
                                      keep=keep, session=session,
                                      prefix=prefix, **(penalties or {}))
            self._streams[uid] = q
            self._stream_seen[uid] = 0
            self._register_locked(uid, deadline_ts)
        self._record_admission(adm_w, adm_m)

        def chunks():
            while True:
                wait_s = timeout_s if deadline_ts is None else min(
                    timeout_s,
                    max(0.05, deadline_ts - time.monotonic() + 2.0))
                try:
                    kind, payload = q.get(timeout=wait_s)
                except queue_mod.Empty:
                    self.abandon_stream(uid)
                    raise TimeoutError(
                        f"request {uid} produced no chunk for {wait_s}s")
                if kind == "tokens":
                    yield payload, None
                elif kind == "done":
                    # consumed: the waiter frame now holds the payload
                    # (abandon_stream's `landed=` covers it from here)
                    with self._lock:
                        self._landed.pop(uid, None)
                    yield [], payload
                    return
                elif kind == "expired":  # deadline sweep cancelled it
                    raise DeadlineExceeded(str(payload))
                else:  # "error"
                    raise RuntimeError(f"scheduler dead: {payload}")

        return uid, len(ids), chunks()

    def cancel_stream(self, uid: int) -> None:
        """Cancel an in-flight streamed request (stop-sequence match) and
        drop its tap. If the request raced to completion first, any
        session its keep=True completion parked is released from the
        dead chunk queue — the exactly-once contract of the slot-leak
        fix (before it, a raced keep-completion's session squatted a
        slot nobody could ever release)."""
        with self._lock:
            q = self._streams.pop(uid, None)
            self._stream_seen.pop(uid, None)
            if not self.batcher.cancel(uid):
                if q is None:  # landed already: the queue moved
                    q, _ = self._landed.pop(uid, (None, None))
                if q is not None:
                    self._release_dead_queue_session(q)
            self._forget_locked(uid, "cancelled")

    def abandon_stream(self, uid: int, landed=None) -> None:
        """Stop tracking a streaming request whose consumer went away
        (client disconnect, chunk timeout) — and WITHDRAW it from the
        batcher. This is the abandoned-stream slot-leak fix: before it,
        a stream abandoned between submit and first token kept decoding
        into its KV slot until natural completion, and a keep=True
        completion then parked a session nobody owned (a permanent slot
        leak — exactly what the ``serve.slot_leak`` drill injects by
        skipping the release below; the scheduler's leak sweep must
        catch and reclaim it). If the completion already LANDED, its
        queue (still holding the "done") is drained from ``_landed``;
        ``landed=`` hands over a completion the caller consumed but
        failed to deliver (final-chunk write died — the client never
        learned the session id, so its parked session is released). A
        no-op once the request finished AND its session was delivered."""
        with self._lock:
            q = self._streams.pop(uid, None)
            if q is None:
                q, _ = self._landed.pop(uid, (None, None))
                if q is not None:
                    self._release_dead_queue_session(q)
                elif landed is not None and getattr(
                        landed, "session", None) is not None:
                    self.batcher.release(landed.session)
                return
            self._stream_seen.pop(uid, None)
            if _maybe_fire_fault("serve.slot_leak"):
                return  # drill: walk away without releasing anything
            if not self.batcher.cancel(uid):
                # raced to completion: its parked session (if any) is in
                # the dead queue — release exactly once
                self._release_dead_queue_session(q)
            self._forget_locked(uid, "abandoned")

    def stats(self) -> dict:
        # Snapshot WITHOUT the step lock: the counters are plain ints
        # mutated only by the scheduler thread, and a liveness probe must
        # not block behind a minutes-long first-compile step quantum.
        return dict(self.batcher.stats)

    def shutdown(self):
        self._stop = True
        self._thread.join(timeout=5)


class GracefulDrain:
    """SIGTERM → drain-and-exit for the HTTP server (the load-balancer
    contract every production rollout needs): stop ACCEPTING work (new
    POSTs get a retryable 503, ``/healthz`` flips to ``draining`` so the
    LB pulls this backend), let IN-FLIGHT requests finish — bounded by
    ``grace_s``, a wedged decode must not outlive the scheduler's
    SIGKILL — then stop the server and the batcher thread cleanly.

    The SIGTERM handler CHAINS to whatever was installed before it (the
    same convention as faults/preemption.py and the watchdog dump
    handler), so composing with diagnostics handlers works in either
    install order. ``request_drain()`` is also callable directly (tests,
    an admin endpoint)."""

    def __init__(self, server, service, grace_s: float = 30.0):
        self.server = server
        self.service = service
        self.grace_s = grace_s
        self.draining = False
        self._inflight = 0
        self._lock = threading.Lock()
        self._prev = None
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------- request gate
    def begin_request(self) -> bool:
        """Admit one request; False once draining (caller answers 503)."""
        with self._lock:
            if self.draining:
                return False
            self._inflight += 1
            return True

    def end_request(self) -> None:
        with self._lock:
            self._inflight -= 1

    # ------------------------------------------------------------ drain
    def install(self) -> None:
        try:
            self._prev = signal.getsignal(signal.SIGTERM)
            signal.signal(signal.SIGTERM, self._handle)
        except ValueError:
            pass  # not the main thread (tests drive request_drain directly)

    def _handle(self, signum, frame) -> None:
        self.request_drain()
        prev = self._prev
        if callable(prev) and prev not in (signal.SIG_DFL, signal.SIG_IGN):
            prev(signum, frame)

    def request_drain(self) -> None:
        with self._lock:
            if self.draining:
                return
            self.draining = True
        events_lib.emit("serve", "drain_begin", grace_s=self.grace_s)
        print(f"[serve] draining: no new requests; waiting up to "
              f"{self.grace_s:.0f}s for in-flight to finish", flush=True)
        # The actual wait runs off-thread: a signal handler (or a test)
        # must return immediately, and server.shutdown() deadlocks when
        # called from a handler thread the server is joining.
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name="serve-drain")
        self._thread.start()

    def _drain(self) -> None:
        deadline = time.monotonic() + self.grace_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.05)
        with self._lock:
            leftover = self._inflight
        if leftover:
            print(f"[serve] drain grace expired with {leftover} request(s) "
                  "still in flight — shutting down anyway", flush=True)
        else:
            print("[serve] drained; shutting down", flush=True)
        events_lib.emit("serve", "drain_done", leftover=leftover)
        self.server.shutdown()  # unblocks serve_forever()
        self.service.shutdown()


def _swap_store(service):
    """The replica's handle onto the weight-publish plane, built lazily
    and cached on the service (same resilient wrapper --advertise uses;
    None outside a store-backed job)."""
    store = getattr(service, "_weight_store", None)
    if store is None:
        from pytorch_distributed_train_tpu import store_plane

        store = store_plane.resilient_worker_store(name="weight-swap")
        if store is not None:
            service._weight_store = store
    return store


def _swap_weights(service, req: dict) -> tuple[int, dict]:
    """POST /admin/weights body: {"version": N?} (default: the newest
    sealed version). Fetch → CRC verify → place (handler thread) →
    stage → scheduler applies between quanta. Every failure leaves the
    replica serving its CURRENT version — a swap can reject, it cannot
    half-land (docs/online_training.md swap protocol)."""
    weights = getattr(service, "weights", None)
    if weights is None:
        return 503, {"error": "no weight plane on this service"}
    t0 = time.monotonic()
    want = req.get("version")
    want = int(want) if want is not None else None
    # `weights.swap` fault point: the injected failure is a 503 BEFORE
    # any fetch — the replica keeps its version, the caller retries
    try:
        _maybe_fire_fault("weights.swap")
    except InjectedFault as e:
        weights.reject(want if want is not None else "latest",
                       f"injected: {e}")
        return 503, {"error": str(e), "serving": weights.version}
    store = _swap_store(service)
    if store is None:
        return 503, {"error": "no TPUSTORE_ADDR: weight swaps ride the "
                              "launcher store"}
    from pytorch_distributed_train_tpu.online import publisher as pub_lib

    fetched = pub_lib.fetch_version(store, want)
    if fetched is None:
        # unsealed / incomplete / corrupt (CRC) — indistinguishable on
        # purpose: none of them may touch the serving params
        weights.reject(want if want is not None else "latest",
                       "verify_failed")
        return 409, {"error": "published version unavailable or failed "
                              "verification", "serving": weights.version}
    info, leaves, header = fetched
    weights.note_published(info["version"], info["step"])
    old = weights.version
    if str(info["version"]) == old:
        return 200, {"status": "already_current", "version": old}
    apply_fn = None
    if service.weight_applier is not None:
        # the expensive half (host→device placement into the serving
        # mesh's shardings) runs HERE, off the scheduler's critical path
        apply_fn = service.weight_applier(leaves, header)
        if apply_fn is None:
            weights.reject(info["version"], "placement_mismatch")
            return 409, {"error": "published leaves do not match the "
                                  "serving params template",
                         "serving": old}
    pending = PendingSwap(version=str(info["version"]),
                          step=int(info["step"]), apply_fn=apply_fn,
                          t0=t0)
    if not weights.stage(pending):
        return 409, {"error": "another swap is in flight",
                     "serving": old}
    if not pending.done.wait(timeout=30.0):
        return 504, {"error": "swap staged but not applied within 30s "
                              "(scheduler wedged?)", "serving": old}
    if pending.error:
        return 500, {"error": pending.error, "serving": weights.version}
    return 200, {"status": "swapped", "version": weights.version,
                 "old_version": old, "step": int(info["step"]),
                 "swap_seconds": round(pending.duration_s, 6)}


def make_handler(service: BatcherService, drain: GracefulDrain | None = None):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _send(self, code: int, obj: dict,
                  headers: dict | None = None):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _health_body(self, status: str) -> dict:
            # Reliability section riding /healthz (lock-free w.r.t. the
            # scheduler — a probe must not block behind a wedged decode):
            # queue depth, slot occupancy, admission state and the SLO
            # snapshot, so the router's balancing/probing needs no
            # second endpoint. Omitted for plane-less service fakes
            # (tests): their healthz keeps the pre-plane shape.
            out = {"status": status, "stats": service.stats()}
            weights = getattr(service, "weights", None)
            if weights is not None:
                # mutable weight version (online/swap.py): the swap is
                # visible here without a restart — current version/step,
                # lag vs the trainer's newest published step, swap count
                out["weights"] = weights.snapshot()
            batcher = getattr(service, "batcher", None)
            plane = getattr(service, "plane", None)
            if batcher is None or plane is None:
                return out
            depth = len(batcher.queue)
            acct = getattr(batcher, "slot_accounting", lambda: {})()
            rel = plane.snapshot(depth, acct)
            if status == "draining":
                rel["admission"] = "draining"
            out["reliability"] = rel
            return out

        def do_GET(self):
            if self.path == "/healthz":
                if drain is not None and drain.draining:
                    # 503 so load balancers stop routing here; the body
                    # says WHY (a drain, not a failure).
                    self._send(503, self._health_body("draining"))
                elif service.healthy():
                    self._send(200, self._health_body("ok"))
                else:
                    body = self._health_body("error")
                    body["error"] = service.error
                    self._send(503, body)
            elif self.path.split("?", 1)[0] == "/metrics":
                # Prometheus scrape (obs/): request counters + latency
                # histograms + batcher gauges, same registry the trainer
                # sidecar serves. Reads plain counters only — never the
                # scheduler lock, so a wedged decode stays scrapable.
                for k, v in service.stats().items():
                    if isinstance(v, (int, float)):
                        get_registry().gauge(
                            f"serve_batcher_{k}",
                            help="continuous-batcher counter").set(v)
                for k, v in getattr(getattr(service, "batcher", None),
                                    "slot_accounting", lambda: {})().items():
                    get_registry().gauge(
                        f"serve_slots_{k}",
                        help="slot/queue occupancy at scrape time").set(v)
                body = render_metrics().encode()
                self.send_response(200)
                self.send_header("Content-Type", _METRICS_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._send(404, {"error": "unknown path"})

        def do_POST(self):
            if self.path.split("?", 1)[0] == "/admin/drain":
                # The drain path over HTTP (same effect as SIGTERM): the
                # router's rolling restart walks replicas through this.
                if drain is None:
                    self._send(503, {"error": "no drain controller"})
                else:
                    drain.request_drain()
                    self._send(202, {"status": "draining"})
                return
            if self.path.split("?", 1)[0] == "/admin/weights":
                # Live weight swap (online/; docs/online_training.md):
                # fetch + verify the published version, stage it, wait
                # for the scheduler to flip it between quanta. Subject
                # to the drain gate: a draining replica is leaving the
                # rotation — swapping it is wasted work.
                if drain is not None and not drain.begin_request():
                    self._send(503, {"error": "server draining"})
                    return
                try:
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        req = json.loads(self.rfile.read(n) or b"{}")
                    except ValueError as e:
                        self._send(400, {"error": f"bad body: {e}"})
                        return
                    # the swap rides the driver's trace: its spans carry
                    # the OLD weight_version tag before apply_pending
                    # re-stamps, the NEW one after — the flip the
                    # timeline report shows
                    ctx = tracing.continue_or_start(
                        self.headers.get("traceparent"))
                    t0 = time.monotonic()
                    try:
                        with tracing.activate(ctx):
                            with span("http.admin.weights"):
                                code, obj = _swap_weights(service, req)
                    finally:
                        tracing.get_tracer().finish(
                            ctx.trace_id,
                            dur_s=time.monotonic() - t0)
                    self._send(code, obj)
                finally:
                    if drain is not None:
                        drain.end_request()
                return
            if self.path.split("?", 1)[0] == "/profile":
                # On-demand capture of the SERVING process (managed
                # profiler plane, obs/profiler.py): time-bounded since
                # there is no step loop to count windows in. Body:
                # {"seconds": N} (default 3, capped at 60). Subject to
                # the drain gate like any other POST: a draining server
                # must not accept new profiling work whose stop timer
                # would outlive the process.
                if drain is not None and not drain.begin_request():
                    self._send(503, {"error": "server draining"})
                    return
                try:
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        req = json.loads(self.rfile.read(n) or b"{}")
                        seconds = min(60.0, max(
                            0.1, float(req.get("seconds", 3.0))))
                        logdir = _serving_profiler().capture_for_seconds(
                            seconds, reason="http")
                    except Exception as e:
                        self._send(500,
                                   {"error": f"{type(e).__name__}: {e}"})
                        return
                    if logdir is None:
                        self._send(409, {"error": "capture already open"})
                    else:
                        self._send(202, {"status": "capturing",
                                         "seconds": seconds,
                                         "dir": logdir})
                finally:
                    if drain is not None:
                        drain.end_request()
                return
            if self.path not in ("/v1/completions", "/v1/preload",
                                 "/v1/chat/completions"):
                self._send(404, {"error": "unknown path"})
                return
            if drain is not None and not drain.begin_request():
                # Draining: the retryable status (the same contract as
                # an injected handler fault) — clients re-resolve and
                # land on a healthy backend.
                self._send(503, {"error": "server draining"})
                return
            try:
                self._do_post_admitted()
            finally:
                if drain is not None:
                    drain.end_request()

        def _do_post_admitted(self):
            # Request-handling observability: a counter per path and a
            # span covering the handler (wait + decode + serialization)
            # — span durations land in the span_seconds{name=...}
            # histogram, so /metrics carries request latency for free.
            get_registry().counter(
                "http_requests_total", labels={"path": self.path},
                help="requests by path").inc()
            # `serve.handler` fault point (faults/; armed via the
            # PDTT_FAULTS env var): an injected handler fault becomes a
            # client-visible 503 — the retryable status well-behaved
            # clients already handle — and a faults_injected_total tick.
            try:
                _maybe_fire_fault("serve.handler")
            except InjectedFault as e:
                self._send(503, {"error": str(e)})
                return
            # Distributed tracing (obs/tracing.py): honor the router's
            # inbound traceparent (NEVER mint over it — the trace-
            # hygiene analyze pass enforces this), else start a root.
            # The http span becomes the replica-side tree root; the
            # scheduler parents the request's queue/prefill/decode/
            # stream phase spans under it; the tail-based retention
            # decision runs when the request ends, below.
            ctx = tracing.continue_or_start(
                self.headers.get("traceparent"))
            t0 = time.monotonic()
            try:
                with tracing.activate(ctx):
                    # full path in the name: '/v1/completions' and
                    # '/v1/chat/completions' must be distinct histogram
                    # series
                    with span("http." + self.path.strip("/")
                              .replace("/", "."), path=self.path):
                        self._handle_post()
            finally:
                # finally: a client that disconnects mid-write raises
                # OSError out of _handle_post's response send — the
                # retention decision (often for an already-flagged 504)
                # must still run
                tracing.get_tracer().finish(
                    ctx.trace_id, dur_s=time.monotonic() - t0)

        def _handle_post(self):
            chat = self.path == "/v1/chat/completions"
            # weight version at ADMIT time: a request straddling a live
            # swap completes at the version it was admitted under — the
            # response says which (stale-version completions are
            # observable, never errors; docs/online_training.md)
            weights = getattr(service, "weights", None)
            admit_version = (weights.version if weights is not None
                             else None)
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                if chat:
                    # OpenAI chat is STATELESS (full history per call) —
                    # the resident-KV session/prefix machinery belongs to
                    # the completions endpoint.
                    if any(k in req for k in ("keep", "session", "prefix")):
                        raise ValueError(
                            "chat/completions is stateless (full messages "
                            "per call); keep/session/prefix live on "
                            "/v1/completions")
                    prompt = render_chat(req["messages"], service.tok)
                else:
                    prompt = str(req["prompt"])
                if self.path == "/v1/preload":
                    self._send(200, {"session": service.preload(prompt)})
                    return
                max_tokens = int(req.get("max_tokens",
                                         service.max_new_default))
                temperature = float(req.get("temperature", 0.0))
                # per-request wall-clock budget (serving_plane deadlines:
                # expiry cancels in the batcher and answers 504; the
                # server's --deadline-default/--deadline-max knobs apply)
                deadline_s = req.get("deadline_s")
                deadline_s = (float(deadline_s)
                              if deadline_s is not None else None)
                keep = bool(req.get("keep", False))
                session = req.get("session")
                session = int(session) if session is not None else None
                prefix = req.get("prefix")
                prefix = int(prefix) if prefix is not None else None
                stop = req.get("stop")
                if stop is not None:
                    if isinstance(stop, str):
                        stop = [stop]
                    stop = [str(x) for x in stop if str(x)]
                penalties = {
                    k: float(req[k])
                    for k in ("repetition_penalty", "presence_penalty",
                              "frequency_penalty", "top_p", "min_p")
                    if k in req
                }
                if "seed" in req and req["seed"] is not None:
                    # OpenAI `seed`: reproducible sampling independent of
                    # batch composition (per-row key chain in serving)
                    penalties["seed"] = int(req["seed"])
                if "logit_bias" in req:
                    # OpenAI convention: string token-id keys
                    penalties["logit_bias"] = {
                        int(k): float(v)
                        for k, v in dict(req["logit_bias"]).items()}
                n = int(req.get("n", 1))
                if n > 1:
                    if (req.get("stream") or keep or session is not None
                            or prefix is not None or stop):
                        raise ValueError(
                            "n > 1 composes with logprobs only (not "
                            "stream/keep/session/prefix/stop)")
                    out = service.complete_n(
                        prompt, max_tokens, temperature, n,
                        logprobs=bool(req.get("logprobs", False)),
                        penalties=penalties, deadline_s=deadline_s)
                    resp = _chat_response(out) if chat else out
                    if admit_version is not None:
                        resp["weight_version"] = admit_version
                    self._send(200, resp)
                    return
                if req.get("stream"):
                    if stop and keep:
                        raise ValueError(
                            "stop with keep is unsupported (a "
                            "stop-canceled request parks no session)")
                    # eager submit: validation errors raise BEFORE any
                    # headers go out, so they get a clean 400/503
                    uid, n_prompt, chunks = service.stream(
                        prompt, max_tokens, temperature, keep=keep,
                        session=session, prefix=prefix,
                        penalties=penalties, deadline_s=deadline_s)
                    self._stream_sse(uid, chunks, stop=stop,
                                     n_prompt=n_prompt, chat=chat)
                    return
                out = service.complete(prompt, max_tokens, temperature,
                                       keep=keep, session=session,
                                       prefix=prefix, stop=stop,
                                       logprobs=bool(
                                           req.get("logprobs", False)),
                                       penalties=penalties,
                                       deadline_s=deadline_s)
                resp = _chat_response(out) if chat else out
                if admit_version is not None:
                    resp["weight_version"] = admit_version
                self._send(200, resp)
            except (KeyError, ValueError, TypeError) as e:
                self._send(400, {"error": f"{e.args[0] if e.args else e}"})
            except OverloadShed as e:
                # load shedding: the admission controller refused the
                # queue slot — 429 with the standard back-off header;
                # the body repeats it so relays (serve_router) can
                # reconstruct the header they cannot see
                self._send(429, {"error": str(e),
                                 "retry_after_s": int(e.retry_after_s)},
                           headers={"Retry-After":
                                    str(int(e.retry_after_s))})
            except DeadlineExceeded as e:
                tracing.flag_current("deadline")
                self._send(504, {"error": str(e)})
            except (TimeoutError, RuntimeError) as e:
                # RuntimeError: scheduler dead OR no slot for preload
                tracing.flag_current("error")
                self._send(503, {"error": str(e)})

        def _stream_sse(self, uid, chunks, stop=None, n_prompt=0,
                        chat=False):
            """Server-sent events: one `data:` chunk per decode tick with
            the TEXT DELTA. Deltas come from re-decoding ALL tokens so
            far and holding back trailing replacement chars (an
            incomplete multi-byte sequence decodes to U+FFFD until its
            continuation bytes arrive — emitting it early would corrupt
            the stream); held-back chars flush at completion, when
            genuinely-invalid bytes are known to be final. Ends with a
            finish_reason chunk then `data: [DONE]`. Mid-stream errors
            become an SSE `error` event (the 200 already went out);
            client disconnects abandon the request in the batcher.
            """
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()  # close-delimited body (HTTP/1.0 default)

            def emit(obj):
                if chat and ("delta" in obj or "finish_reason" in obj):
                    # OpenAI chat.completion.chunk shape; error events
                    # pass through untranslated.
                    obj = {
                        "object": "chat.completion.chunk",
                        "choices": [{
                            "index": 0,
                            "delta": ({"content": obj["delta"]}
                                      if obj.get("delta") else {}),
                            "finish_reason": obj.get("finish_reason"),
                        }],
                        **({"usage": obj["usage"]}
                           if "usage" in obj else {}),
                    }
                self.wfile.write(f"data: {json.dumps(obj)}\n\n".encode())
                self.wfile.flush()

            acc: list[int] = []
            sent_text = ""
            stopped = False
            undelivered = None  # consumed completion not yet sent
            try:
                for toks, comp in chunks:
                    if not stopped and toks:
                        acc.extend(toks)
                        trimmed = trim_at_eos(acc, service.tok.eos_id)
                        stopped = len(trimmed) < len(acc)
                        acc = trimmed
                        text = service.tok.decode(acc)
                        if stop:
                            hit = _find_stop(text, stop)
                            if hit is not None:
                                # cancel on-device work; emit up to the
                                # match and finish with reason "stop"
                                service.cancel_stream(uid)
                                cut = text[: hit]
                                if len(cut) > len(sent_text):
                                    emit({"delta": cut[len(sent_text):]})
                                emit({"delta": "",
                                      "finish_reason": "stop",
                                      "session": None,
                                      "usage": {
                                          "prompt_tokens": n_prompt,
                                          "completion_tokens": len(acc)}})
                                break
                        stable = (text if stopped
                                  else text.rstrip("\ufffd"))
                        if stop:
                            # hold back any tail that could still grow
                            # into a stop match next tick
                            h = _stop_holdback(stable, stop)
                            stable = stable[: len(stable) - h]
                        if len(stable) > len(sent_text):
                            emit({"delta": stable[len(sent_text):]})
                            sent_text = stable
                    if comp is not None:
                        final = service.tok.decode(acc)
                        reason = comp.finish_reason
                        if stop:
                            hit = _find_stop(final, stop)
                            if hit is not None:
                                final, reason = final[: hit], "stop"
                        tail = final[len(sent_text):]
                        undelivered = comp  # until the session goes out
                        emit({"delta": tail,
                              "finish_reason": reason,
                              "session": comp.session,
                              "usage": {
                                  "prompt_tokens": len(comp.prompt),
                                  "completion_tokens": len(comp.tokens)}})
                        undelivered = None
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
            except OSError:  # client went away mid-stream
                service.abandon_stream(uid, landed=undelivered)
            except (TimeoutError, RuntimeError) as e:
                try:
                    emit({"error": str(e)})
                except OSError:
                    service.abandon_stream(uid)

    return Handler


def build_plane(args) -> ReliabilityPlane:
    """ReliabilityPlane from the CLI knobs (docs/serving_reliability.md
    has the full table). The tail-latency monitor is always armed
    (journal-only); profiler captures engage with --profile-on-tail."""
    monitor = None
    if args.tail_sigma > 0:
        monitor = TailLatencyMonitor(
            sigma=args.tail_sigma,
            profiler=(_serving_profiler() if args.profile_on_tail
                      else None),
            capture_seconds=args.tail_capture_seconds,
            cooldown_s=args.tail_cooldown)
    return ReliabilityPlane(
        max_queue_depth=args.max_queue_depth,
        shed_ttft_s=args.shed_ttft,
        deadline_default_s=args.deadline_default,
        deadline_max_s=args.deadline_max,
        slots=args.slots, monitor=monitor)


def build_service(args) -> BatcherService:
    if args.fake_backend:
        # Deterministic pure-Python token mill (serving_plane/testing.py)
        # — the reliability drills' and slo_soak's backend: boots in
        # import time, decode pace set by --fake-step-delay.
        from pytorch_distributed_train_tpu.serving_plane.testing import (
            FakeByteTok,
            FakeTokenBatcher,
        )

        batcher = FakeTokenBatcher(slots=args.slots,
                                   step_delay_s=args.fake_step_delay)
        return BatcherService(batcher, FakeByteTok(),
                              max_new_default=args.max_new_default,
                              plane=build_plane(args))
    import jax

    from pytorch_distributed_train_tpu.config import get_preset
    from pytorch_distributed_train_tpu.data.text import load_tokenizer
    from pytorch_distributed_train_tpu.serving import (
        ContinuousBatcher,
        PagedContinuousBatcher,
        Seq2SeqContinuousBatcher,
        load_params_for_serving,
    )

    cfg = get_preset(args.config)
    cfg.apply_overrides(args.set)
    tok = load_tokenizer(args.tokenizer)
    params = load_params_for_serving(cfg, args.safetensors, args.quantize)
    if cfg.model.name.startswith("t5"):
        cls, extra = Seq2SeqContinuousBatcher, {}
    else:
        extra = {"auto_prefix_min": args.auto_prefix_min,
                 "spec_k": args.spec_k,
                 "spec_ngram": args.spec_ngram}
        if args.page_size > 0:
            cls = PagedContinuousBatcher
            extra["page_size"] = args.page_size
            extra["page_blocks"] = args.page_blocks
        else:
            cls = ContinuousBatcher
    batcher = cls(cfg.model, cfg.precision, params, slots=args.slots,
                  top_k=args.top_k, top_p=args.top_p, min_p=args.min_p,
                  rng=jax.random.PRNGKey(args.seed), **extra)
    service = BatcherService(batcher, tok,
                             max_new_default=args.max_new_default,
                             plane=build_plane(args))
    service.weight_applier = _make_weight_applier(batcher)
    return service


def _make_weight_applier(batcher):
    """Weight-swap placement for a real model backend: published leaves
    (the trainer's ``{"params": ...}`` savable, global flatten order) →
    device arrays in THIS batcher's param shardings → a cheap apply fn
    the scheduler flips between quanta. None on any shape/dtype
    mismatch (e.g. a --quantize serving tree vs fp32 trainer params):
    the swap rejects instead of serving a half-cast model."""

    def prepare(leaves, header):
        from pytorch_distributed_train_tpu.online import (
            publisher as pub_lib,
        )

        placed = pub_lib.place_leaves({"params": batcher.params}, leaves)
        if placed is None:
            return None

        def apply():
            batcher.params = placed["params"]

        return apply

    return prepare


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default="llama2_7b")
    p.add_argument("--set", action="append", default=[], metavar="KEY=VALUE")
    p.add_argument("--safetensors", default="",
                   help="model weights (required unless --fake-backend)")
    p.add_argument("--tokenizer", default="",
                   help="local HF tokenizer dir; empty → byte tokenizer")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=0.0)
    p.add_argument("--min-p", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-new-default", type=int, default=64)
    p.add_argument("--auto-prefix-min", type=int, default=0,
                   help="auto-fork completions from any PRELOADED "
                        "template of >= N tokens that prefixes the "
                        "prompt (0 = off); explicit prefix=/session= "
                        "always win")
    p.add_argument("--spec-k", type=int, default=0,
                   help="prompt-lookup SPECULATIVE serving: verify K "
                        "n-gram proposals per row per step (0 = off; "
                        "composes with penalties/logit_bias — the "
                        "penalized accept kernel preserves the lockstep "
                        "law)")
    p.add_argument("--spec-ngram", type=int, default=3,
                   help="with --spec-k: n-gram length for the lookup")
    p.add_argument("--page-size", type=int, default=0,
                   help="PAGED KV cache: tokens per block (0 = dense "
                        "per-slot reservation). Resident KV then scales "
                        "with actual lengths; forks share prefix blocks "
                        "copy-on-write (llama family)")
    p.add_argument("--page-blocks", type=int, default=0,
                   help="with --page-size: pool size in blocks (0 = "
                        "dense-equivalent slots*ceil(max_seq_len/"
                        "page_size))")
    p.add_argument("--quantize", default="", choices=["", "int8", "int4"])
    p.add_argument("--drain-grace", type=float, default=30.0,
                   help="seconds SIGTERM waits for in-flight requests "
                        "before shutting down (graceful drain; size "
                        "below the scheduler's kill grace)")
    # ---- serving reliability plane (docs/serving_reliability.md) ----
    p.add_argument("--max-queue-depth", type=int, default=0,
                   help="admission control: shed (429 + Retry-After) "
                        "once this many requests wait for a slot "
                        "(0 = unbounded)")
    p.add_argument("--shed-ttft", type=float, default=0.0,
                   help="admission control: shed once the estimated "
                        "TTFT for a new request exceeds this many "
                        "seconds (0 = off)")
    p.add_argument("--deadline-default", type=float, default=0.0,
                   help="default per-request wall-clock budget in "
                        "seconds; expiry cancels the request in the "
                        "batcher and answers 504 (0 = no default; "
                        "requests may still send deadline_s)")
    p.add_argument("--deadline-max", type=float, default=0.0,
                   help="cap on any client-requested deadline_s "
                        "(0 = uncapped)")
    p.add_argument("--tail-sigma", type=float, default=6.0,
                   help="tail-latency anomaly detector: median+MAD "
                        "sigma on TTFT / inter-token series "
                        "(0 = detector off)")
    p.add_argument("--tail-cooldown", type=float, default=60.0,
                   help="seconds between anomaly-triggered profiler "
                        "captures")
    p.add_argument("--tail-capture-seconds", type=float, default=2.0,
                   help="length of an anomaly-triggered capture")
    p.add_argument("--profile-on-tail", action="store_true",
                   help="fire the managed profiler on tail-latency "
                        "anomalies (anomalies journal regardless)")
    # ---- distributed request tracing (obs/tracing.py) ----
    p.add_argument("--trace-dir", default="",
                   help="retained-trace JSONL directory (default "
                        "$PDTT_TRACE_DIR, else a traces/ sibling of "
                        "the event journal; empty + no env = traces "
                        "counted but not spilled)")
    p.add_argument("--trace-sample-pct", type=float, default=None,
                   help="random baseline %% of traces retained "
                        "(default $PDTT_TRACE_SAMPLE_PCT or 0)")
    p.add_argument("--trace-keep-slow-ms", type=float, default=None,
                   help="retain any request trace slower than this "
                        "(tail-based sampling; default "
                        "$PDTT_TRACE_KEEP_SLOW_MS or 250)")
    p.add_argument("--weight-version", default="",
                   help="correlation tag stamped on every span/trace "
                        "(default: safetensors basename, or 'fake') — "
                        "an online weight swap updates it, so ROADMAP-4 "
                        "is traceable day one")
    p.add_argument("--advertise", action="store_true",
                   help="register host:port with the elastic launcher "
                        "store so tools/serve_router.py discovers this "
                        "replica (needs TPUSTORE_ADDR)")
    p.add_argument("--fake-backend", action="store_true",
                   help="serve a deterministic fake token batcher "
                        "(tests, slo_soak, router drills — no model)")
    p.add_argument("--fake-step-delay", type=float, default=0.0,
                   help="with --fake-backend: seconds per decode step")
    args = p.parse_args(argv)
    if not args.safetensors and not args.fake_backend:
        p.error("--safetensors is required (or pass --fake-backend)")

    tracing.configure(args.trace_dir or tracing.default_dir(),
                      sample_pct=args.trace_sample_pct,
                      keep_slow_ms=args.trace_keep_slow_ms)
    boot_version = args.weight_version or (
        os.path.basename(args.safetensors) if args.safetensors
        else "fake")
    spans_lib.set_correlation_tags(
        weight_version=boot_version,
        gen=os.environ.get("RESTART_GENERATION", "0"))
    try:
        service = build_service(args)
    except (KeyError, ValueError, FileNotFoundError, OSError) as e:
        print(f"serve_http: error: {e.args[0] if e.args else e}",
              file=sys.stderr)
        return 2
    # --weight-version only SEEDS the mutable weight state: a live swap
    # (/admin/weights) advances it, and /healthz + span tags follow
    service.weights = WeightState(version=boot_version)
    server = ThreadingHTTPServer((args.host, args.port), None)
    drain = GracefulDrain(server, service, grace_s=args.drain_grace)
    server.RequestHandlerClass = make_handler(service, drain)
    drain.install()
    adv_store, adv_idx = None, -1
    if args.advertise:
        from pytorch_distributed_train_tpu import store_plane
        from pytorch_distributed_train_tpu.elastic import (
            publish_obs_endpoint,
            publish_replica,
            routable_host,
        )

        # resilient wrapper (store_plane): the publish and the exit
        # tombstone get bounded timeouts + retries instead of wedging
        # startup/shutdown behind a slow launcher store
        store = store_plane.resilient_worker_store(name="serve-advertise")
        if store is None:
            print("serve_http: --advertise ignored (no TPUSTORE_ADDR)",
                  flush=True)
        else:
            # a wildcard bind is unconnectable from peers: advertise a
            # routable address instead
            addr = (f"{routable_host(args.host)}:"
                    f"{server.server_address[1]}")
            idx = publish_replica(store, addr)
            adv_store, adv_idx = store, idx
            # ... and the same address into the obs-endpoint registry,
            # so the fleet collector scrapes this replica's /metrics +
            # /healthz without static config (docs/observability.md
            # "Fleet health plane").
            publish_obs_endpoint(store, "serving", addr)
            print(f"serve_http: advertised as replica {idx} ({addr})",
                  flush=True)
    print(f"serving on http://{args.host}:{server.server_address[1]} "
          f"(slots={args.slots})", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        service.shutdown()  # idempotent: the drain path already did this
        if adv_store is not None:
            # clean exit (drain completed or ^C): tombstone the registry
            # slot so discovery stops returning this address forever — a
            # crash skips this, and the prober handles that stale entry
            from pytorch_distributed_train_tpu.elastic import (
                tombstone_replica,
            )

            tombstone_replica(adv_store, adv_idx)
    return 0


if __name__ == "__main__":
    sys.exit(main())
