#!/usr/bin/env python
"""HTTP serving endpoint over the continuous batcher — the end-user
service surface (torch-ecosystem analogue: TGI / vLLM's OpenAI-style
server, scoped to stdlib http.server: zero extra dependencies).

    python tools/serve_http.py --config llama2_7b \
        --safetensors model.st --tokenizer /models/llama2-tok \
        --port 8000 --slots 8 [--quantize int8]

    curl -s localhost:8000/v1/completions -d '{
        "prompt": "The capital of France is",
        "max_tokens": 32, "temperature": 0.7}'

API (JSON over POST, one object per request):
- ``POST /v1/completions``: {prompt, max_tokens?, temperature?} →
  {text, finish_reason, usage:{prompt_tokens, completion_tokens}}.
  ``top_k``/``top_p`` are SERVER-wide flags (static jit args — per-request
  values would recompile; temperature is the per-request knob).
- ``GET /healthz``: {status, stats} — liveness + batcher counters.

Threading model: request handler threads (ThreadingHTTPServer) enqueue
into the batcher under a lock and wait on a per-request event; ONE
scheduler thread drives ``batcher.step()`` — all device work stays on a
single thread, handlers only block on Python events. Requests admit into
free slots mid-stream, so concurrent callers batch together on the chip
without knowing about each other.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class BatcherService:
    """Thread-safe facade over a (seq2seq-aware) continuous batcher: a
    single scheduler thread steps the device; callers submit and wait."""

    def __init__(self, batcher, tokenizer, *, idle_sleep_s: float = 0.005,
                 max_new_default: int = 64):
        self.batcher = batcher
        self.tok = tokenizer
        self.max_new_default = max_new_default
        self._lock = threading.Lock()
        self._done: dict[int, object] = {}
        self._events: dict[int, threading.Event] = {}
        self._abandoned: set[int] = set()  # timed-out uids: discard results
        self.error: str | None = None  # scheduler-death reason (terminal)
        self._idle_sleep_s = idle_sleep_s
        self._stop = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop:
            try:
                with self._lock:
                    busy = bool(self.batcher.queue
                                or self.batcher.active_slots)
                    finished = self.batcher.step() if busy else []
                    for c in finished:
                        if c.uid in self._abandoned:
                            self._abandoned.discard(c.uid)
                            continue  # waiter gave up; drop, don't leak
                        self._done[c.uid] = c
                        ev = self._events.pop(c.uid, None)
                        if ev is not None:
                            ev.set()
            except Exception as e:  # noqa: BLE001 — must not die silently
                # Device/compile errors are terminal for the only decode
                # thread: record the reason (healthz flips to error), fail
                # every waiter immediately instead of letting them time out.
                self.error = f"{type(e).__name__}: {e}"
                with self._lock:
                    for ev in self._events.values():
                        ev.set()
                    self._events.clear()
                return
            if not busy:
                time.sleep(self._idle_sleep_s)

    def healthy(self) -> bool:
        return self.error is None and self._thread.is_alive()

    def complete(self, prompt: str, max_tokens: int, temperature: float,
                 timeout_s: float = 600.0) -> dict:
        ids = self.tok.encode(prompt)
        if not ids:
            raise ValueError("empty prompt after tokenization")
        ev = threading.Event()
        with self._lock:
            # Checked UNDER the lock: the scheduler's death path clears
            # _events under this lock, so registering after a pre-lock
            # check could enqueue an event nothing will ever set.
            if self.error is not None:
                raise RuntimeError(f"scheduler dead: {self.error}")
            uid = self.batcher.submit(ids, max_tokens,
                                      temperature=temperature,
                                      eos_id=self.tok.eos_id)
            self._events[uid] = ev
        timed_out = not ev.wait(timeout_s)
        with self._lock:
            # The completion may have landed in the wait→lock window even
            # on the timeout path — prefer returning it over abandoning
            # (which would leak the stored result forever: uids never
            # repeat, so nothing else would pop it).
            c = self._done.pop(uid, None)
            if timed_out and c is None:
                self._events.pop(uid, None)
                self._abandoned.add(uid)
        if c is None:
            if timed_out:
                raise TimeoutError(
                    f"request {uid} timed out after {timeout_s}s")
            raise RuntimeError(f"scheduler dead: {self.error}")
        new = c.tokens
        if self.tok.eos_id in new:
            new = new[: new.index(self.tok.eos_id)]
        return {
            "text": self.tok.decode(new),
            "finish_reason": c.finish_reason,
            "usage": {"prompt_tokens": len(ids),
                      "completion_tokens": len(c.tokens)},
        }

    def stats(self) -> dict:
        # Snapshot WITHOUT the step lock: the counters are plain ints
        # mutated only by the scheduler thread, and a liveness probe must
        # not block behind a minutes-long first-compile step quantum.
        return dict(self.batcher.stats)

    def shutdown(self):
        self._stop = True
        self._thread.join(timeout=5)


def make_handler(service: BatcherService):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet by default
            pass

        def _send(self, code: int, obj: dict):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                if service.healthy():
                    self._send(200, {"status": "ok",
                                     "stats": service.stats()})
                else:
                    self._send(503, {"status": "error",
                                     "error": service.error,
                                     "stats": service.stats()})
            else:
                self._send(404, {"error": "unknown path"})

        def do_POST(self):
            if self.path != "/v1/completions":
                self._send(404, {"error": "unknown path"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                out = service.complete(
                    str(req["prompt"]),
                    int(req.get("max_tokens", service.max_new_default)),
                    float(req.get("temperature", 0.0)),
                )
                self._send(200, out)
            except (KeyError, ValueError, TypeError) as e:
                self._send(400, {"error": f"{e.args[0] if e.args else e}"})
            except (TimeoutError, RuntimeError) as e:
                self._send(503, {"error": str(e)})

    return Handler


def build_service(args) -> BatcherService:
    import jax

    from pytorch_distributed_train_tpu.config import get_preset
    from pytorch_distributed_train_tpu.data.text import load_tokenizer
    from pytorch_distributed_train_tpu.serving import (
        ContinuousBatcher,
        Seq2SeqContinuousBatcher,
        load_params_for_serving,
    )

    cfg = get_preset(args.config)
    cfg.apply_overrides(args.set)
    tok = load_tokenizer(args.tokenizer)
    params = load_params_for_serving(cfg, args.safetensors, args.quantize)
    cls = (Seq2SeqContinuousBatcher if cfg.model.name.startswith("t5")
           else ContinuousBatcher)
    batcher = cls(cfg.model, cfg.precision, params, slots=args.slots,
                  top_k=args.top_k, top_p=args.top_p,
                  rng=jax.random.PRNGKey(args.seed))
    return BatcherService(batcher, tok,
                          max_new_default=args.max_new_default)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", default="llama2_7b")
    p.add_argument("--set", action="append", default=[], metavar="KEY=VALUE")
    p.add_argument("--safetensors", required=True)
    p.add_argument("--tokenizer", default="",
                   help="local HF tokenizer dir; empty → byte tokenizer")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-new-default", type=int, default=64)
    p.add_argument("--quantize", default="", choices=["", "int8"])
    args = p.parse_args(argv)

    try:
        service = build_service(args)
    except (KeyError, ValueError, FileNotFoundError, OSError) as e:
        print(f"serve_http: error: {e.args[0] if e.args else e}",
              file=sys.stderr)
        return 2
    server = ThreadingHTTPServer((args.host, args.port),
                                 make_handler(service))
    print(f"serving on http://{args.host}:{server.server_address[1]} "
          f"(slots={args.slots})", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        service.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
