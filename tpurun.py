#!/usr/bin/env python
"""tpurun — launch a gang of training workers with restart supervision.

Usage:
    python tpurun.py --nprocs 4 -- train.py --config llama2_7b ...

The torchrun analogue (SURVEY C10): native rendezvous store + whole-gang
restart from the latest checkpoint. See pytorch_distributed_train_tpu/elastic.py.
"""

import sys

from pytorch_distributed_train_tpu.elastic import main

if __name__ == "__main__":
    sys.exit(main())
