#!/usr/bin/env python
"""bench.py — training-throughput benchmarks on the local TPU chip(s).

Default (the north-star, BASELINE.json:2): ResNet-50 ImageNet-shape
training, images/sec/chip. Prints ONE JSON line:
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": R}

vs_baseline compares against the first measured value recorded in
BENCH_BASELINE.json (the reference publishes no numbers — BASELINE.md
policy: first instrumented run IS the baseline, ratio 1.0 that round).
Only the default configuration seeds/reads the baseline ratio; other
models/shapes report vs_baseline against their own recorded key when
present, else 1.0.

Secondary modes: ``--model llama`` / ``--model bert_base`` measure
tokens/sec/chip on a ~1B-param Llama (or BERT-base MLM) with the same
machinery.

Methodology: synthetic data (isolates device throughput from disk),
bf16 compute policy, full train step (fwd+bwd+optimizer) on all local
devices. Timing enqueues `--steps` steps back-to-back and then fetches the
final step's loss VALUE: the loss depends on the (donated) state chain, so
the fetch forces every enqueued step to have executed. This measures
pipelined steady-state throughput the way a real training loop runs, and —
unlike `block_until_ready` — cannot return early under remote/tunnelled
PJRT backends (observed: block_until_ready on this sandbox's axon tunnel
reports readiness ~40x before execution finishes).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import threading
import time
import traceback

VISION = ("resnet18", "resnet50", "vit_b16")

# Substrings identifying a device-backend bring-up failure (vs a bench bug).
# Matching errors raised BEFORE bring-up completed (see _bringup_done) yield
# ONE parseable JSON line + exit 3, so a wedged/absent TPU lease produces a
# structured record instead of a raw traceback (observed:
# jax.device_count() raising "Unable to initialize backend 'axon':
# UNAVAILABLE: TPU backend setup/compile error"). Errors after bring-up are
# real bench/framework bugs and propagate as normal tracebacks.
_BACKEND_ERR_MARKERS = (
    "Unable to initialize backend",
    "backend setup/compile error",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "No visible TPU",
)


_LKG_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_LKG.json")


def _load_lkg() -> dict:
    try:
        with open(_LKG_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def _update_lkg(record: dict) -> None:
    """Record a successful measurement as the metric's last-known-good
    row. The LKG store exists so a later wedged-lease round still emits
    numbers with provenance instead of a bare null (VERDICT r3 #1)."""
    if not record.get("metric"):
        return
    lkg = _load_lkg()
    rows = lkg.setdefault("rows", {})
    rows[record["metric"]] = {
        **{k: v for k, v in record.items() if k != "metric"},
        "measured": time.strftime("%Y-%m-%d"),
        "argv": " ".join(sys.argv[1:]),
    }
    try:
        # Atomic replace: the bench runs under a kill-on-stall watchdog,
        # and a truncate-then-die would destroy the whole LKG history
        # this feature exists to preserve.
        tmp = _LKG_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(lkg, f, indent=1, sort_keys=True)
        os.replace(tmp, _LKG_PATH)
    except OSError:
        pass  # read-only checkout: the printed record still stands


def _ledger_append(record: dict) -> None:
    """Mirror a measured record into the perf ledger (obs/perf.py;
    docs/performance.md) — the append-only trajectory the regression
    gate (tools/perf_ledger --check) compares across rounds. Best-effort
    by contract: a read-only checkout still prints the record."""
    try:
        from pytorch_distributed_train_tpu.obs.perf import (
            PerfLedger,
            default_ledger_path,
        )

        PerfLedger(default_ledger_path(os.path.dirname(
            os.path.abspath(__file__)))).append_record(record,
                                                       source="bench")
    except Exception as e:
        print(f"bench.py: perf-ledger append failed "
              f"({type(e).__name__}: {e})", file=sys.stderr, flush=True)


def _emit(record: dict, device_metric: bool = True) -> None:
    """Print the one-line JSON record and, when it is a real hardware
    measurement (TPU backend; host-pipeline benches pass False and are
    recorded unconditionally), persist it as last-known-good and append
    it to the perf ledger."""
    print(json.dumps(record), flush=True)
    if device_metric:
        try:
            import jax

            if jax.devices()[0].platform != "tpu":
                return  # CPU smoke numbers must never pose as LKG
        except Exception:
            return
    _update_lkg(record)
    _ledger_append(record)


def _emit_backend_unavailable(detail: str) -> None:
    """Structured no-hardware record. Never a bare null when measured
    numbers exist on disk: the last-known-good rows ride along, stamped
    stale so the reader can't mistake them for this round's capture."""
    out = {
        "error": "tpu_unavailable",
        "detail": detail[-1500:],
        "metric": None,
        "value": None,
    }
    lkg = _load_lkg()
    if lkg.get("rows"):
        try:
            mtime = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(os.path.getmtime(_LKG_PATH)))
        except OSError:
            mtime = None
        out["stale"] = True
        out["last_known_good"] = {
            "note": "prior successful measurements (NOT this run's): "
                    "see per-row 'measured' dates",
            "file_mtime": mtime,
            "rows": lkg["rows"],
        }
    print(json.dumps(out), flush=True)


def probe_once(timeout_s: float = 90.0) -> tuple[bool, str]:
    """ONE subprocess backend-health probe (the canonical definition —
    tools/tpu_probe.sh calls this so the manual and automated gates can
    never drift). Fetches a computed VALUE, not block_until_ready (which
    this tunnel reports early), so success proves the chip executes."""
    import subprocess

    probe = ("import jax, jax.numpy as jnp; "
             "print('n=', jax.device_count(), "
             "'v=', float(jnp.ones((8, 8)).sum()))")
    try:
        r = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True,
            text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"probe hung >{timeout_s:.0f}s (lease wedged)"
    if r.returncode == 0:
        return True, r.stdout.strip()
    tail = (r.stderr or r.stdout).strip().splitlines()
    return False, (tail[-1][-200:] if tail else f"rc={r.returncode}")


def _wait_for_backend() -> None:
    """Bounded retry/backoff for the device-backend bring-up.

    A transient lease wedge on the tunnelled backend used to cost an entire
    round's perf evidence: jax caches a failed backend init for the process
    lifetime, and a wedged ``jax.devices()`` can block forever. So the
    health probe runs in a SUBPROCESS with a per-attempt timeout — the
    probe fetches a computed VALUE (not block_until_ready, which this
    tunnel reports early) so success proves the chip executes, not merely
    that the client initialized. Retries back off exponentially until
    BENCH_BRINGUP_RETRY_S (default 600 s) elapses, then the structured
    ``tpu_unavailable`` record is emitted with the attempt history.
    Respects JAX_PLATFORMS=cpu (tests): returns immediately.
    """
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return
    import subprocess

    deadline_s = float(os.environ.get("BENCH_BRINGUP_RETRY_S", "600"))
    probe_timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "90"))
    t0 = time.monotonic()
    attempts = []
    backoff = 5.0
    while True:
        # The deadline bounds TOTAL wall-clock, probe time included: a
        # probe launched near the deadline gets only the remaining
        # budget (floor 10 s — below that a tunnel probe can't prove
        # anything), so the loop can no longer overshoot its stated
        # budget by a full probe_timeout (BENCH_r04 ran 676 s against
        # a 600 s budget).
        elapsed = time.monotonic() - t0
        remaining = deadline_s - elapsed
        if remaining <= 0:
            _emit_backend_unavailable(
                f"backend unhealthy after {len(attempts)} probes over "
                f"{elapsed:.0f}s (retry budget {deadline_s:.0f}s); last: "
                f"{attempts[-1] if attempts else 'none'}")
            os._exit(3)
        ok, detail = probe_once(min(probe_timeout_s, max(10.0, remaining)))
        if ok:
            if attempts:
                print(f"bench.py: backend healthy after "
                      f"{len(attempts)} failed probe(s), "
                      f"{time.monotonic() - t0:.0f}s",
                      file=sys.stderr, flush=True)
            return
        attempts.append(detail)
        elapsed = time.monotonic() - t0
        print(f"bench.py: backend probe {len(attempts)} failed "
              f"({attempts[-1]}); {elapsed:.0f}/{deadline_s:.0f}s elapsed",
              file=sys.stderr, flush=True)
        _touch()  # deliberate retry, not a hang: hold off the watchdog
        time.sleep(min(backoff, max(0.1, deadline_s - elapsed)))
        backoff = min(backoff * 2, 60.0)


_progress_ts = [time.monotonic()]
_watchdog_armed = [False]
_bringup_done = [False]
# Process-start anchor for the bench goodput_pct denominator (module
# import ≈ process start; monotonic so NTP can't skew the split).
_T_MAIN0 = [time.monotonic()]


def _touch() -> None:
    """Mark bench progress (resets the watchdog deadline)."""
    _progress_ts[0] = time.monotonic()


def _disarm_watchdog() -> None:
    """Called once warmup has EXECUTED on the device: the backend is proven
    healthy, and the timed region may legitimately block longer than any
    fixed idle budget (one un-touchable value fetch spans all timed steps),
    so the bring-up watchdog stands down."""
    _watchdog_armed[0] = False
    _bringup_done[0] = True


def _arm_watchdog(seconds: float) -> None:
    """Hard-exit if bench BRING-UP makes no progress for ``seconds``.

    Covers backend import → state init → warmup execution: a wedged device
    lease (observed on the axon tunnel after an orphaned Mosaic remote
    compile) blocks the first jnp call forever, and a CI driver should get
    a loud nonzero exit instead of an eternal hang. Progress points
    (_touch) reset the deadline; after warmup the watchdog disarms (see
    _disarm_watchdog). Override with BENCH_TIMEOUT_S; 0 disables."""
    _watchdog_armed[0] = True

    def watch():
        while _watchdog_armed[0]:
            idle = time.monotonic() - _progress_ts[0]
            if idle > seconds:
                print(
                    f"bench.py watchdog: no progress for "
                    f"{idle:.0f}s — aborting", file=sys.stderr, flush=True)
                if _bringup_done[0]:
                    # Post-bring-up stall (host pipeline loop): NOT a lease
                    # problem — don't let the record blame the TPU.
                    print(json.dumps({
                        "error": "bench_stalled",
                        "detail": f"no progress for {idle:.0f}s after "
                                  "bring-up (host-side stall)",
                        "metric": None,
                        "value": None,
                    }), flush=True)
                else:
                    _emit_backend_unavailable(
                        f"no bring-up progress for {idle:.0f}s (device "
                        "lease wedged — first device op never returned)")
                os._exit(3)
            time.sleep(min(60.0, seconds / 4))

    threading.Thread(target=watch, daemon=True).start()


def pipeline_bench(args) -> None:
    """Host input-pipeline throughput (SURVEY hard part #1): sampler →
    batch augment/normalize → numpy batches, NO device involved. The
    augment is the fused C++ pass (native/imgops, internally multithreaded)
    on u8 storage; with the native build absent it falls back to the
    single-threaded numpy path — the metric name records which one ran so
    the numbers aren't conflated. (The per-item thread pool and the
    producer/prefetch stages don't apply to array-style datasets; what's
    measured here is the per-batch collate cost the train loop overlaps
    with device steps.) Deliberately does NOT seed/read BENCH_BASELINE.json:
    host throughput scales with whatever else shares the host cores, so a
    cross-run ratio would gate CI on machine load, not on code.

    ISSUE 12 arms (each its own metric name → fresh ledger trajectory):
    ``--packed-cache`` stores the dataset as packed shards and reads
    them through the mmap path (data/packed_cache.py);
    ``--device-augment`` ships raw u8 (host augment collapses to the
    read — the stall_split records the shift; the device-side cost is
    measured by the training benches, not here); ``--mp-workers N``
    collates in the shared-memory decode pool (data/workers.py)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # never touch the TPU here
    _bringup_done[0] = True  # host-only mode: no stall/error here is the TPU's
    import shutil
    import tempfile

    import numpy as np

    from pytorch_distributed_train_tpu.config import DataConfig
    from pytorch_distributed_train_tpu.data.datasets import U8ImageDataset
    from pytorch_distributed_train_tpu.data.pipeline import HostDataLoader
    from pytorch_distributed_train_tpu.native import imgops

    size = args.image_size
    n = 4096
    batch = args.batch_per_chip or 256
    if batch * 2 > n:
        raise SystemExit(
            f"--batch-per-chip {batch} too large for the {n}-sample "
            "synthetic dataset (need >= 2 batches: 1 warmup + 1 timed)")
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (n, size, size, 3), dtype=np.uint8)
    labels = rng.integers(0, 1000, n).astype(np.int32)
    mean = np.array([0.485, 0.456, 0.406], np.float32)
    std = np.array([0.229, 0.224, 0.225], np.float32)
    tmp = None
    try:
        if args.packed_cache:
            from tools.pack_dataset import pack_arrays

            from pytorch_distributed_train_tpu.data.packed_cache import (
                PackedImageDataset,
            )

            tmp = tempfile.mkdtemp(prefix="bench-packed-")
            pack_arrays(images, labels, tmp, split="train",
                        shard_records=max(batch, n // 4),
                        meta={"mean": mean.tolist(), "std": std.tolist(),
                              "pad": 4})
            del images  # the mmap is the storage under test, not RAM
            ds = PackedImageDataset(tmp, augment=True, split="train",
                                    raw_u8=args.device_augment)
        else:
            ds = U8ImageDataset(images, labels, mean=mean, std=std,
                                augment=True, raw_u8=args.device_augment)
        cfg = DataConfig(batch_size=batch, mp_workers=args.mp_workers)
        loader = HostDataLoader(ds, cfg, train=True, num_hosts=1, host_id=0)

        it = loader.epoch(0)
        next(it)  # warm caches (and fork+prime the worker pool)
        _touch()
        t0 = time.perf_counter()
        seen = 0
        for b in it:
            seen += len(b["label"])
            _touch()  # per-batch progress (host loop is touchable)
        wall = time.perf_counter() - t0
        loader.close()
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)
    native = "native" if imgops.available() else "numpy"
    parts = ["input_pipeline"]
    if args.packed_cache:
        parts.append("packed")
    parts.append("rawu8" if args.device_augment else native)
    if loader.mp_workers > 0:
        parts.append(f"mp{loader.mp_workers}")
    record = {
        "metric": "_".join(parts) + "_images_per_sec",
        "value": round(seen / wall, 2),
        "unit": "images/sec (host)",
        "vs_baseline": 1.0,
    }
    if loader.mp_workers > 0:
        record["mp_workers"] = loader.mp_workers
    from pytorch_distributed_train_tpu.obs import perf as perf_lib

    split = perf_lib.get_input_stats().split()
    if split:
        record["stall_split"] = split
    _emit(record, device_metric=False)


def pipeline_decode_bench(args) -> None:
    """JPEG-decode input pipeline throughput (SURVEY §7.4.1 — the part
    `--model pipeline` deliberately excludes): synthetic photo-like JPEGs
    in a WebDataset tar shard → TarShardImageDataset → the configured
    loader, full decode + RandomResizedCrop + flip + normalize per image.
    ``--decoder native`` routes through native/jpegdec.cpp (libjpeg batch
    decode in C++ threads); ``pil`` is the per-item PIL path. The metric
    name records decoder AND loader actually used. Never touches a device
    and never seeds a baseline key (host-load-dependent, like the collate
    bench)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # never touch the TPU here
    _bringup_done[0] = True  # host-only mode
    import shutil
    import tempfile

    import numpy as np

    from pytorch_distributed_train_tpu.config import DataConfig
    from pytorch_distributed_train_tpu.data.datasets import (
        TarShardImageDataset,
        write_jpeg_tar_shard,
    )

    n = 2048
    batch = args.batch_per_chip or 256
    if batch * 2 > n:
        raise SystemExit(
            f"--batch-per-chip {batch} too large for the {n}-sample "
            "synthetic shard (need >= 2 batches: 1 warmup + 1 timed)")
    tmp = tempfile.mkdtemp(prefix="bench-decode-")
    try:
        rng = np.random.default_rng(0)
        shard = os.path.join(tmp, "bench-000000.tar")
        write_jpeg_tar_shard(shard, n, rng, per_image=_touch)
        workers = args.workers or (os.cpu_count() or 1)
        ds = TarShardImageDataset(shard, args.image_size, train=True,
                                  native_decode=args.decoder == "native",
                                  decode_threads=workers)
        decoder = "native" if ds.native_decode else "pil"
        if args.decoder == "native" and decoder != "native":
            raise SystemExit("--decoder native requested but the jpegdec "
                             "library is unavailable")
        cfg = DataConfig(batch_size=batch, loader=args.loader,
                         num_workers=workers, mp_workers=args.mp_workers)
        if args.loader == "grain":
            from pytorch_distributed_train_tpu.data.grain_pipeline import (
                GrainHostDataLoader,
            )

            # num_hosts/host_id EXPLICIT: the defaults call
            # jax.process_count(), which initializes the device backend —
            # on this sandbox the axon hook then blocks forever when the
            # TPU lease is wedged. This (not host-core contention) was
            # round 2's grain-arm DNF: a host-only bench must never touch
            # the device. The threads arm below always passed them.
            loader = GrainHostDataLoader(ds, cfg, train=True,
                                         num_hosts=1, host_id=0)
        else:
            from pytorch_distributed_train_tpu.data.pipeline import (
                HostDataLoader,
            )

            loader = HostDataLoader(ds, cfg, train=True, num_hosts=1,
                                    host_id=0)
        it = loader.epoch(0)
        next(it)  # warm caches / spin up workers
        _touch()
        t0 = time.perf_counter()
        seen = 0
        for b in it:
            seen += len(b["label"])
            _touch()
        wall = time.perf_counter() - t0
        close = getattr(loader, "close", None)
        if close is not None:
            close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    if args.loader == "grain":
        # grain + pool: effective count is the pool-clamped num_workers
        mp_used = loader.num_workers if loader._pool_budget > 0 else 0
    else:
        mp_used = loader.mp_workers
    mp_sfx = f"_mp{mp_used}" if mp_used else ""
    record = {
        "metric": f"input_pipeline_decode_{decoder}_{args.loader}"
                  f"{mp_sfx}_images_per_sec",
        "value": round(seen / wall, 2),
        "unit": "images/sec (host)",
        "vs_baseline": 1.0,
    }
    if mp_used:
        record["mp_workers"] = mp_used
    per_worker = getattr(loader, "decode_threads_per_worker", 0)
    if per_worker:
        # Ledger note for the pil_grain_mp8 regression fix (ISSUE 14
        # satellite): the per-worker PIL decode-thread clamp is part of
        # this row's identity — rows before/after the clamp must be
        # tellable apart in the trajectory.
        record["decode_threads_per_worker"] = per_worker
        record["note"] = ("mp+grain item decode: per-worker PIL pool "
                          "clamped to the host core share "
                          "(workers.python_thread_budget)")
    # Staged attribution (obs/perf.py): which stage of the decode
    # pipeline the wall went to — the per-stage view of the host wall.
    from pytorch_distributed_train_tpu.obs import perf as perf_lib

    split = perf_lib.get_input_stats().split()
    if split:
        record["stall_split"] = split
    if args.loader == "grain":
        # The process-worker count actually used (host-core bounded —
        # grain_pipeline.bounded_workers): 0 = in-process mode on
        # core-starved hosts. Recorded so grain numbers from different
        # host shapes are never conflated.
        record["grain_workers"] = loader.num_workers
    _emit(record, device_metric=False)


def decode_bench(args) -> None:
    """KV-cache decode throughput (tokens/sec/chip) on the ~1B llama —
    the serving-side counterpart of the training bench. Prefills once
    (untimed), warms the single-token executable, then times N-1 pure
    decode steps driven directly. Never seeds a training baseline key."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_train_tpu import quant
    from pytorch_distributed_train_tpu.config import (
        ModelConfig,
        PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.generate import build_decode_model
    from pytorch_distributed_train_tpu.models.registry import build_model

    if args.model != "llama":
        raise SystemExit("--decode-tokens supports --model llama")
    if args.decode_tokens < 2:
        raise SystemExit("--decode-tokens must be >= 2 (timing needs at "
                         "least one pure decode step after the warmup one)")
    bpc = args.batch_per_chip or 8
    new_tokens = args.decode_tokens
    prompt_len = 16 if args.tiny else 128
    if prompt_len + new_tokens + 1 > args.seq_len:
        # generate()'s length guard doesn't run on this direct-step path;
        # overflowing the cache would silently clamp writes into the last
        # slot and time a semantically broken decode.
        raise SystemExit(
            f"prompt ({prompt_len}) + decode tokens ({new_tokens}) + 1 "
            f"exceeds --seq-len {args.seq_len}; raise --seq-len")
    dims = _llama_dims(args.tiny)
    model_cfg = ModelConfig(
        name="llama", **dims,
        max_seq_len=min(args.seq_len, prompt_len + new_tokens + 1),
        attention_impl="xla",  # decode steps are single-token; dense is right
        kv_cache_dtype=args.kv_cache_dtype,
    )
    precision = PrecisionConfig(compute_dtype="bfloat16")
    _touch()
    train_model = build_model(model_cfg, precision)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, dims["vocab_size"],
                                          (bpc, prompt_len)), jnp.int32)
    params = jax.jit(
        lambda r: train_model.init({"params": r}, ids[:1, :8],
                                   train=False)["params"]
    )(jax.random.PRNGKey(0))
    if args.quantize:
        params = jax.jit(lambda p: quant.quantize_tree_named(
            p, args.quantize))(params)
    model = build_decode_model(model_cfg, precision)
    _touch()

    # Drive the single-token step loop directly: prefill once (untimed),
    # warm the decode executable, then time N pure decode steps — no
    # noisy two-run subtraction.
    from pytorch_distributed_train_tpu.generate import (
        _decode_step,
        init_cache,
    )

    cache = init_cache(model, bpc)
    logits, cache = _decode_step(model, params, cache, ids)  # prefill
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
    logits, cache = _decode_step(model, params, cache, nxt)  # compile step
    float(logits[0, 0])
    _disarm_watchdog()
    t0 = time.perf_counter()
    for _ in range(new_tokens - 1):
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)[:, None]
        logits, cache = _decode_step(model, params, cache, nxt)
    float(logits[0, 0])  # forces the chain (donated-cache dependency)
    wall = time.perf_counter() - t0
    # Single-device generation (no mesh) — per-chip IS the run's rate.
    per_chip = bpc * (new_tokens - 1) / wall
    suffix = (f"_{args.quantize}" if args.quantize else "") + (
        "_tiny" if args.tiny else "")
    record = {
        "metric": f"llama_decode{suffix}_tokens_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": 1.0,
    }
    # MBU — decode's utilization measure (bandwidth-bound, so MFU would
    # mislead): bytes moved per token (weights/B + KV read at the run's
    # average fill) over the chip's HBM peak. The quantization levers
    # change the numerator exactly as documented (utils/flops.py).
    from pytorch_distributed_train_tpu.utils import flops as flops_lib

    wbytes = {"int8": 1.0, "int4": 0.5}.get(args.quantize, 2.0)
    kvbytes = 1.0 if args.kv_cache_dtype.startswith("float8") else 2.0
    bpt = flops_lib.decode_bytes_per_token(
        model_cfg, batch=bpc, avg_position=prompt_len + new_tokens / 2,
        weight_bytes_per_param=wbytes, kv_bytes_per_elt=kvbytes)
    mbu = flops_lib.mbu_pct(per_chip, bpt,
                            flops_lib.device_hbm_bandwidth())
    record["model_mb_per_token"] = round(bpt / 1e6, 3)
    if mbu is not None:
        record["mbu_pct"] = round(mbu, 2)
    _emit(record)


def _llama_dims(tiny: bool) -> dict:
    """The ~1.1B llama shape the decode/spec/serve benches share (tiny:
    CI-smoke sizes — never comparable to real numbers)."""
    return (dict(vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
                 num_kv_heads=4, mlp_dim=128) if tiny else
            dict(vocab_size=32000, hidden_size=2048, num_layers=16,
                 num_heads=16, num_kv_heads=16, mlp_dim=5504))


def serve_bench(args) -> None:
    """Continuous-batching serving throughput (serving.ContinuousBatcher):
    ``--serve N`` requests with MIXED prompt lengths and budgets drain
    through ``--batch-per-chip`` slots (default 8). The aggregate
    generated-tokens/sec is the serving rate a lockstep generate() cannot
    reach on this workload — lockstep pads every request to the longest
    prompt and keeps finished rows in the batch until the longest budget
    drains. ``occupancy`` (live-slot fraction per step) reports how full
    the batch stayed. Never seeds a training baseline key."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_train_tpu.config import (
        ModelConfig,
        PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.serving import ContinuousBatcher

    from pytorch_distributed_train_tpu import quant

    if args.model != "llama":
        raise SystemExit("--serve supports --model llama")
    n_req = args.serve
    slots = args.batch_per_chip or 8
    dims = _llama_dims(args.tiny)
    p_lo, p_hi = (4, 12) if args.tiny else (32, 256)
    b_lo, b_hi = (2, 6) if args.tiny else (16, 96)
    turns = max(args.serve_turns, 1)
    if turns > 1 and args.serve_prefix:
        raise SystemExit("--serve-turns and --serve-prefix are separate "
                         "workloads; pick one")
    # chat workload: later turns are shorter than openers
    t_lo, t_hi = (2, 6) if args.tiny else (16, 64)
    prefix_len = args.serve_prefix
    if prefix_len and slots < 2:
        raise SystemExit(
            "--serve-prefix needs --batch-per-chip >= 2: the template "
            "occupies one slot for the whole run")
    # headroom = the longest request the workload can draw (opener/user
    # turn + budget); the cap guards HBM, not correctness — refuse
    # prefixes that would eat the headroom rather than truncate silently
    max_len = (32 * turns + prefix_len if args.tiny
               else min(4096, 512 * turns) + prefix_len)
    if not args.tiny and max_len > 8192:
        raise SystemExit(
            f"--serve-prefix {prefix_len} pushes max_seq_len to "
            f"{max_len} (> 8192); lower the prefix length")
    model_cfg = ModelConfig(name="llama", **dims, max_seq_len=max_len,
                            attention_impl="xla",
                            kv_cache_dtype=args.kv_cache_dtype)
    precision = PrecisionConfig(compute_dtype="bfloat16")
    _touch()
    train_model = build_model(model_cfg, precision)
    params = jax.jit(
        lambda r: train_model.init({"params": r},
                                   jnp.zeros((1, 8), jnp.int32),
                                   train=False)["params"]
    )(jax.random.PRNGKey(0))
    if args.quantize:
        params = jax.jit(lambda p: quant.quantize_tree_named(
            p, args.quantize))(params)
    _touch()

    rng = np.random.default_rng(0)
    V = dims["vocab_size"]
    reqs = [(rng.integers(p_lo, p_hi + 1), rng.integers(b_lo, b_hi + 1))
            for _ in range(n_req)]
    extra_turns = [[(rng.integers(t_lo, t_hi + 1),
                     rng.integers(b_lo, b_hi + 1))
                    for _ in range(turns - 1)] for _ in range(n_req)]

    def make_batcher():
        if args.serve_paged:
            from pytorch_distributed_train_tpu.serving import (
                PagedContinuousBatcher,
            )

            return PagedContinuousBatcher(
                model_cfg, precision, params, slots=slots,
                page_size=args.serve_paged, spec_k=args.serve_spec)
        return ContinuousBatcher(model_cfg, precision, params, slots=slots,
                                 spec_k=args.serve_spec)

    def run_prefix_workload(b) -> int:
        """Shared-system-prompt workload: every request = prefix_len
        system tokens + its own user turn. Fork arm: ONE preload serves
        all requests; resend arm: each request re-prefills
        system+user."""
        system = list(rng.integers(0, V, prefix_len))
        sid = None if args.serve_resend else b.preload(system)
        for i in range(n_req):
            user = list(rng.integers(0, V, int(reqs[i][0])))
            if args.serve_resend:
                b.submit(system + user, int(reqs[i][1]))
            else:
                b.submit(user, int(reqs[i][1]), prefix=sid)
        n = 0
        for c in b.run():
            assert c.finish_reason == "length", c.finish_reason
            n += 1
        assert n == n_req
        return b.stats["generated_tokens"]

    def run_workload(b) -> int:
        """Drive the full (possibly multi-turn) workload; returns total
        generated tokens. Multi-turn: sessions resume by default; with
        --serve-resend each turn re-prefills the FULL history instead
        (the no-session baseline the session arm is measured against)."""
        conv_of_uid: dict[int, int] = {}
        turn_of_conv = [0] * n_req
        history = [list(rng.integers(0, V, int(reqs[i][0])))
                   for i in range(n_req)]
        for i in range(n_req):
            uid = b.submit(history[i], int(reqs[i][1]),
                           keep=turns > 1 and not args.serve_resend)
            conv_of_uid[uid] = i
        remaining = n_req * turns
        while remaining:
            for c in b.step():
                i = conv_of_uid.pop(c.uid)
                remaining -= 1
                t = turn_of_conv[i] = turn_of_conv[i] + 1
                if t >= turns:
                    continue
                n_turn, budget = extra_turns[i][t - 1]
                turn_toks = list(rng.integers(0, V, int(n_turn)))
                last = t >= turns - 1
                if args.serve_resend:
                    history[i] += c.tokens + turn_toks
                    uid = b.submit(history[i], int(budget))
                else:
                    uid = b.submit(turn_toks, int(budget),
                                   keep=not last, session=c.session)
                conv_of_uid[uid] = i
        return b.stats["generated_tokens"]

    # Warm EXACTLY the executables the timed run will hit. The workload's
    # submit lengths are deterministic a priori — every request
    # length-finishes (no eos), so turn t's history is opener +
    # sum(budgets + turn lengths so far) — which makes the prefill and
    # resume bucket sets computable before running anything. Executables
    # cache across batchers (structurally equal static module args), so
    # compiles land here, not inside the timed A/B (which would skew the
    # session-vs-resend comparison by unequal compile time).
    prefill_lens, resume_lens, fork_lens = set(), set(), set()
    if prefix_len:
        if args.serve_resend:
            prefill_lens = {prefix_len + int(n) for n, _ in reqs}
        else:
            prefill_lens = {prefix_len}
            fork_lens = {int(n) for n, _ in reqs}  # forked turn ingests
    else:
        for i in range(n_req):
            hist, budget = int(reqs[i][0]), int(reqs[i][1])
            prefill_lens.add(hist)
            for n_turn, next_budget in extra_turns[i]:
                if args.serve_resend:
                    hist += budget + int(n_turn)
                    prefill_lens.add(hist)
                    budget = int(next_budget)
                else:
                    resume_lens.add(1 + int(n_turn))
    warm = make_batcher()
    for bucket in sorted({warm._bucket(n) for n in prefill_lens}):
        warm.submit(rng.integers(0, V, bucket), 2)
    list(warm.run())
    if resume_lens:
        # chain resumes on one parked session, one per DISTINCT resume
        # bucket (turn length bucket-1 → ingest 1+len fills it exactly)
        uid = warm.submit(rng.integers(0, V, 4), 2, keep=True)
        for bucket in sorted({warm._bucket(n) for n in resume_lens}):
            done = {c.uid: c for c in warm.run()}
            uid = warm.submit(rng.integers(0, V, bucket - 1), 2,
                              keep=True, session=done[uid].session)
        list(warm.run())
    if fork_lens:
        # warm the fork-continuation buckets off one throwaway template
        # (fork ingest is the turn alone: templates carry no unconsumed
        # token, so bucket(len) == the timed executable's shape)
        wsid = warm.preload(rng.integers(0, V, 4))
        for bucket in sorted({warm._bucket(n) for n in fork_lens}):
            warm.submit(rng.integers(0, V, bucket), 2, prefix=wsid)
        list(warm.run())
    _disarm_watchdog()

    b = make_batcher()
    t0 = time.perf_counter()
    total = run_prefix_workload(b) if prefix_len else run_workload(b)
    wall = time.perf_counter() - t0
    # admission tokens: every REQUEST prefill/resume/fork samples one
    # token outside a batched step; preloads prefill but admit nothing
    admissions = (b.stats["prefills"] - b.stats["preloads"]
                  + b.stats["resumes"] + b.stats["forks"])
    occupancy = (b.stats["generated_tokens"] - admissions
                 ) / max(b.stats["slot_token_slots"], 1)
    suffix = (f"_{args.quantize}" if args.quantize else "") + (
        "_tiny" if args.tiny else "")
    arm = ""
    if turns > 1:
        arm = "_chat_resend" if args.serve_resend else "_chat"
    elif prefix_len:
        arm = "_prefix_resend" if args.serve_resend else "_prefix"
    if args.serve_spec:
        arm += f"_spec{args.serve_spec}"
    if args.serve_paged:
        arm += f"_paged{args.serve_paged}"
    _emit({
        "metric": f"llama_serve{arm}{suffix}_tokens_per_sec_per_chip",
        "value": round(total / wall, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": 1.0,
        "requests": n_req,
        "turns": turns,
        "prefix_len": prefix_len,
        "slots": slots,
        "prefills": b.stats["prefills"],
        "resumes": b.stats["resumes"],
        "forks": b.stats["forks"],
        "occupancy": round(occupancy, 3),
    })


def spec_bench(args) -> None:
    """Speculative-decoding throughput (B=1, latency regime). Two arms:

    - default: a quarter-ish-size RANDOM draft — acceptance ~0, so this is
      the overhead FLOOR (worst case: all speculation wasted);
    - ``--spec-self``: draft == target — acceptance 1, the machinery
      CEILING (k+1 committed tokens per verify at full draft cost).

    A trained/distilled draft lands between the two; compare against the
    ``llama_decode`` metric (note that one is B=8). Never seeds a
    baseline key."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_train_tpu.config import (
        ModelConfig,
        PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.speculative import (
        speculative_generate,
    )

    if args.model != "llama":
        raise SystemExit("--speculative supports --model llama")
    if args.prompt_lookup and args.spec_self:
        raise SystemExit("--prompt-lookup has no draft model to self-pair")
    k = args.speculative
    new_tokens = args.decode_tokens or 64
    prompt_len = 16 if args.tiny else 128
    dims = _llama_dims(args.tiny)
    d_dims = (dict(vocab_size=512, hidden_size=32, num_layers=1,
                   num_heads=2, num_kv_heads=2, mlp_dim=64) if args.tiny
              else dict(vocab_size=32000, hidden_size=512, num_layers=4,
                        num_heads=8, num_kv_heads=8, mlp_dim=1376))
    max_len = prompt_len + new_tokens + k + 2
    cfg = ModelConfig(name="llama", **dims, max_seq_len=max_len,
                      kv_cache_dtype=args.kv_cache_dtype,
                      attention_impl="xla")
    precision = PrecisionConfig(compute_dtype="bfloat16")
    _touch()

    def init_params(c, seed):
        m = build_model(c, precision)
        return jax.jit(lambda r: m.init(
            {"params": r}, jnp.zeros((1, 8), jnp.int32),
            train=False)["params"])(jax.random.PRNGKey(seed))

    params = init_params(cfg, 0)
    if args.prompt_lookup:
        draft_cfg = draft_params = None
        arm = f"plookup_n{args.prompt_lookup}"
    elif args.spec_self:
        draft_cfg, draft_params, arm = cfg, params, "self"
    else:
        draft_cfg = ModelConfig(name="llama", **d_dims, max_seq_len=max_len,
                                kv_cache_dtype=args.kv_cache_dtype,
                                attention_impl="xla")
        draft_params, arm = init_params(draft_cfg, 1), "randdraft"
    _touch()
    rng0 = np.random.default_rng(0)
    if args.prompt_lookup and args.plookup_periodic:
        # repetition-heavy prompt: the regime prompt lookup exists for
        # (summarization/edit/RAG workloads echo their context) — a
        # periodic pattern gives matches every round; acceptance is then
        # up to the model
        pat = rng0.integers(0, dims["vocab_size"], 8)
        prompt = jnp.asarray(
            np.tile(pat, prompt_len // 8 + 1)[None, :prompt_len], jnp.int32)
        arm += "_periodic"
    else:
        prompt = jnp.asarray(
            rng0.integers(0, dims["vocab_size"], (1, prompt_len)),
            jnp.int32)
    # warm every executable (prefills, draft steps, verify, accept);
    # capped at new_tokens so the warmup horizon fits the cache the
    # timed run sized (max_len above)
    warm_tokens = min(max(2 * k, 4), new_tokens)

    def run(n_toks, with_stats=False):
        if args.prompt_lookup:
            from pytorch_distributed_train_tpu.speculative import (
                prompt_lookup_generate,
            )

            return prompt_lookup_generate(
                cfg, precision, params, prompt, n_toks, k=k,
                ngram=args.prompt_lookup, temperature=0.0,
                return_stats=with_stats)
        return speculative_generate(
            cfg, precision, params, draft_cfg, draft_params, prompt,
            n_toks, k=k, temperature=0.0, return_stats=with_stats)

    run(warm_tokens)
    _disarm_watchdog()
    t0 = time.perf_counter()
    out, stats = run(new_tokens, with_stats=True)
    wall = time.perf_counter() - t0
    suffix = "_tiny" if args.tiny else ""
    record = {
        "metric": f"llama_spec_{arm}_k{k}{suffix}_tokens_per_sec",
        "value": round((out.shape[1] - prompt_len) / wall, 2),
        "unit": "tokens/sec (B=1)",
        "vs_baseline": 1.0,
        "accept_rate": round(stats["accept_rate"], 4),
        "tokens_per_round": round(stats["tokens_per_round"], 3),
    }
    if "match_rate" in stats:
        record["match_rate"] = round(stats["match_rate"], 3)
    _emit(record)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   help="resnet18|resnet50|vit_b16|llama|bert_base|pipeline")
    p.add_argument("--batch-per-chip", type=int, default=0,
                   help="0 → model default (128 vision, 8 llama, 32 bert)")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--remat-policy", default="full",
                   choices=["full", "dots", "dots_no_batch"],
                   help="llama only: what block remat keeps resident "
                        "(models/remat.py)")
    p.add_argument("--fused-head", action="store_true",
                   help="llama only: fused chunked LM-head loss "
                        "(model.fused_lm_loss) — (B,S,V) logits never "
                        "materialize.")
    p.add_argument("--optimizer", default="",
                   help="override the model's default optimizer (llama: "
                        "adamw; bert: lamb; vision: momentum) — e.g. "
                        "adafactor to probe optimizer-state HBM headroom")
    p.add_argument("--moment-dtype", default="",
                   help="optimizer moment storage dtype ('' = fp32; "
                        "bfloat16 halves adam/adamw/lamb first-moment HBM)")
    p.add_argument("--decode-tokens", type=int, default=0,
                   help="llama only: measure KV-cache DECODE throughput "
                        "instead of training — generate this many tokens "
                        "per sequence (timed after a warmup generation)")
    p.add_argument("--speculative", type=int, default=0, metavar="K",
                   help="llama only: speculative-decoding bench with "
                        "speculation depth K (B=1; see spec_bench)")
    p.add_argument("--serve", type=int, default=0, metavar="N_REQUESTS",
                   help="llama only: continuous-batching serving bench — "
                        "drain N mixed-length requests through "
                        "--batch-per-chip slots (see serve_bench)")
    p.add_argument("--serve-turns", type=int, default=1, metavar="T",
                   help="with --serve: chat workload — each request is a "
                        "T-turn conversation resumed via KV sessions")
    p.add_argument("--serve-resend", action="store_true",
                   help="with --serve-turns/--serve-prefix: re-prefill "
                        "instead of resuming/forking (the no-cache "
                        "baseline the session/prefix arms beat)")
    p.add_argument("--serve-spec", type=int, default=0, metavar="K",
                   help="with --serve: prompt-lookup speculative serving "
                        "(K proposals per row per step; random-token "
                        "workloads measure the overhead floor — real "
                        "text with repetition measures the win)")
    p.add_argument("--serve-paged", type=int, default=0, metavar="PAGE",
                   help="with --serve: PAGED KV cache with PAGE-token "
                        "blocks (dense-equivalent pool; measures the "
                        "paging overhead/win vs the per-slot "
                        "reservation at identical workload)")
    p.add_argument("--serve-prefix", type=int, default=0, metavar="LEN",
                   help="with --serve: all requests share a LEN-token "
                        "system prompt, served via ONE preloaded "
                        "template forked per request (--serve-resend: "
                        "re-prefill system+user each time instead). The "
                        "template occupies one slot for the whole run — "
                        "the fork arm pays 1/slots occupancy to save "
                        "LEN-token prefills, so it wins when LEN is "
                        "large relative to user turns and slots")
    p.add_argument("--spec-self", action="store_true",
                   help="with --speculative: draft == target (acceptance-1 "
                        "machinery ceiling instead of the random-draft "
                        "floor)")
    p.add_argument("--prompt-lookup", type=int, default=0, metavar="NGRAM",
                   help="with --speculative K: draft-FREE n-gram prompt "
                        "lookup instead of a draft model "
                        "(speculative.prompt_lookup_generate)")
    p.add_argument("--plookup-periodic", action="store_true",
                   help="with --prompt-lookup: repetition-heavy prompt "
                        "(the workload regime the technique targets) "
                        "instead of the random floor")
    p.add_argument("--kv-cache-dtype", default="",
                   choices=["", "bfloat16", "float8_e4m3fn", "float8_e5m2"],
                   help="decode/serve benches: KV-cache STORAGE dtype "
                        "(fp8 halves the per-step cache read)")
    p.add_argument("--quantize", default="", choices=["", "int8", "int4"],
                   help="decode bench: weight-only int8 (per-channel) or "
                        "int4 (group-wise) params (quant.py)")
    p.add_argument("--quant-training", default="", choices=["", "int8"],
                   help="llama training bench: AQT-style int8 QAT matmuls "
                        "(quant.int8_dot_general — int8 MXU path)")
    p.add_argument("--tiny", action="store_true",
                   help="decode bench: toy model sizes for CI smoke on CPU "
                        "(never comparable to real numbers)")
    p.add_argument("--pipeline-decode", action="store_true",
                   help="with --model pipeline: measure the JPEG-DECODE "
                        "pipeline (synthetic tar shard) instead of the "
                        "pre-decoded collate path")
    p.add_argument("--decoder", default="pil", choices=["pil", "native"],
                   help="decode bench: per-item PIL vs native libjpeg "
                        "batch decode (native/jpegdec.cpp)")
    p.add_argument("--loader", default="threads", choices=["threads", "grain"],
                   help="decode bench: host loader backend (SURVEY C17)")
    p.add_argument("--workers", type=int, default=0,
                   help="decode bench: loader workers (0 → cpu count)")
    p.add_argument("--mp-workers", type=int, default=0,
                   help="pipeline benches: shared-memory decode worker "
                        "PROCESSES (data/workers.py; 0 = in-process). "
                        "Clamped to cpu_count-1; metric name records the "
                        "effective count")
    p.add_argument("--packed-cache", action="store_true",
                   help="with --model pipeline: store the synthetic "
                        "dataset as packed pre-decoded shards "
                        "(tools/pack_dataset.py format) and read through "
                        "the mmap path (data/packed_cache.py)")
    p.add_argument("--device-augment", action="store_true",
                   help="with --model pipeline: host ships raw uint8 "
                        "(data.device_augment mode) — measures the host "
                        "side with the augment share collapsed into "
                        "device compute")
    p.add_argument("--stem", default="conv", choices=["conv", "space_to_depth"],
                   help="resnet ImageNet stem: space_to_depth is the exact "
                        "MXU-friendly 4x4/s1 rewrite (models/resnet.py)")
    p.add_argument("--offload-opt", action="store_true",
                   help="keep optimizer state in pinned HOST memory between "
                        "steps (ZeRO-Offload analogue; TPU backends only)")
    p.add_argument("--attention-impl", default="auto",
                   choices=["auto", "xla", "pallas", "chunked"],
                   help="LM attention backend. 'auto' picks the Pallas flash "
                        "kernel on real TPU backends but falls back to XLA "
                        "under the axon tunnel, whose remote compile hangs "
                        "on Mosaic kernels (ops/attention.py _pallas_usable). "
                        "'chunked' is the pure-XLA flash-style path: O(S* "
                        "chunk) memory, compiles everywhere.")
    # ---- ISSUE 14 compute-graph arms (each encodes into the metric
    # name -> fresh ledger trajectory; never seeds a canonical baseline)
    p.add_argument("--grad-accum", type=int, default=0, metavar="N",
                   help="microbatched train step: lax.scan over N "
                        "microbatches with accumulated grads "
                        "(train.grad_accum_steps; metric gains _gaN)")
    p.add_argument("--overlap-collectives", action="store_true",
                   help="shard_map DP step with per-bucket grad pmeans "
                        "inside the accumulation scan + the latency-"
                        "hiding XLA flag preset (metric gains _overlap)")
    p.add_argument("--grad-bucket-mb", type=int, default=25,
                   help="bucket cap for --overlap-collectives (DDP "
                        "bucket_cap_mb analogue)")
    p.add_argument("--fused-epilogue", action="store_true",
                   help="one-pass fused clip+update+gate epilogue "
                        "(ops/fused_update.py; metric gains _fusedep). "
                        "Needs an adamw/adam/sgd/momentum optimizer — "
                        "combine with --optimizer for lamb/adafactor "
                        "presets")
    args = p.parse_args()

    if args.overlap_collectives:
        # Scheduler preset must be in XLA_FLAGS before the FIRST jax
        # import in this process (config.py is jax-free). TPU backends
        # only — XLA:CPU/GPU reject unknown --xla_tpu_* flags FATALLY —
        # so gate on the platform actually resolving to TPU: an
        # explicit JAX_PLATFORMS naming tpu, or no request at all on a
        # host with libtpu installed (jax's default pick). A CPU smoke
        # of this arm still runs; it measures collective PLACEMENT,
        # not overlap.
        import importlib.util

        plat = os.environ.get("JAX_PLATFORMS", "")
        tpu_backend = "tpu" in plat or (
            plat == "" and importlib.util.find_spec("libtpu") is not None)
        if tpu_backend:
            from pytorch_distributed_train_tpu.config import (
                ensure_latency_hiding_flags,
            )

            ensure_latency_hiding_flags()

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # The env var alone does not stick on hosts whose sitecustomize
        # force-registers a TPU plugin (this sandbox's axon hook): the
        # config update is what actually pins the backend, and a wedged
        # lease otherwise hangs a "CPU" smoke run forever.
        import jax

        jax.config.update("jax_platforms", "cpu")

    timeout_s = float(os.environ.get("BENCH_TIMEOUT_S", "1800"))
    if timeout_s > 0:
        _arm_watchdog(timeout_s)

    if args.quant_training and (args.model != "llama" or args.decode_tokens):
        # Same convention as the Trainer guard: a silently-ignored knob
        # records fp numbers as an int8 measurement.
        raise SystemExit("--quant-training supports llama TRAINING only "
                         "(decode-side int8 is --quantize)")
    if args.model == "pipeline":
        if args.pipeline_decode:
            return pipeline_decode_bench(args)
        return pipeline_bench(args)
    # Every remaining mode touches the device: wait out a transient lease
    # wedge (bounded) before the in-process backend init commits to it.
    _wait_for_backend()
    if args.serve:
        return serve_bench(args)
    if args.speculative:
        return spec_bench(args)
    if args.decode_tokens:
        return decode_bench(args)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.config import (
        MeshConfig,
        ModelConfig,
        OptimConfig,
        PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.losses import get_loss_fn
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.optim import make_optimizer
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh
    from pytorch_distributed_train_tpu.parallel.partition import rules_for_model
    from pytorch_distributed_train_tpu.train_state import TrainState

    n_chips = jax.device_count()
    mesh = build_mesh(MeshConfig(data=-1))
    vision = args.model in VISION

    if vision:
        model_cfg = ModelConfig(name=args.model, num_classes=1000,
                                image_size=args.image_size, stem=args.stem,
                                attention_impl=args.attention_impl)
        loss_name = "softmax_xent"
        opt = OptimConfig(name="momentum", learning_rate=0.1,
                          schedule="constant", warmup_steps=0)
        bpc = args.batch_per_chip or 128
    elif args.model == "llama":
        # ~1.1B params: the largest shape that trains comfortably on one
        # v5e chip's HBM with remat; scales out via mesh config in train.py.
        model_cfg = ModelConfig(
            name="llama", vocab_size=32000, hidden_size=2048, num_layers=16,
            num_heads=16, num_kv_heads=16, mlp_dim=5504,
            max_seq_len=args.seq_len, remat=True,
            remat_policy=args.remat_policy,
            attention_impl=args.attention_impl,
            fused_lm_loss=args.fused_head,
            quant_training=args.quant_training,
        )
        loss_name = "fused_causal_lm_xent" if args.fused_head else "causal_lm_xent"
        opt = OptimConfig(name="adamw", learning_rate=3e-4,
                          schedule="constant", warmup_steps=0)
        bpc = args.batch_per_chip or 8
    elif args.model == "t5":
        # t5-small shapes (the t5_small preset): seq2seq throughput —
        # tokens counted as encoder source + decoder target per example.
        model_cfg = ModelConfig(
            name="t5", vocab_size=32128, hidden_size=512, num_layers=6,
            decoder_layers=6, num_heads=8, mlp_dim=2048,
            max_seq_len=min(args.seq_len, 512),
        )
        loss_name = "seq2seq_xent"
        opt = OptimConfig(name="adafactor", learning_rate=1e-2,
                          schedule="constant", warmup_steps=0)
        bpc = args.batch_per_chip or 64
    elif args.model == "bert_base":
        model_cfg = ModelConfig(
            name="bert_base", vocab_size=30522, hidden_size=768,
            num_layers=12, num_heads=12, mlp_dim=3072,
            max_seq_len=min(args.seq_len, 512),
            attention_impl=args.attention_impl,
        )
        # True masked-LM objective (BASELINE.json:10): 15% dynamic masking
        # with the 80/10/10 recipe via data.datasets.synthetic_mlm — the
        # measured workload now matches the spec (round 1 trained plain
        # next-token xent here).
        loss_name = "mlm_xent"
        opt = OptimConfig(name="lamb", learning_rate=1e-3,
                          schedule="constant", warmup_steps=0)
        bpc = args.batch_per_chip or 32
    else:
        raise SystemExit(f"unknown bench model {args.model!r}")

    if args.optimizer:
        opt = OptimConfig(name=args.optimizer, learning_rate=opt.learning_rate,
                          schedule="constant", warmup_steps=0)
    if args.moment_dtype:
        opt = dataclasses.replace(opt, moment_dtype=args.moment_dtype)

    _touch()  # backend import + arg setup done
    model = build_model(model_cfg, PrecisionConfig(compute_dtype="bfloat16"))
    tx, lr_sched = make_optimizer(opt, total_steps=1000)
    rules = rules_for_model(args.model)
    seq = model_cfg.max_seq_len

    if args.overlap_collectives and args.offload_opt:
        # Same refusal as the trainer's: the shard_map step cannot
        # stage pinned-host opt state (an obscure sharding error — or a
        # meaningless measurement — otherwise).
        raise SystemExit("--overlap-collectives + --offload-opt is "
                         "unsupported (shard_map cannot stage host-"
                         "memory opt state)")

    fused_update = None
    if args.fused_epilogue:
        from pytorch_distributed_train_tpu.optim import make_fused_update

        # Raises with the reason for inexpressible optimizers (lamb/
        # adafactor presets) — same loud-knob convention as
        # --quant-training; pair with --optimizer to fuse those benches.
        fused_update = make_fused_update(opt, lr_sched)

    tgt_seq = seq // 4 if args.model == "t5" else 0  # t5_small's 512/128

    def init_state(rng):
        if vision:
            dummy = (jnp.zeros((2, args.image_size, args.image_size, 3)),)
        elif args.model == "t5":
            dummy = (jnp.zeros((2, seq), jnp.int32),
                     jnp.zeros((2, tgt_seq), jnp.int32))
        else:
            dummy = (jnp.zeros((2, seq), jnp.int32),)
        variables = model.init({"params": rng}, *dummy, train=False)
        return TrainState.create(params=variables["params"], tx=tx,
                                 batch_stats=variables.get("batch_stats", {}))

    rng = jax.random.PRNGKey(0)
    shape = jax.eval_shape(init_state, rng)
    sharding = steps_lib.state_shardings(mesh, rules, shape)
    opt_dev_sharding = sharding.opt_state
    if args.offload_opt:
        if jax.devices()[0].platform == "cpu":
            raise SystemExit(
                "--offload-opt needs a TPU backend — the CPU backend "
                "cannot execute host-memory placement "
                "(annotate_device_placement)")
        sharding = steps_lib.offload_state_shardings(sharding)
    state = jax.jit(init_state, out_shardings=sharding)(rng)
    _touch()  # state materialized on device
    accum = max(args.grad_accum, 1)
    reduce_grads = reduce_metrics = None
    n_buckets = 0
    if args.overlap_collectives:
        reduce_grads, buckets = steps_lib.overlap_grad_reducer(
            shape.params, max(args.grad_bucket_mb, 1), ("data", "fsdp"))
        reduce_metrics = steps_lib.metrics_reducer(("data", "fsdp"))
        n_buckets = len(buckets)
    train_step = steps_lib.make_train_step(
        model, get_loss_fn(loss_name), tx, grad_accum_steps=accum,
        fused_update=fused_update, reduce_grads=reduce_grads,
        reduce_metrics=reduce_metrics)
    if args.offload_opt:
        train_step = steps_lib.offload_opt_state(
            train_step, opt_dev_sharding, sharding.opt_state)
    if args.overlap_collectives:
        step = steps_lib.jit_overlap_train_step(train_step, mesh, sharding)
    else:
        step = steps_lib.jit_train_step(train_step, mesh, sharding)

    global_batch = bpc * n_chips
    # Under --overlap-collectives the scan splits each SHARD's batch
    # (batch axes data x fsdp = n_chips here), not the global one.
    accum_unit = bpc if args.overlap_collectives else global_batch
    if accum_unit % accum:
        raise SystemExit(
            f"--grad-accum {accum} does not divide the "
            f"{'per-shard' if args.overlap_collectives else 'global'} "
            f"batch {accum_unit}")
    rng_np = np.random.default_rng(0)
    if vision:
        batch = {
            "image": jnp.asarray(
                rng_np.standard_normal(
                    (global_batch, args.image_size, args.image_size, 3)
                ),
                jnp.float32,
            ),
            "label": jnp.asarray(rng_np.integers(0, 1000, global_batch),
                                 jnp.int32),
        }
        items_per_step, unit_noun = global_batch, "images"
    elif args.model == "bert_base":
        from pytorch_distributed_train_tpu.data.datasets import synthetic_mlm

        ds = synthetic_mlm(global_batch, seq, model_cfg.vocab_size,
                           mlm_prob=0.15)
        mlm_batch = ds.get_batch(np.arange(global_batch), rng_np, train=True)
        batch = {k: jnp.asarray(v) for k, v in mlm_batch.items()}
        items_per_step, unit_noun = global_batch * seq, "tokens"
    elif args.model == "t5":
        labels = rng_np.integers(0, model_cfg.vocab_size,
                                 (global_batch, tgt_seq))
        batch = {
            "input_ids": jnp.asarray(
                rng_np.integers(0, model_cfg.vocab_size,
                                (global_batch, seq)), jnp.int32),
            "decoder_input_ids": jnp.asarray(
                np.concatenate([np.zeros((global_batch, 1), np.int64),
                                labels[:, :-1]], 1), jnp.int32),
            "labels": jnp.asarray(labels, jnp.int32),
        }
        items_per_step = global_batch * (seq + tgt_seq)
        unit_noun = "tokens"
    else:
        batch = {"input_ids": jnp.asarray(
            rng_np.integers(0, model_cfg.vocab_size, (global_batch, seq)),
            jnp.int32)}
        items_per_step, unit_noun = global_batch * seq, "tokens"

    # Timing always excludes compile: at least one warmup step runs.
    t_warm0 = time.monotonic()
    for _ in range(max(args.warmup, 1)):
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])  # value fetch = hard sync (see module docstring)
    compile_s = time.monotonic() - t_warm0
    _disarm_watchdog()  # warmup executed: backend is healthy

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = step(state, batch, rng)
    loss = float(metrics["loss"])  # forces the whole donated-state chain
    _touch()  # timed steps executed
    wall = time.perf_counter() - t0
    assert np.isfinite(loss), f"non-finite loss {loss}"

    per_step = wall / args.steps
    per_chip = items_per_step / per_step / n_chips

    # bert carries an explicit _mlm tag: the round-1 key measured plain
    # next-token xent and must never be compared against the MLM workload.
    bench_name = "bert_base_mlm" if args.model == "bert_base" else args.model
    # Compute-graph arms encode into the metric name (PR 12 convention:
    # each arm owns its ledger trajectory; the gate never cross-judges).
    arm_parts = []
    if accum > 1:
        arm_parts.append(f"ga{accum}")
    if args.overlap_collectives:
        arm_parts.append("overlap")
    if args.fused_epilogue:
        arm_parts.append("fusedep")
    arm_sfx = ("_" + "_".join(arm_parts)) if arm_parts else ""
    metric = f"{bench_name}{arm_sfx}_{unit_noun}_per_sec_per_chip"
    # Only canonical shapes may seed a baseline key — smoke runs with
    # non-default shapes must not (BASELINE.md policy).
    default_opt = (not args.optimizer and not args.moment_dtype
                   and not args.offload_opt and not arm_parts)
    if vision:
        # resnet50 is the north-star; vit_b16 also tracks its own key so
        # regressions there are visible across rounds (resnet18 stays a
        # smoke config).
        canonical = (args.model in ("resnet50", "vit_b16")
                     and args.batch_per_chip in (0, 128)
                     and args.image_size == 224 and default_opt
                     and args.stem == "conv")
    elif args.model == "llama":
        # fused-head runs are a different program (no logits materialized) —
        # they must not share a baseline key with the dense-head config.
        canonical = (args.batch_per_chip in (0, 8) and args.seq_len == 2048
                     and args.attention_impl == "auto"
                     and not args.fused_head and not args.quant_training
                     and args.remat_policy == "full" and default_opt)
    elif args.model == "t5":
        canonical = (args.batch_per_chip in (0, 64) and args.seq_len >= 512
                     and default_opt)
    else:  # bert_base
        canonical = (args.batch_per_chip in (0, 32) and args.seq_len >= 512
                     and args.attention_impl == "auto" and default_opt)
    baseline_path = os.path.join(os.path.dirname(__file__),
                                 "BENCH_BASELINE.json")
    base = {}
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f)
    vs = per_chip / base[metric] if base.get(metric) else 1.0
    if metric not in base and canonical:
        # First measured run of a canonical config seeds its baseline key.
        base[metric] = per_chip
        base.setdefault("recorded", time.strftime("%Y-%m-%d"))
        with open(baseline_path, "w") as f:
            json.dump(base, f, indent=1)

    record = {
        "metric": metric,
        "value": round(per_chip, 2),
        "unit": f"{unit_noun}/sec/chip",
        "vs_baseline": round(vs, 4),
        # Bench-local goodput split (obs/goodput.py vocabulary): wall to
        # warmup/compile vs the timed steady-state steps; goodput_pct is
        # the timed fraction of the whole bench process life — a bench
        # that spent ten minutes in backend bring-up says so.
        "goodput_s_compile": round(compile_s, 3),
        "goodput_s_step": round(wall, 3),
        "goodput_pct": round(
            100.0 * wall / max(time.monotonic() - _T_MAIN0[0], 1e-9), 2),
    }
    if accum > 1:
        record["grad_accum_steps"] = accum
    if args.overlap_collectives:
        record["grad_buckets"] = n_buckets
        record["grad_bucket_mb"] = args.grad_bucket_mb
    if args.fused_epilogue:
        record["fused_epilogue"] = True
    from pytorch_distributed_train_tpu.obs import perf as perf_lib

    # Synthetic device batches: the stall split is usually empty — a
    # nonzero split here means a real loader fed this bench.
    split = perf_lib.get_input_stats().split()
    if split:
        record["stall_split"] = split
    # MFU accounting (VERDICT r3 #2): analytic model FLOPs/item (2xMACs,
    # train = 3x fwd — utils/flops.py conventions) over the detected
    # chip's bf16 peak. None on CPU backends (no MXU peak to divide by).
    from pytorch_distributed_train_tpu.utils import flops as flops_lib

    fpi = flops_lib.train_flops_per_item(model_cfg, None if vision else seq)
    peak = flops_lib.device_peak_flops(jax.devices()[0])
    mfu = flops_lib.mfu_pct(per_chip, fpi, peak)
    if fpi is not None:
        record["model_gflops_per_item"] = round(fpi / 1e9, 3)
    if mfu is not None:
        record["mfu_pct"] = round(mfu, 2)
    _emit(record)


if __name__ == "__main__":
    try:
        main()
    except Exception as exc:
        msg = f"{type(exc).__name__}: {exc}"
        if not _bringup_done[0] and any(m in msg for m in _BACKEND_ERR_MARKERS):
            traceback.print_exc(file=sys.stderr)
            _emit_backend_unavailable(msg)
            sys.exit(3)
        raise
