#!/usr/bin/env python
"""bench.py — north-star benchmark: ResNet-50 ImageNet-shape training
throughput, images/sec/chip (BASELINE.json:2).

Prints ONE JSON line:
  {"metric": "resnet50_images_per_sec_per_chip", "value": N,
   "unit": "images/sec/chip", "vs_baseline": R}

vs_baseline compares against the first measured value recorded in
BENCH_BASELINE.json (the reference publishes no numbers — BASELINE.md
policy: first instrumented run IS the baseline, ratio 1.0 that round).

Methodology: synthetic data (isolates device throughput from disk),
bf16 compute policy, full train step (fwd+bwd+SGD update) on all local
devices. Timing enqueues `--steps` steps back-to-back and then fetches the
final step's loss VALUE: the loss depends on the (donated) state chain, so
the fetch forces every enqueued step to have executed. This measures
pipelined steady-state throughput the way a real training loop runs, and —
unlike `block_until_ready` — cannot return early under remote/tunnelled
PJRT backends (observed: block_until_ready on this sandbox's axon tunnel
reports readiness ~40x before execution finishes).
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch-per-chip", type=int, default=128)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--model", default="resnet50")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.config import (
        MeshConfig,
        ModelConfig,
        OptimConfig,
        PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.losses import get_loss_fn
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.optim import make_optimizer
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh
    from pytorch_distributed_train_tpu.parallel.partition import rules_for_model
    from pytorch_distributed_train_tpu.train_state import TrainState

    n_chips = jax.device_count()
    mesh = build_mesh(MeshConfig(data=-1, fsdp=1, tensor=1, context=1))
    model_cfg = ModelConfig(name=args.model, num_classes=1000,
                            image_size=args.image_size)
    model = build_model(model_cfg, PrecisionConfig(compute_dtype="bfloat16"))
    tx, _ = make_optimizer(
        OptimConfig(name="momentum", learning_rate=0.1, schedule="constant",
                    warmup_steps=0),
        total_steps=1000,
    )
    rules = rules_for_model(args.model)

    def init_state(rng):
        x = jnp.zeros((2, args.image_size, args.image_size, 3))
        variables = model.init({"params": rng}, x, train=False)
        return TrainState.create(params=variables["params"], tx=tx,
                                 batch_stats=variables.get("batch_stats", {}))

    rng = jax.random.PRNGKey(0)
    shape = jax.eval_shape(init_state, rng)
    sharding = steps_lib.state_shardings(mesh, rules, shape)
    state = jax.jit(init_state, out_shardings=sharding)(rng)
    step = steps_lib.jit_train_step(
        steps_lib.make_train_step(model, get_loss_fn("softmax_xent"), tx),
        mesh, sharding,
    )

    global_batch = args.batch_per_chip * n_chips
    rng_np = np.random.default_rng(0)
    batch = {
        "image": jnp.asarray(
            rng_np.standard_normal(
                (global_batch, args.image_size, args.image_size, 3)
            ),
            jnp.float32,
        ),
        "label": jnp.asarray(rng_np.integers(0, 1000, global_batch), jnp.int32),
    }

    for _ in range(args.warmup):
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])  # value fetch = hard sync (see module docstring)

    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, metrics = step(state, batch, rng)
    loss = float(metrics["loss"])  # forces the whole donated-state chain
    wall = time.perf_counter() - t0
    assert np.isfinite(loss), f"non-finite loss {loss}"

    per_step = wall / args.steps
    imgs_per_sec = global_batch / per_step
    per_chip = imgs_per_sec / n_chips

    baseline_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    default_run = (args.batch_per_chip == 128 and args.image_size == 224
                   and args.model == "resnet50")
    vs = 1.0
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            base = json.load(f).get("resnet50_images_per_sec_per_chip")
        if base:
            vs = per_chip / base
    elif default_run:
        # First measured default run seeds the baseline (BASELINE.md policy);
        # smoke runs with non-default shapes must not.
        with open(baseline_path, "w") as f:
            json.dump({"resnet50_images_per_sec_per_chip": per_chip,
                       "recorded": time.strftime("%Y-%m-%d")}, f)

    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(vs, 4),
    }))


if __name__ == "__main__":
    main()
