"""Weight-only int8 decode params (quant.py): error bounds, structure,
size, and end-to-end generation with quantized weights."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_train_tpu import quant
from pytorch_distributed_train_tpu.config import ModelConfig, PrecisionConfig
from pytorch_distributed_train_tpu.generate import (
    build_decode_model,
    generate,
)
from pytorch_distributed_train_tpu.models.registry import build_model


def test_leaf_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 32)) * 0.1, jnp.float32)
    q = quant.quantize_leaf(w)
    assert q["w_int8"].dtype == jnp.int8
    assert q["scale"].shape == (1, 32)
    back = quant.dequantize_leaf(q, jnp.float32)
    # symmetric absmax: per-element error <= half a quantization step
    bound = np.asarray(q["scale"])[0] / 2 + 1e-7
    err = np.abs(np.asarray(back) - np.asarray(w))
    assert np.all(err <= bound[None, :] + 1e-6)
    # zero channels stay exactly zero (scale guard against /0)
    w0 = w.at[:, 3].set(0.0)
    back0 = quant.dequantize_leaf(quant.quantize_leaf(w0), jnp.float32)
    assert np.all(np.asarray(back0)[:, 3] == 0.0)


def test_tree_quantization_targets_and_size():
    params = {
        "attn": {"q_proj": {"kernel": jnp.ones((64, 64))}},
        "embed": {"embedding": jnp.ones((100, 64))},
        "norm": {"scale": jnp.ones((64,))},
        "fc": {"bias": jnp.ones((64,))},
    }
    q = quant.quantize_tree(params)
    assert quant.is_quantized(q)
    assert set(q["attn"]["q_proj"]["kernel"].keys()) == {"w_int8", "scale"}
    assert set(q["embed"]["embedding"].keys()) == {"w_int8", "scale"}
    # vectors untouched
    assert isinstance(q["norm"]["scale"], jax.Array)
    assert isinstance(q["fc"]["bias"], jax.Array)
    # resident bytes: int8 + small scales ≈ 1/4 of fp32
    assert quant.tree_param_bytes(q) < 0.3 * quant.tree_param_bytes(params)
    # dequantize restores structure and dtype
    d = quant.dequantize_tree(q, jnp.float32)
    assert (jax.tree_util.tree_structure(d)
            == jax.tree_util.tree_structure(params))


@pytest.mark.parametrize("family", ["llama", "gpt2"])
def test_quantized_generate_end_to_end(family):
    V, S = 128, 24
    cfg = ModelConfig(name=family, vocab_size=V, hidden_size=64,
                      num_layers=2, num_heads=4, num_kv_heads=4,
                      mlp_dim=128, max_seq_len=S)
    train_model = build_model(cfg, PrecisionConfig())
    ids = jnp.asarray(np.random.default_rng(0).integers(0, V, (2, 8)),
                      jnp.int32)
    params = train_model.init({"params": jax.random.PRNGKey(0)}, ids,
                              train=False)["params"]
    model = build_decode_model(cfg, PrecisionConfig())
    full = generate(model, params, ids, 8)
    qparams = quant.quantize_tree(params)
    qout = generate(model, qparams, ids, 8)
    assert qout.shape == full.shape == (2, 16)
    # prompts echo through unchanged
    np.testing.assert_array_equal(np.asarray(qout[:, :8]), np.asarray(ids))
    # deterministic under the same key
    qout2 = generate(model, qparams, ids, 8)
    np.testing.assert_array_equal(np.asarray(qout), np.asarray(qout2))
    # quantization noise is small at the logits level: compare one full
    # forward (teacher-forced) between full and dequantized params
    logits_f = train_model.apply({"params": params}, ids, train=False)
    logits_q = train_model.apply(
        {"params": quant.dequantize_tree(qparams, jnp.float32)}, ids,
        train=False)
    denom = np.abs(np.asarray(logits_f)).max() + 1e-6
    rel = np.abs(np.asarray(logits_f) - np.asarray(logits_q)).max() / denom
    assert rel < 0.15, rel


def test_scale_granularity_per_leaf_kind():
    """3D q/k/v-layout kernels keep per-(head, head_dim) scales; out-proj
    layout keeps per-output-channel; embeddings per-row."""
    qkv = quant.quantize_leaf(jnp.ones((256, 4, 64)))   # (C, H, D)
    assert qkv["scale"].shape == (1, 4, 64)
    oproj = quant.quantize_leaf(jnp.ones((4, 64, 256)))  # (H, D, C)
    assert oproj["scale"].shape == (1, 1, 256)
    tree = quant.quantize_tree({"embed": {"embedding": jnp.ones((100, 32))}})
    assert tree["embed"]["embedding"]["scale"].shape == (100, 1)

    # an outlier in head 0 must not widen head 1's quantization step
    w = jnp.zeros((256, 2, 8)).at[0, 0, 0].set(100.0).at[:, 1, :].set(0.5)
    q = quant.quantize_leaf(w)
    back = quant.dequantize_leaf(q, jnp.float32)
    np.testing.assert_allclose(np.asarray(back[:, 1, :]), 0.5, rtol=0.01)


# ===================================================== int8 QAT (training)

def test_int8_dot_general_forward_error_and_ste():
    """AQT core: forward within quant error of the fp dot (per-token ×
    per-channel scales); backward is EXACTLY the fp dot's vjp (STE)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 16, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 32)) * 0.05, jnp.float32)
    dims = (((2,), (0,)), ((), ()))
    out = quant.int8_dot_general(x, w, dims)
    ref = jax.lax.dot_general(x, w, dims)
    rel = float(jnp.abs(out - ref).mean() / jnp.abs(ref).mean())
    assert rel < 0.02, rel

    # STE: the custom-vjp backward is the fp dot's transpose at the
    # original values — same cotangent in, identical grads out.
    g = jnp.asarray(rng.standard_normal(ref.shape), jnp.float32)
    _, vjp8 = jax.vjp(lambda a, b: quant.int8_dot_general(a, b, dims), x, w)
    _, vjpf = jax.vjp(lambda a, b: jax.lax.dot_general(a, b, dims), x, w)
    for a, b in zip(vjp8(g), vjpf(g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    # multi-axis contraction (the o_proj DenseGeneral layout)
    y = jnp.asarray(rng.standard_normal((2, 8, 4, 16)), jnp.float32)
    wo = jnp.asarray(rng.standard_normal((4, 16, 64)) * 0.05, jnp.float32)
    dims2 = (((2, 3), (0, 1)), ((), ()))
    o2 = quant.int8_dot_general(y, wo, dims2)
    r2 = jax.lax.dot_general(y, wo, dims2)
    rel2 = float(jnp.abs(o2 - r2).mean() / jnp.abs(r2).mean())
    assert rel2 < 0.02, rel2

    # dtype follows lhs (flax hands both in the compute dtype)
    ob = quant.int8_dot_general(x.astype(jnp.bfloat16),
                                w.astype(jnp.bfloat16), dims)
    assert ob.dtype == jnp.bfloat16


def test_int8_qat_llama_trains():
    """Tiny llama with quant_training='int8': forward close to the fp
    model at init (same params), loss decreases over steps, grads finite."""
    import optax

    from pytorch_distributed_train_tpu.losses import get_loss_fn

    tiny = dict(name="llama", vocab_size=128, hidden_size=64, num_layers=2,
                num_heads=4, num_kv_heads=4, mlp_dim=128, max_seq_len=32)
    fp_model = build_model(ModelConfig(**tiny), PrecisionConfig())
    q_model = build_model(ModelConfig(**tiny, quant_training="int8"),
                          PrecisionConfig())
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (8, 32)), jnp.int32)
    params = fp_model.init({"params": jax.random.PRNGKey(0)}, ids,
                           train=False)["params"]
    # identical param trees: the dot_general override adds no params
    q_init = q_model.init({"params": jax.random.PRNGKey(0)}, ids,
                          train=False)["params"]
    assert jax.tree_util.tree_structure(params) == \
        jax.tree_util.tree_structure(q_init)

    fp_logits = fp_model.apply({"params": params}, ids, train=False)
    q_logits = q_model.apply({"params": params}, ids, train=False)
    rel = float(jnp.abs(q_logits - fp_logits).mean()
                / (jnp.abs(fp_logits).mean() + 1e-9))
    assert rel < 0.2, rel  # quantization noise, not garbage

    loss_fn = get_loss_fn("causal_lm_xent")
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss(p):
            logits = q_model.apply({"params": p}, ids, train=True)
            return loss_fn(logits, {"input_ids": ids})[0]

        l, g = jax.value_and_grad(loss)(params)
        updates, opt_state = tx.update(g, opt_state)
        return optax.apply_updates(params, updates), opt_state, l, g

    losses = []
    for _ in range(8):
        params, opt_state, l, g = step(params, opt_state)
        losses.append(float(l))
        assert all(np.all(np.isfinite(np.asarray(x)))
                   for x in jax.tree_util.tree_leaves(g))
    assert losses[-1] < losses[0], losses


def test_int8_qat_threads_into_pipelined_llama():
    """llama_pp reuses LlamaBlock; the knob must reach the block template
    (full pipelined execution is covered by test_pipeline_parallel — here
    we pin the config plumbing that would otherwise silently drop it)."""
    from pytorch_distributed_train_tpu.config import MeshConfig
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh

    tiny = dict(name="llama_pp", vocab_size=128, hidden_size=64,
                num_layers=2, num_heads=4, num_kv_heads=4, mlp_dim=128,
                max_seq_len=32, pipeline_microbatches=2)
    mesh_cfg = MeshConfig(stage=2)
    mesh = build_mesh(mesh_cfg, jax.devices("cpu")[:2])
    q_model = build_model(ModelConfig(**tiny, quant_training="int8"),
                          PrecisionConfig(), mesh=mesh, mesh_cfg=mesh_cfg)
    assert q_model.block.quant == "int8"
    fp_model = build_model(ModelConfig(**tiny), PrecisionConfig(),
                           mesh=mesh, mesh_cfg=mesh_cfg)
    assert fp_model.block.quant == ""


def test_int8_qat_gpt2_forward():
    """gpt2 threads quant_training into its blocks: same param tree as fp,
    forward within quantization noise."""
    import numpy as np

    tiny = dict(name="gpt2", vocab_size=128, hidden_size=64, num_layers=2,
                num_heads=4, mlp_dim=128, max_seq_len=32)
    fp_model = build_model(ModelConfig(**tiny), PrecisionConfig())
    q_model = build_model(ModelConfig(**tiny, quant_training="int8"),
                          PrecisionConfig())
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (4, 32)), jnp.int32)
    params = fp_model.init({"params": jax.random.PRNGKey(0)}, ids,
                           train=False)["params"]
    q_init = q_model.init({"params": jax.random.PRNGKey(0)}, ids,
                          train=False)["params"]
    assert jax.tree_util.tree_structure(params) == \
        jax.tree_util.tree_structure(q_init)
    fp_out = fp_model.apply({"params": params}, ids, train=False)
    q_out = q_model.apply({"params": params}, ids, train=False)
    rel = float(jnp.abs(q_out - fp_out).mean()
                / (jnp.abs(fp_out).mean() + 1e-9))
    assert rel < 0.2, rel


def test_quant_training_guarded_to_llama(tmp_path):
    from pytorch_distributed_train_tpu.config import get_preset
    from pytorch_distributed_train_tpu.trainer import Trainer

    cfg = get_preset("resnet18_cifar10")
    cfg.model.quant_training = "int8"
    cfg.checkpoint.dir = str(tmp_path)
    with pytest.raises(ValueError, match="quant_training"):
        Trainer(cfg)


def test_int4_leaf_roundtrip_and_grouping():
    """Group-wise int4: error bounded by each group's absmax/14 half-step;
    the grouping axis/size is recoverable from shapes alone (the struct
    carries no metadata)."""
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((256, 32)) * 0.1, jnp.float32)
    q = quant.quantize_leaf_int4(w, group_size=128)
    assert q["w_int4"].dtype == jnp.int4
    assert q["scale"].shape == (2, 1, 32)  # 256 → 2 groups of 128
    back = np.asarray(quant.dequantize_leaf(q, jnp.float32))
    scale = np.asarray(q["scale"])  # half-step bound per group
    err = np.abs(back - np.asarray(w)).reshape(2, 128, 32)
    assert np.all(err <= scale / 2 + 1e-6)
    # int4 error is larger than int8's but bounded ~absmax/14 per group
    assert err.max() <= np.abs(np.asarray(w)).max() / 14 * 1.05

    # Indivisible axis → one group (int8-granularity at int4 width)
    w2 = jnp.asarray(rng.standard_normal((100, 8)), jnp.float32)
    q2 = quant.quantize_leaf_int4(w2, group_size=128)
    assert q2["scale"].shape == (1, 1, 8)
    # zero guard
    back0 = quant.dequantize_leaf(
        quant.quantize_leaf_int4(jnp.zeros((128, 4))), jnp.float32)
    assert np.all(np.asarray(back0) == 0.0)


def test_int4_tree_and_bytes():
    params = {
        "attn": {"q_proj": {"kernel": jnp.ones((128, 64))}},
        "embed": {"embedding": jnp.ones((256, 64))},
        "norm": {"scale": jnp.ones((64,))},
    }
    q = quant.quantize_tree(params, bits=4)
    assert quant.is_quantized(q)
    assert set(q["attn"]["q_proj"]["kernel"].keys()) == {"w_int4", "scale"}
    # logical bytes: ~1/8 of fp32 (packed device representation)
    assert quant.tree_param_bytes(q) < 0.2 * quant.tree_param_bytes(params)
    d = quant.dequantize_tree(q, jnp.float32)
    assert (jax.tree_util.tree_structure(d)
            == jax.tree_util.tree_structure(params))
    with pytest.raises(ValueError, match="bits"):
        quant.quantize_tree(params, bits=2)


def test_int4_generate_matches_fp_argmax_mostly():
    """Weight-only int4 decode must stay CLOSE to the fp model: greedy
    generations from the same prompt agree on most steps (int4 is lossier
    than int8 — exact match isn't the bar; trajectory sanity is)."""
    from pytorch_distributed_train_tpu.generate import (
        build_decode_model,
        generate,
    )
    from pytorch_distributed_train_tpu.models.registry import build_model

    V, S = 128, 24
    cfg = ModelConfig(name="llama", vocab_size=V, hidden_size=64,
                      num_layers=2, num_heads=4, num_kv_heads=4,
                      mlp_dim=128, max_seq_len=S)
    prec = PrecisionConfig(compute_dtype="float32")
    params = build_model(cfg, prec).init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 4), jnp.int32), train=False)["params"]
    model = build_decode_model(cfg, prec)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, V, (2, 8)), jnp.int32)
    fp = np.asarray(generate(model, params, prompt, 8))
    q4 = np.asarray(generate(
        model, jax.jit(lambda p: quant.quantize_tree(p, bits=4))(params),
        prompt, 8))
    gen_fp, gen_q4 = fp[:, 8:], q4[:, 8:]
    agree = (gen_fp == gen_q4).mean()
    assert agree >= 0.5, (agree, gen_fp, gen_q4)
