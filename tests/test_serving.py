"""Continuous batching (serving.py).

Correctness anchor: a slot-based batcher serving many requests of
different lengths, admitted at different times, must produce for EVERY
request exactly what lockstep generate() produces for that request alone
— same weights, same sampling law. Per-row cache indices
(models/llama.py decode_rows) are what make this equality non-trivial:
slots decode at different offsets inside one batched step.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_train_tpu.config import ModelConfig, PrecisionConfig
from pytorch_distributed_train_tpu.generate import (
    build_decode_model,
    generate,
)
from pytorch_distributed_train_tpu.models.registry import build_model
from pytorch_distributed_train_tpu.serving import (
    ContinuousBatcher,
    build_serving_model,
)

V, C, L, H, MLP, MAXLEN = 61, 32, 2, 2, 48, 48


def _cfg(**kw):
    base = dict(name="llama", vocab_size=V, hidden_size=C, num_layers=L,
                num_heads=H, num_kv_heads=H, mlp_dim=MLP, max_seq_len=MAXLEN)
    base.update(kw)
    return ModelConfig(**base)


@pytest.fixture(scope="module")
def setup():
    cfg = _cfg()
    train_model = build_model(cfg, PrecisionConfig())
    ids = jnp.zeros((1, 4), jnp.int32)
    params = train_model.init({"params": jax.random.PRNGKey(0)}, ids,
                              train=False)["params"]
    return cfg, params


def _reference(cfg, params, prompt, n):
    """Lockstep generate() for one prompt — the ground truth."""
    dm = build_decode_model(cfg, PrecisionConfig())
    out = generate(dm, params, jnp.asarray([prompt], jnp.int32), n)
    return [int(t) for t in np.asarray(out)[0, len(prompt):]]


def test_matches_lockstep_generate_mixed_lengths(setup):
    cfg, params = setup
    rng = np.random.default_rng(1)
    prompts = [list(map(int, rng.integers(0, V, n))) for n in (3, 9, 17, 5)]
    budgets = [6, 3, 8, 5]

    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    uids = [b.submit(p, n) for p, n in zip(prompts, budgets)]
    done = {c.uid: c for c in b.run()}

    assert sorted(done) == sorted(uids)
    for uid, p, n in zip(uids, prompts, budgets):
        assert done[uid].tokens == _reference(cfg, params, p, n), \
            f"request {uid} diverged from lockstep generate()"
        assert done[uid].finish_reason == "length"


def test_mid_stream_admission_into_freed_slot(setup):
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    p1, p2 = [5, 6, 7], [11, 3]
    u1 = b.submit(p1, 4)
    # drain request 1 fully with the single slot, then admit request 2
    finished = []
    while not finished:
        finished = b.step()
    assert finished[0].uid == u1
    u2 = b.submit(p2, 3)
    done = {c.uid: c for c in b.run()}
    assert done[u2].tokens == _reference(cfg, params, p2, 3)
    # slot reuse must not leak request 1's cache into request 2
    assert done[u2].tokens != finished[0].tokens[:3] or \
        _reference(cfg, params, p2, 3) == finished[0].tokens[:3]


def test_eos_frees_slot_early(setup):
    cfg, params = setup
    prompt = [9, 2, 4]
    ref = _reference(cfg, params, prompt, 8)
    eos = ref[3]  # greedy emits this at step 4 → batcher must stop there
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    uid = b.submit(prompt, 8, eos_id=eos)
    done = {c.uid: c for c in b.run()}
    assert done[uid].finish_reason == "eos"
    assert done[uid].tokens == ref[:4]


def test_free_slots_do_not_corrupt_active_rows(setup):
    """A batcher with 4 slots serving ONE request: the three dead rows
    free-run through every decode step and must not perturb the live row."""
    cfg, params = setup
    prompt = [1, 2, 3, 4, 5]
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=4)
    uid = b.submit(prompt, 10)
    done = {c.uid: c for c in b.run()}
    assert done[uid].tokens == _reference(cfg, params, prompt, 10)


def test_sampling_temperature_is_per_row(setup):
    cfg, params = setup
    rng = np.random.default_rng(2)
    p1 = list(map(int, rng.integers(0, V, 4)))
    p2 = list(map(int, rng.integers(0, V, 4)))
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2,
                          rng=jax.random.PRNGKey(7))
    u1 = b.submit(p1, 5, temperature=0.0)
    b.submit(p2, 5, temperature=1.5)
    done = {c.uid: c for c in b.run()}
    # the greedy row must be exactly the deterministic continuation even
    # though its batch-mate sampled stochastically
    assert done[u1].tokens == _reference(cfg, params, p1, 5)


def test_serving_model_requires_decode_rows():
    cfg = ModelConfig(name="resnet18")
    with pytest.raises(ValueError, match="decode"):
        build_serving_model(cfg, PrecisionConfig())


def test_stats_track_throughput(setup):
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    b.submit([1, 2], 3)
    b.submit([3, 4, 5], 3)
    list(b.run())
    assert b.stats["prefills"] == 2
    assert b.stats["generated_tokens"] == 6
    assert b.stats["steps"] >= 2


def test_gpt2_matches_lockstep_generate():
    """decode_rows covers gpt2 too: per-row LEARNED-position slices (the
    wpe counter is per-row state, unlike llama's stateless rope)."""
    cfg = ModelConfig(name="gpt2", vocab_size=V, hidden_size=C,
                      num_layers=L, num_heads=H, mlp_dim=MLP,
                      max_seq_len=MAXLEN, dropout_rate=0.0)
    train_model = build_model(cfg, PrecisionConfig())
    params = train_model.init({"params": jax.random.PRNGKey(1)},
                              jnp.zeros((1, 4), jnp.int32),
                              train=False)["params"]
    rng = np.random.default_rng(3)
    prompts = [list(map(int, rng.integers(0, V, n))) for n in (4, 11, 7)]
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    uids = [b.submit(p, 5) for p in prompts]
    done = {c.uid: c for c in b.run()}
    for uid, p in zip(uids, prompts):
        assert done[uid].tokens == _reference(cfg, params, p, 5), \
            "gpt2 slot diverged from lockstep generate()"


# ------------------------------------------------------- seq2seq (t5)

def _t5_cfg():
    return ModelConfig(name="t5", vocab_size=53, hidden_size=32,
                       num_layers=2, num_heads=4, mlp_dim=64,
                       max_seq_len=24, dropout_rate=0.0)


@pytest.fixture(scope="module")
def t5_setup():
    cfg = _t5_cfg()
    model = build_model(cfg, PrecisionConfig())
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, 4), jnp.int32),
                        jnp.zeros((1, 2), jnp.int32),
                        train=False)["params"]
    return cfg, params


def _t5_reference(cfg, params, src, n, eos_id=None):
    from pytorch_distributed_train_tpu.generate import generate_seq2seq

    out = generate_seq2seq(cfg, PrecisionConfig(), params,
                           jnp.asarray([src], jnp.int32), n, eos_id=eos_id)
    return [int(t) for t in np.asarray(out)[0]]


def test_t5_serving_matches_lockstep(t5_setup):
    """Mixed source lengths over fewer slots than requests: every target
    must equal the lockstep generate_seq2seq output — pins the per-row
    decoder offsets, the per-slot relative-bias rows, and the
    cross-attention masking of each slot's padded source."""
    from pytorch_distributed_train_tpu.serving import (
        Seq2SeqContinuousBatcher,
    )

    cfg, params = t5_setup
    rng = np.random.default_rng(4)
    sources = [list(map(int, rng.integers(2, 53, n))) for n in (3, 15, 8)]
    budgets = [6, 4, 7]
    b = Seq2SeqContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    uids = [b.submit(s, n) for s, n in zip(sources, budgets)]
    done = {c.uid: c for c in b.run()}
    assert sorted(done) == sorted(uids)
    for uid, s, n in zip(uids, sources, budgets):
        assert done[uid].tokens == _t5_reference(cfg, params, s, n), \
            f"t5 request {uid} diverged from lockstep generate_seq2seq()"
        # slot reuse must not leak the previous occupant's logprobs
        assert len(done[uid].logprobs) == len(done[uid].tokens)


def test_t5_serving_eos_frees_slot(t5_setup):
    from pytorch_distributed_train_tpu.serving import (
        Seq2SeqContinuousBatcher,
    )

    cfg, params = t5_setup
    src = [5, 9, 3, 17]
    ref = _t5_reference(cfg, params, src, 8)
    eos = ref[2]
    b = Seq2SeqContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    uid = b.submit(src, 8, eos_id=eos)
    done = {c.uid: c for c in b.run()}
    assert done[uid].finish_reason == "eos"
    assert done[uid].tokens == ref[:3]


def test_t5_serving_refuses_causal_models(t5_setup):
    from pytorch_distributed_train_tpu.serving import (
        Seq2SeqContinuousBatcher,
    )

    with pytest.raises(ValueError, match="t5 family"):
        Seq2SeqContinuousBatcher(_cfg(), PrecisionConfig(), None)


def test_tensor_parallel_serving_matches_single_device(setup):
    """Multi-chip continuous batching: params via shard_decode_params on
    a data x tensor mesh, cache allocated into its mesh layout — every
    request's greedy output must equal the single-device batcher's."""
    from pytorch_distributed_train_tpu.config import MeshConfig
    from pytorch_distributed_train_tpu.generate import shard_decode_params
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh

    cfg, params = setup
    mesh = build_mesh(MeshConfig(tensor=2))  # data fills the rest
    sharded = shard_decode_params("llama", mesh, params)
    rng = np.random.default_rng(5)
    prompts = [list(map(int, rng.integers(0, V, n))) for n in (4, 13, 7)]

    b = ContinuousBatcher(cfg, PrecisionConfig(), sharded, slots=2,
                          mesh=mesh)
    uids = [b.submit(p, 5) for p in prompts]
    done = {c.uid: c for c in b.run()}
    for uid, p in zip(uids, prompts):
        assert done[uid].tokens == _reference(cfg, params, p, 5), \
            "TP serving diverged from single-device"


# --------------------------------------------------------- chat sessions

def test_session_resume_matches_full_conversation(setup):
    """The multi-turn anchor: turn 2 resumed from a parked session must
    produce EXACTLY what lockstep generate() produces on the whole
    concatenated conversation — the parked K/V (which free-ran through
    other slots' steps between turns) is bit-equivalent to a fresh
    prefill of the full history."""
    cfg, params = setup
    turn1, turn2 = [7, 3, 9, 2], [11, 5, 6]
    k1, k2 = 5, 6

    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=3)
    u1 = b.submit(turn1, k1, keep=True)
    done = {c.uid: c for c in b.run()}
    sid = done[u1].session
    assert sid is not None
    gen1 = done[u1].tokens

    # churn the batcher between turns: other requests decode while the
    # session sits parked (its counters free-run; resume must not care)
    b.submit([1, 2, 3], 7)
    b.submit([4, 4, 4, 4, 4, 4, 4, 4], 4)
    list(b.run())

    u2 = b.submit(turn2, k2, session=sid)
    done2 = {c.uid: c for c in b.run()}
    gen2 = done2[u2].tokens

    full_prompt = turn1 + gen1 + turn2
    assert gen2 == _reference(cfg, params, full_prompt, k2), \
        "session resume diverged from full-conversation lockstep"


def test_session_chained_turns(setup):
    """Three turns chained keep->resume->resume, checked against the
    full conversation each time."""
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    history = [9, 1, 4]
    sid = None
    for i, (turn, k) in enumerate([(None, 4), ([2, 8], 3), ([5], 4)]):
        prompt = history if sid is None else turn
        uid = b.submit(prompt, k, keep=True, session=sid)
        done = {c.uid: c for c in b.run()}
        gen = done[uid].tokens
        sid = done[uid].session
        if turn is not None:
            history = history + turn
        assert gen == _reference(cfg, params, history, k), f"turn {i}"
        history = history + gen


def test_session_eviction_under_slot_pressure(setup):
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    u1 = b.submit([1, 2], 2, keep=True)
    done = {c.uid: c for c in b.run()}
    sid = done[u1].session
    # a fresh request needs the only slot -> the parked session evicts
    u2 = b.submit([3, 4, 5], 2)
    done = {c.uid: c for c in b.run()}
    assert done[u2].finish_reason == "length"
    with pytest.raises(ValueError, match="unknown session"):
        b.submit([6], 2, session=sid)


def test_t5_batcher_refuses_sessions(t5_setup):
    from pytorch_distributed_train_tpu.serving import (
        Seq2SeqContinuousBatcher,
    )

    cfg, params = t5_setup
    b = Seq2SeqContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    with pytest.raises(ValueError, match="sessions"):
        b.submit([1, 2], 3, keep=True)


def test_no_livelock_fresh_head_blocks_behind_parked_resume(setup):
    """slots=1: a fresh request queued AHEAD of a resume for the only
    (parked) slot must not livelock the scheduler — the resume admits
    first (its slot is reserved), then the fresh request takes over."""
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    u1 = b.submit([1, 2], 2, keep=True)
    done = {c.uid: c for c in b.run()}
    sid = done[u1].session
    uf = b.submit([3, 4, 5], 2)          # fresh, queue head
    ur = b.submit([6], 2, session=sid)   # resume behind it
    done = {c.uid: c for c in b.run()}
    assert set(done) == {uf, ur}
    assert done[ur].finish_reason == "length"
    assert done[uf].finish_reason == "length"


# ------------------------------------------------------- prefix caching

def test_prefix_fork_matches_concatenated_prompt(setup):
    """One preloaded system prompt serves many forks: each fork's output
    must equal lockstep generate() on system+user, the template must
    survive all forks, and only ONE prefill of the system prompt ever
    runs."""
    cfg, params = setup
    system = [7, 7, 3, 9, 2, 5]
    users = [[11, 4], [6, 1, 8], [13]]
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=3)
    sid = b.preload(system)
    uids = [b.submit(u, 5, prefix=sid) for u in users]
    done = {c.uid: c for c in b.run()}
    for uid, u in zip(uids, users):
        assert done[uid].tokens == _reference(cfg, params, system + u, 5), \
            "fork diverged from lockstep on the concatenated prompt"
    assert b.stats["prefills"] == 1  # the system prompt, once
    assert b.stats["forks"] == len(users)
    # template still parked: a later fork still works
    u4 = b.submit([2, 2], 4, prefix=sid)
    done = {c.uid: c for c in b.run()}
    assert done[u4].tokens == _reference(cfg, params, system + [2, 2], 4)


def test_prefix_fork_with_keep_creates_independent_session(setup):
    cfg, params = setup
    system = [5, 9, 1, 3]
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=3)
    sid = b.preload(system)
    u1 = b.submit([4, 2], 3, prefix=sid, keep=True)
    done = {c.uid: c for c in b.run()}
    chat_sid = done[u1].session
    gen1 = done[u1].tokens
    # continue the forked chat; the template is untouched
    u2 = b.submit([8], 4, session=chat_sid)
    done = {c.uid: c for c in b.run()}
    hist = system + [4, 2] + gen1 + [8]
    assert done[u2].tokens == _reference(cfg, params, hist, 4)
    u3 = b.submit([1], 3, prefix=sid)  # template still serves forks
    done = {c.uid: c for c in b.run()}
    assert done[u3].tokens == _reference(cfg, params, system + [1], 3)


def test_preload_capacity_and_eviction(setup):
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    sid = b.preload([1, 2, 3])
    # the only slot is template-reserved; a fresh request evicts it (LRU)
    uf = b.submit([4, 5], 2)
    done = {c.uid: c for c in b.run()}
    assert done[uf].finish_reason == "length"
    with pytest.raises(ValueError, match="unknown session"):
        b.submit([6], 2, prefix=sid)


def test_session_and_prefix_mutually_exclusive(setup):
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    sid = b.preload([1, 2])
    with pytest.raises(ValueError, match="mutually exclusive"):
        b.submit([3], 2, session=sid, prefix=sid)


def test_fork_with_one_slot_does_not_deadlock(setup):
    """slots=1: a fork needs a slot BESIDES its template — impossible at
    one slot. The scheduler must sacrifice the template (the fork then
    surfaces as session_evicted) instead of spinning forever."""
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    sid = b.preload([1, 2, 3])
    uid = b.submit([4, 5], 2, prefix=sid)
    done = {c.uid: c for c in b.run()}
    assert done[uid].finish_reason == "session_evicted"


def test_cancel_queued_and_active(setup):
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    u1 = b.submit([1, 2, 3], 20)
    u2 = b.submit([4, 5], 3)        # queued behind u1
    assert b.cancel(u2) is True     # de-queued before admission
    b.step()                        # u1 active now
    assert b.cancel(u1) is True     # frees the active slot
    assert b.cancel(999) is False
    done = list(b.run())
    assert done == []               # canceled requests yield nothing
    u3 = b.submit([6], 2)           # the freed slot serves new work
    done = {c.uid: c for c in b.run()}
    assert done[u3].finish_reason == "length"


def test_logprobs_accompany_tokens(setup):
    """Every generated token carries its raw-model log-probability; for a
    greedy request each must equal the max of the teacher-forced
    log-softmax at that position."""
    cfg, params = setup
    prompt = [3, 1, 4, 1, 5]
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    uid = b.submit(prompt, 6)
    done = {c.uid: c for c in b.run()}
    c = done[uid]
    assert len(c.logprobs) == len(c.tokens) == 6
    assert all(lp <= 0.0 for lp in c.logprobs)

    full_model = build_model(cfg, PrecisionConfig())
    seq = jnp.asarray([prompt + c.tokens], jnp.int32)
    logits = full_model.apply({"params": params}, seq, train=False)
    lp_all = np.asarray(jax.nn.log_softmax(
        np.asarray(logits[0], np.float32), -1))
    for i, (tok, lp) in enumerate(zip(c.tokens, c.logprobs)):
        pos = len(prompt) - 1 + i
        assert abs(lp - lp_all[pos, tok]) < 1e-3, i
        assert abs(lp - lp_all[pos].max()) < 1e-3, i  # greedy == argmax


def test_release_frees_template_slot(setup):
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    sid = b.preload([1, 2, 3])
    assert b.release(sid) is True
    assert b.release(sid) is False  # already gone
    with pytest.raises(ValueError, match="unknown session"):
        b.submit([4], 2, prefix=sid)
    # both slots usable again
    u1, u2 = b.submit([5, 6], 2), b.submit([7], 2)
    done = {c.uid for c in b.run()}
    assert done == {u1, u2}


def test_penalized_request_matches_lockstep_generate(setup):
    """A greedy request with repetition_penalty through the batcher must
    equal generate()'s penalized lockstep output (same penalty law over
    prompt+generated), and an unpenalized request in the SAME batch must
    be unaffected by its penalized neighbor."""
    cfg, params = setup
    from pytorch_distributed_train_tpu.generate import (
        build_decode_model,
        generate,
    )

    prompt = [7, 7, 7, 7, 7, 7]
    n = 8
    dm = build_decode_model(cfg, PrecisionConfig())
    ref_pen = np.asarray(generate(
        dm, params, jnp.asarray([prompt], jnp.int32), n,
        repetition_penalty=3.0))[0, len(prompt):].tolist()
    ref_plain = np.asarray(generate(
        dm, params, jnp.asarray([prompt], jnp.int32), n))[0,
                                                          len(prompt):].tolist()

    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    u_pen = b.submit(prompt, n, repetition_penalty=3.0)
    u_plain = b.submit(prompt, n)
    done = {c.uid: c for c in b.run()}
    assert done[u_pen].tokens == ref_pen
    assert done[u_plain].tokens == ref_plain
    assert ref_pen != ref_plain  # the penalty actually changed the path


def test_penalty_validation_and_openai_fields(setup):
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    with pytest.raises(ValueError, match="repetition_penalty"):
        b.submit([1, 2], 2, repetition_penalty=0.0)
    # presence/frequency accepted and the run completes
    u = b.submit([1, 2, 3], 4, presence_penalty=0.4, frequency_penalty=0.2)
    done = {c.uid: c for c in b.run()}
    assert len(done[u].tokens) == 4


def test_seq2seq_penalties_score_decoder_stream():
    """Seq2seq penalties must actually engage (decoder-stream counts, the
    encoder source is NOT context): a strong presence penalty forbids a
    token from repeating in the decoded stream vs the plain run."""
    from pytorch_distributed_train_tpu.serving import (
        Seq2SeqContinuousBatcher,
    )

    cfg = ModelConfig(name="t5", vocab_size=64, hidden_size=32,
                      num_layers=2, decoder_layers=2, num_heads=4,
                      mlp_dim=64, max_seq_len=32, dropout_rate=0.0)
    params = build_model(cfg, PrecisionConfig()).init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 6), jnp.int32), jnp.zeros((1, 2), jnp.int32),
        train=False)["params"]
    src = [5, 9, 12, 3]
    b = Seq2SeqContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    u_plain = b.submit(src, 10)
    u_pen = b.submit(src, 10, repetition_penalty=50.0,
                     presence_penalty=20.0)
    done = {c.uid: c for c in b.run()}
    plain, pen = done[u_plain].tokens, done[u_pen].tokens
    # the penalized stream cannot emit the same token twice in a row
    assert all(a != b2 for a, b2 in zip(pen[:-1], pen[1:])), pen
    # (plain output on a random tiny model typically loops — if it
    # happens not to, the no-consecutive-repeat property above still
    # proves the penalty engaged only if outputs differ; assert that
    # when the plain run has repeats)
    if any(a == b2 for a, b2 in zip(plain[:-1], plain[1:])):
        assert pen != plain


def test_logit_bias_bans_and_forces(setup):
    """OpenAI logit_bias through the batcher: -100 bans a token the plain
    greedy run emits; +100 on a chosen token forces it every step."""
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    prompt = [3, 1, 4, 1, 5]
    u = b.submit(prompt, 6)
    plain = {c.uid: c for c in b.run()}[u].tokens
    banned = plain[0]
    u2 = b.submit(prompt, 6, logit_bias={banned: -100.0})
    out = {c.uid: c for c in b.run()}[u2].tokens
    assert banned not in out, (banned, out)
    u3 = b.submit(prompt, 4, logit_bias={7: 100.0})
    forced = {c.uid: c for c in b.run()}[u3].tokens
    assert forced == [7, 7, 7, 7]
    with pytest.raises(ValueError, match="out of range"):
        b.submit(prompt, 2, logit_bias={10 ** 6: -1.0})


def test_logit_bias_generate_matches_batcher(setup):
    cfg, params = setup
    from pytorch_distributed_train_tpu.generate import (
        build_decode_model,
        generate,
    )

    prompt = [3, 1, 4, 1, 5]
    dm = build_decode_model(cfg, PrecisionConfig())
    ref = np.asarray(generate(dm, params,
                              jnp.asarray([prompt], jnp.int32), 6,
                              logit_bias={2: 100.0}))[0, len(prompt):]
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    u = b.submit(prompt, 6, logit_bias={2: 100.0})
    out = {c.uid: c for c in b.run()}[u].tokens
    assert out == ref.tolist() == [2] * 6


def test_batcher_first_token_unmoved_by_additive_penalties(setup):
    """OpenAI semantics (ADVICE r3): presence/frequency count generated
    tokens only, so the first sampled token matches the unpenalized
    greedy one even when the prompt is saturated with a single token."""
    cfg, params = setup
    prompt = [9] * 8
    b1 = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    u1 = b1.submit(prompt, 1)
    b2 = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    u2 = b2.submit(prompt, 1, presence_penalty=50.0,
                   frequency_penalty=10.0)
    t1 = {c.uid: c for c in b1.run()}[u1].tokens
    t2 = {c.uid: c for c in b2.run()}[u2].tokens
    assert t1 == t2


def test_batcher_additive_penalties_engage_on_generated(setup):
    """...but once tokens ARE generated, a strong presence penalty must
    forbid consecutive repeats (the generated-only context engages)."""
    cfg, params = setup
    prompt = [9] * 8
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    u = b.submit(prompt, 8, presence_penalty=50.0)
    toks = {c.uid: c for c in b.run()}[u].tokens
    assert all(a != b2 for a, b2 in zip(toks[:-1], toks[1:])), toks


def test_submit_rejects_out_of_range_logit_bias(setup):
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    with pytest.raises(ValueError, match=r"\[-100, 100\]"):
        b.submit([1, 2], 2, logit_bias={3: 150.0})
    with pytest.raises(ValueError, match=r"\[-100, 100\]"):
        b.submit([1, 2], 2, logit_bias={3: -101.0})


def test_seq2seq_logit_bias_applies_to_first_token():
    """The admission sampler must honor logit_bias from token one even
    when the batcher does not count the prompt (seq2seq): a -100 ban on
    the greedy first token forces a different first token."""
    from pytorch_distributed_train_tpu.serving import (
        Seq2SeqContinuousBatcher,
    )

    cfg = ModelConfig(name="t5", vocab_size=64, hidden_size=32,
                      num_layers=2, decoder_layers=2, num_heads=4,
                      mlp_dim=64, max_seq_len=32, dropout_rate=0.0)
    params = build_model(cfg, PrecisionConfig()).init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 6), jnp.int32), jnp.zeros((1, 2), jnp.int32),
        train=False)["params"]
    src = [5, 9, 12, 3]
    b = Seq2SeqContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    u = b.submit(src, 2)
    first = {c.uid: c for c in b.run()}[u].tokens[0]
    b2 = Seq2SeqContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    u2 = b2.submit(src, 2, logit_bias={int(first): -100.0})
    first_banned = {c.uid: c for c in b2.run()}[u2].tokens[0]
    assert first_banned != first


def test_filter_logits_array_matches_scalar_per_row():
    """The per-row top_p/min_p array path must equal the scalar path
    row-for-row (including disabled rows: out-of-range array entries =
    keep-all, exactly what scalar 0.0 does at trace time)."""
    from pytorch_distributed_train_tpu.generate import filter_logits

    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.standard_normal((3, 32)), jnp.float32)
    ps = [0.3, 0.9, 0.0]       # row 2: disabled
    ms = [0.0, 0.05, 0.2]      # row 0: disabled
    arr = filter_logits(
        logits, 1.0, 0,
        top_p=jnp.asarray(ps, jnp.float32)[:, None],
        min_p=jnp.asarray(ms, jnp.float32)[:, None])
    for i, (p, m) in enumerate(zip(ps, ms)):
        ref = filter_logits(logits[i:i + 1], 1.0, 0, top_p=p, min_p=m)
        np.testing.assert_array_equal(np.asarray(arr[i]),
                                      np.asarray(ref[0]))


def test_per_request_top_p_matches_server_wide(setup):
    """A request carrying top_p must sample exactly as a batcher whose
    SERVER-wide top_p is that value (same seed): the per-row operand is
    the same law, just scoped to the request."""
    cfg, params = setup
    prompt = [5, 9, 2, 14]
    rng = jax.random.PRNGKey(7)
    b_server = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1,
                                 top_p=0.5, rng=rng)
    u1 = b_server.submit(prompt, 6, temperature=1.3)
    t_server = {c.uid: c for c in b_server.run()}[u1].tokens
    b_req = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1,
                              rng=rng)
    u2 = b_req.submit(prompt, 6, temperature=1.3, top_p=0.5)
    t_req = {c.uid: c for c in b_req.run()}[u2].tokens
    assert t_server == t_req

    # and the override is per-REQUEST: the next (default) request on the
    # same batcher is NOT nucleus-filtered (equals a no-top_p run)
    b_plain = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1,
                                rng=rng)
    u3 = b_plain.submit(prompt, 6, temperature=1.3)
    t_plain = {c.uid: c for c in b_plain.run()}[u3].tokens
    u4 = b_req.submit(prompt, 6, temperature=1.3)
    t_after = {c.uid: c for c in b_req.run()}[u4].tokens
    # same batcher, fresh request, default settings — the row reset must
    # have cleared the 0.5 override (rng advanced, so compare against a
    # DISTRIBUTION property instead of exact tokens: the reset row uses
    # keep-all filtering, which the law test above pins; here just assert
    # the slot state went back to the server default)
    assert float(b_req._top_p[0]) == b_req.top_p
    assert t_plain is not None and t_after is not None


def test_submit_validates_top_p_range(setup):
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    with pytest.raises(ValueError, match="top_p"):
        b.submit([1, 2], 2, top_p=1.5)
    with pytest.raises(ValueError, match="min_p"):
        b.submit([1, 2], 2, min_p=-0.1)


def test_auto_prefix_forks_from_matching_template(setup):
    """auto_prefix_min: a submit whose prompt starts with a preloaded
    template's tokens forks from it automatically — output identical to
    the explicit-prefix fork AND to the no-template full prefill (greedy),
    with the prefill savings visible in stats."""
    cfg, params = setup
    system = [7, 3, 9, 11, 2, 5]
    turn = [4, 8, 1]
    b_plain = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    u0 = b_plain.submit(system + turn, 5)
    ref = {c.uid: c for c in b_plain.run()}[u0].tokens

    b_auto = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2,
                               auto_prefix_min=4)
    sid = b_auto.preload(system)
    u1 = b_auto.submit(system + turn, 5)  # no explicit prefix=
    got = {c.uid: c for c in b_auto.run()}[u1].tokens
    assert got == ref
    assert b_auto.stats["auto_prefix_hits"] == 1
    assert b_auto.stats["forks"] == 1
    assert sid in b_auto._parked  # template survives the fork


def test_auto_prefix_respects_min_and_exact_match(setup):
    cfg, params = setup
    short = [7, 3]
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2,
                          auto_prefix_min=4)
    b.preload(short)
    # template shorter than the threshold: no auto fork
    u = b.submit(short + [4, 8], 3)
    _ = {c.uid: c for c in b.run()}[u]
    assert b.stats["auto_prefix_hits"] == 0
    # prompt EXACTLY equal to a template: remainder would be empty —
    # no auto fork (fork ingest needs a token), plain prefill instead
    long = [7, 3, 9, 11, 2]
    b2 = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2,
                           auto_prefix_min=4)
    b2.preload(long)
    u2 = b2.submit(list(long), 3)
    _ = {c.uid: c for c in b2.run()}[u2]
    assert b2.stats["auto_prefix_hits"] == 0


def test_auto_prefix_prefers_longest_template(setup):
    cfg, params = setup
    a = [7, 3, 9, 11]
    ab = [7, 3, 9, 11, 2, 5, 13, 6]
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=3,
                          auto_prefix_min=2)
    b.preload(a)
    sid_long = b.preload(ab)
    u = b.submit(ab + [4, 8], 3)
    done = {c.uid: c for c in b.run()}[u]
    assert b.stats["auto_prefix_hits"] == 1
    # longest match wins: the fork ingested only [4, 8] (2 tokens) on
    # top of the 8-token template — visible via the trimmed prompt
    assert done.prompt == [4, 8]
    assert sid_long in b._parked


def test_auto_prefix_bypassed_for_repetition_penalty(setup):
    """repetition_penalty != 1.0 skips the auto-prefix match: the
    rewrite would truncate the penalty context to the remainder, so the
    same request would sample differently depending on whether a
    template happened to be parked. Presence/frequency (generated-only)
    and logit_bias (context-free) still auto-fork."""
    cfg, params = setup
    system = [7, 3, 9, 11, 2, 5]
    turn = [4, 8, 1, 4, 8, 1]
    # ground truth: penalized full-prompt decode, no templates anywhere
    b_ref = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    u0 = b_ref.submit(system + turn, 6, repetition_penalty=1.7)
    ref = {c.uid: c for c in b_ref.run()}[u0].tokens

    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2,
                          auto_prefix_min=4)
    b.preload(system)
    u1 = b.submit(system + turn, 6, repetition_penalty=1.7)
    got = {c.uid: c for c in b.run()}[u1].tokens
    assert got == ref  # identical law whether or not a template parked
    assert b.stats["auto_prefix_hits"] == 0  # the match was bypassed
    # generated-only penalties keep the optimization
    u2 = b.submit(system + turn, 4, presence_penalty=0.5)
    _ = {c.uid: c for c in b.run()}[u2]
    assert b.stats["auto_prefix_hits"] == 1


def test_auto_prefix_off_by_default(setup):
    cfg, params = setup
    system = [7, 3, 9, 11, 2, 5]
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2)
    b.preload(system)
    u = b.submit(system + [4], 3)
    _ = {c.uid: c for c in b.run()}[u]
    assert b.stats["auto_prefix_hits"] == 0
    assert b.stats["forks"] == 0


def test_seeded_request_reproduces_across_batch_compositions(setup):
    """OpenAI `seed`: a seeded request's sampled output is identical
    whether it runs alone or beside unrelated traffic (per-row key chain
    — independent of slot assignment, step rng, and neighbors)."""
    cfg, params = setup
    prompt = [5, 9, 2, 14]
    b_alone = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=3,
                                rng=jax.random.PRNGKey(1))
    u = b_alone.submit(prompt, 6, temperature=1.2, seed=42)
    alone = {c.uid: c for c in b_alone.run()}[u].tokens

    b_busy = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=3,
                               rng=jax.random.PRNGKey(999))
    # unrelated traffic first: the seeded request lands in a DIFFERENT
    # slot with a different shared-rng history
    b_busy.submit([3, 3, 8, 1, 12], 9, temperature=0.9)
    b_busy.submit([6, 6], 4, temperature=1.5)
    u2 = b_busy.submit(prompt, 6, temperature=1.2, seed=42)
    busy = {c.uid: c for c in b_busy.run()}[u2].tokens
    assert alone == busy

    # different seed → (overwhelmingly) different trajectory
    b3 = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=3,
                           rng=jax.random.PRNGKey(1))
    u3 = b3.submit(prompt, 6, temperature=1.2, seed=43)
    other = {c.uid: c for c in b3.run()}[u3].tokens
    assert other != alone


def test_seed_with_greedy_is_inert(setup):
    cfg, params = setup
    prompt = [5, 9, 2]
    b1 = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    u1 = b1.submit(prompt, 4, seed=7)  # temperature 0: greedy
    b2 = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    u2 = b2.submit(prompt, 4)
    assert {c.uid: c for c in b1.run()}[u1].tokens == \
        {c.uid: c for c in b2.run()}[u2].tokens


def test_seq2seq_seeded_request_reproduces(setup):
    """seed/top_p thread through the shared admission path for the t5
    batcher too — same seeded request, same output, different traffic."""
    from pytorch_distributed_train_tpu.serving import (
        Seq2SeqContinuousBatcher,
    )

    cfg = ModelConfig(name="t5", vocab_size=64, hidden_size=32,
                      num_layers=2, decoder_layers=2, num_heads=4,
                      mlp_dim=64, max_seq_len=32, dropout_rate=0.0)
    params = build_model(cfg, PrecisionConfig()).init(
        {"params": jax.random.PRNGKey(0)},
        jnp.zeros((1, 6), jnp.int32), jnp.zeros((1, 2), jnp.int32),
        train=False)["params"]
    src = [5, 9, 12, 3]
    b1 = Seq2SeqContinuousBatcher(cfg, PrecisionConfig(), params, slots=2,
                                  rng=jax.random.PRNGKey(3))
    u1 = b1.submit(src, 6, temperature=1.1, seed=11, top_p=0.9)
    alone = {c.uid: c for c in b1.run()}[u1].tokens
    b2 = Seq2SeqContinuousBatcher(cfg, PrecisionConfig(), params, slots=2,
                                  rng=jax.random.PRNGKey(77))
    b2.submit([8, 2, 4], 9, temperature=0.7)  # neighbor traffic
    u2 = b2.submit(src, 6, temperature=1.1, seed=11, top_p=0.9)
    busy = {c.uid: c for c in b2.run()}[u2].tokens
    assert alone == busy


class TestSpeculativeServing:
    """Prompt-lookup speculative serving (spec_k > 0): per-row n-gram
    proposals verified in one (slots, k+1) forward."""

    def _mk(self, setup, **kw):
        cfg, params = setup
        return ContinuousBatcher(cfg, PrecisionConfig(), params, **kw)

    def test_greedy_parity_mixed_slots(self, setup):
        """Greedy outputs under speculation equal the plain batcher's,
        token-for-token, across mixed repetitive/random prompts with
        different budgets finishing at different times."""
        reqs = [([7, 8, 9, 7, 8, 9, 7, 8, 9, 7, 8], 10),
                ([5, 9, 2, 14, 3], 6),
                ([4, 4, 1, 4, 4, 1, 4, 4], 8)]
        plain = self._mk(setup, slots=3)
        uids = [plain.submit(p, n) for p, n in reqs]
        ref = {c.uid: c.tokens for c in plain.run()}
        spec = self._mk(setup, slots=3, spec_k=4, spec_ngram=3)
        uids2 = [spec.submit(p, n) for p, n in reqs]
        got = {c.uid: c.tokens for c in spec.run()}
        for u1, u2 in zip(uids, uids2):
            assert ref[u1] == got[u2], (ref[u1], got[u2])
        assert spec.stats["spec_rounds"] >= 1
        assert spec.stats["generated_tokens"] == sum(n for _, n in reqs)

    def test_greedy_parity_matches_lockstep_generate(self, setup):
        from pytorch_distributed_train_tpu.generate import (
            build_decode_model,
            generate,
        )

        cfg, params = setup
        prompt = [6, 2, 6, 2, 6, 2, 6, 2]
        n = 9
        dm = build_decode_model(cfg, PrecisionConfig())
        ref = np.asarray(generate(
            dm, params, jnp.asarray([prompt], jnp.int32),
            n))[0, len(prompt):].tolist()
        b = self._mk(setup, slots=2, spec_k=3, spec_ngram=2)
        u = b.submit(prompt, n)
        got = {c.uid: c for c in b.run()}[u]
        assert got.tokens == ref
        # logprobs parallel the tokens and are finite raw-law values
        assert len(got.logprobs) == len(got.tokens)
        assert all(lp <= 0.0 for lp in got.logprobs)

    def test_eos_and_sessions_under_speculation(self, setup):
        """EOS mid-acceptance trims exactly like the plain path, and a
        kept session parked under speculation resumes correctly (the
        rider-token invariant holds when the rider's KV is already in
        the cache)."""
        cfg, params = setup
        prompt = [3, 11, 3, 11, 3, 11, 3]
        plain = self._mk(setup, slots=2)
        u1 = plain.submit(prompt, 6, keep=True)
        c1 = {c.uid: c for c in plain.run()}[u1]
        u1b = plain.submit([9, 1], 5, session=c1.session)
        ref = {c.uid: c for c in plain.run()}[u1b].tokens

        spec = self._mk(setup, slots=2, spec_k=3, spec_ngram=2)
        u2 = spec.submit(prompt, 6, keep=True)
        c2 = {c.uid: c for c in spec.run()}[u2]
        assert c2.tokens == c1.tokens
        u2b = spec.submit([9, 1], 5, session=c2.session)
        got = {c.uid: c for c in spec.run()}[u2b].tokens
        assert got == ref

        # eos parity
        eos = c1.tokens[0]  # force an early stop on a token we know comes
        p3 = self._mk(setup, slots=1)
        u3 = p3.submit(prompt, 6, eos_id=eos)
        r3 = {c.uid: c for c in p3.run()}[u3]
        s3 = self._mk(setup, slots=1, spec_k=3, spec_ngram=2)
        u4 = s3.submit(prompt, 6, eos_id=eos)
        r4 = {c.uid: c for c in s3.run()}[u4]
        assert r3.tokens == r4.tokens
        assert r3.finish_reason == r4.finish_reason == "eos"

    def test_penalized_spec_matches_penalized_plain(self, setup):
        """Penalties/logit_bias COMPOSE with speculation: the penalized
        accept kernel advances each row's count context per accepted
        draft, so greedy outputs match the penalized plain batcher
        token-for-token — including rounds that commit several tokens
        (the mid-acceptance count-bump subtlety)."""
        reqs = [
            (([7, 8, 9] * 5)[:13], 10, dict(repetition_penalty=1.8)),
            ([5, 9, 2, 14, 3, 5, 9, 2, 14], 8,
             dict(presence_penalty=0.9, frequency_penalty=0.4)),
            ([4, 4, 1] * 4, 8, dict(logit_bias={4: -8.0, 9: 3.0})),
            ([6, 2, 6, 2, 6, 2], 6, {}),  # unpenalized neighbor
        ]
        plain = self._mk(setup, slots=4)
        uids = [plain.submit(p, n, **kw) for p, n, kw in reqs]
        ref = {c.uid: c.tokens for c in plain.run()}
        spec = self._mk(setup, slots=4, spec_k=4, spec_ngram=3)
        uids2 = [spec.submit(p, n, **kw) for p, n, kw in reqs]
        got = {c.uid: c.tokens for c in spec.run()}
        for u1, u2 in zip(uids, uids2):
            assert ref[u1] == got[u2], (ref[u1], got[u2])

    def test_penalized_spec_matches_lockstep_generate(self, setup):
        cfg, params = setup
        prompt = [6, 2, 6, 2, 6, 2, 6, 2, 6, 2]
        n = 9
        dm = build_decode_model(cfg, PrecisionConfig())
        ref = np.asarray(generate(
            dm, params, jnp.asarray([prompt], jnp.int32), n,
            repetition_penalty=1.6,
            presence_penalty=0.3))[0, len(prompt):].tolist()
        b = self._mk(setup, slots=2, spec_k=3, spec_ngram=2)
        u = b.submit(prompt, n, repetition_penalty=1.6,
                     presence_penalty=0.3)
        got = {c.uid: c for c in b.run()}[u]
        assert got.tokens == ref
        assert len(got.logprobs) == len(got.tokens)
        assert all(lp <= 0.0 for lp in got.logprobs)

    def test_penalized_rows_actually_accept_drafts(self, setup):
        """Proof the mid-acceptance count-advance path executes: a
        logit_bias-pinned row (bias +100 forces one token, making
        generation periodic — the regime prompt lookup wins) routed
        through the PENALIZED kernel accepts drafts, and its output
        still matches the penalized plain batcher. (Repetition-penalized
        rows legitimately reject most proposals — the penalty fights
        the repetition the lookup bets on — so acceptance must be
        proven on a row where the two cooperate.)"""
        cfg, params = setup
        prompt = [5, 5, 5, 5, 5]
        kw = dict(logit_bias={5: 100.0}, presence_penalty=0.2)
        plain = self._mk(setup, slots=1)
        u0 = plain.submit(prompt, 8, **kw)
        ref = {c.uid: c for c in plain.run()}[u0].tokens
        b = self._mk(setup, slots=1, spec_k=3, spec_ngram=2)
        u = b.submit(prompt, 8, **kw)
        got = {c.uid: c for c in b.run()}[u].tokens
        assert got == ref == [5] * 8
        # the only row is penalized+biased → every accepted draft came
        # from the penalized accept kernel's count-advanced law
        assert b.stats.get("spec_accepted", 0) >= 1

    def test_seeded_penalized_reproduces_under_speculation(self, setup):
        """A seeded, penalized, SAMPLED request under speculation is
        batch-composition independent (same contract as the plain
        path)."""
        prompt = [7, 8, 9, 7, 8, 9, 7, 8]
        kw = dict(temperature=1.1, seed=21, repetition_penalty=1.4)
        b1 = self._mk(setup, slots=2, spec_k=3, spec_ngram=2,
                      rng=jax.random.PRNGKey(5))
        u1 = b1.submit(prompt, 6, **kw)
        alone = {c.uid: c for c in b1.run()}[u1].tokens
        b2 = self._mk(setup, slots=2, spec_k=3, spec_ngram=2,
                      rng=jax.random.PRNGKey(777))
        b2.submit([2, 12, 4], 8, temperature=0.8)
        u2 = b2.submit(prompt, 6, **kw)
        busy = {c.uid: c for c in b2.run()}[u2].tokens
        assert alone == busy

    def test_preload_fork_parity_under_speculation(self, setup):
        """A preloaded template survives speculative traffic intact:
        every spec round re-pins ALL rows (the template included) to
        _pos, so preload must record the template's true position — a
        stale 0 would let each verify write k+1 garbage K/V entries
        INTO the template content, corrupting every later fork."""
        cfg, params = setup
        template = [3, 14, 15, 9, 2, 6]
        tail = [5, 3, 5, 3, 5]
        ref = _reference(cfg, params, template + tail, 7)

        spec = self._mk(setup, slots=3, spec_k=3, spec_ngram=2)
        sid = spec.preload(template)
        # spec traffic while the template is parked: rounds re-pin its
        # row every step — with the fix its writes stay beyond the
        # template's content
        u0 = spec.submit([7, 8, 9, 7, 8, 9, 7, 8], 10)
        _ = {c.uid: c for c in spec.run()}
        u1 = spec.submit(tail, 7, prefix=sid)
        got = {c.uid: c for c in spec.run()}[u1]
        assert got.tokens == ref
        # and the template keeps serving (fork, not consume)
        u2 = spec.submit(tail, 7, prefix=sid)
        got2 = {c.uid: c for c in spec.run()}[u2]
        assert got2.tokens == ref

    def test_preload_enforces_spec_headroom(self, setup):
        """preload rejects templates whose pinned-row verify writes
        could clamp back into template content (len + spec_k + 1 must
        fit max_seq_len)."""
        cfg, _ = setup
        b = self._mk(setup, slots=1, spec_k=3)
        with pytest.raises(ValueError, match="spec margin"):
            b.preload(list(range(2, 2 + cfg.max_seq_len - 3)))
        # same length is fine without speculation
        b2 = self._mk(setup, slots=1)
        b2.preload([2] * (cfg.max_seq_len - 3))

    def test_host_device_time_split_exposed(self, setup):
        b = self._mk(setup, slots=2, spec_k=3, spec_ngram=2)
        b.submit([7, 8, 9, 7, 8, 9, 7], 6)
        list(b.run())
        assert b.stats["device_ms"] > 0.0
        assert b.stats["host_ms"] >= 0.0
        assert b.stats["admit_ms"] > 0.0


def test_ngram_index_matches_rescan_proposals():
    """The incremental per-row n-gram index proposes EXACTLY what the
    O(context) backward rescan (speculative.propose_from_context)
    proposes, at every step of random token streams — the index is a
    pure speedup, not a semantics change."""
    from pytorch_distributed_train_tpu.serving import (
        _ngram_append,
        _ngram_build,
        _ngram_propose,
    )
    from pytorch_distributed_train_tpu.speculative import (
        propose_from_context,
    )

    rng = np.random.default_rng(7)
    for ngram, k, vocab in ((2, 3, 4), (3, 4, 3), (1, 2, 5)):
        base = [int(t) for t in rng.integers(0, vocab, 6)]
        ctx = list(base)
        idx = _ngram_build(ctx, ngram)
        for step in range(60):
            assert _ngram_propose(ctx, idx, ngram, k) == \
                propose_from_context(ctx, k, ngram), \
                (ngram, k, step, ctx)
            _ngram_append(ctx, idx, int(rng.integers(0, vocab)), ngram)


def test_seed_range_validated(setup):
    cfg, params = setup
    b = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=1)
    for bad in (-1, -5, 2**32):
        with pytest.raises(ValueError, match="seed"):
            b.submit([1, 2, 3], 4, seed=bad)
    b.submit([1, 2, 3], 4, seed=2**32 - 1)  # boundary ok


def test_seeded_sampling_reproduces_under_speculation(setup):
    """Unpenalized seeded sampling under speculation stays
    batch-composition independent (module-level twin of the in-class
    penalized variant)."""
    cfg, params = setup
    prompt = [7, 8, 9, 7, 8, 9, 7, 8]
    b1 = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2,
                           spec_k=3, spec_ngram=2,
                           rng=jax.random.PRNGKey(5))
    u1 = b1.submit(prompt, 6, temperature=1.1, seed=21)
    alone = {c.uid: c for c in b1.run()}[u1].tokens
    b2 = ContinuousBatcher(cfg, PrecisionConfig(), params, slots=2,
                           spec_k=3, spec_ngram=2,
                           rng=jax.random.PRNGKey(777))
    b2.submit([2, 12, 4], 8, temperature=0.8)
    u2 = b2.submit(prompt, 6, temperature=1.1, seed=21)
    busy = {c.uid: c for c in b2.run()}[u2].tokens
    assert alone == busy
