"""Fused epilogues (ISSUE 14, ops/fused_update.py) vs their oracles:

- the one-pass optimizer epilogue must be BIT-IDENTICAL to the optax
  chain make_optimizer builds for the same config — params and the full
  opt_state (counters, moments, the sentinel LR-cooldown leaf), gated
  and ungated;
- the fused model-block epilogues (bias+GELU, residual+LayerNorm) must
  be bit-identical to the nn.Dense/nn.LayerNorm formulation with an
  unchanged param tree;
- the CPU AOT A/B (tools/aot_ab.py arms) must show the fused epilogue
  touching no more bytes than the chain.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_train_tpu.config import (
    ModelConfig,
    OptimConfig,
    PrecisionConfig,
)
from pytorch_distributed_train_tpu.models.registry import build_model
from pytorch_distributed_train_tpu.optim import (
    fused_update_unsupported_reason,
    make_fused_update,
    make_optimizer,
)


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "layer1": {
            "kernel": jnp.asarray(rng.standard_normal((4, 5)), jnp.float32),
            "bias": jnp.asarray(rng.standard_normal(5), jnp.float32),
        },
        "scale": jnp.asarray(rng.standard_normal(5), jnp.float32),
    }


CASES = {
    "adamw_full": OptimConfig(
        name="adamw", learning_rate=1e-3, schedule="cosine",
        warmup_steps=2, weight_decay=0.01, grad_clip_norm=1.0,
        decay_exclude=r"bias$,scale$"),
    "adamw_plain": OptimConfig(
        name="adamw", learning_rate=1e-3, schedule="constant",
        warmup_steps=0, weight_decay=0.0),
    "adam_coupled_wd": OptimConfig(
        name="adam", learning_rate=1e-3, schedule="constant",
        warmup_steps=0, weight_decay=0.01),
    "momentum_nesterov": OptimConfig(
        name="momentum", learning_rate=0.1, momentum=0.9, nesterov=True,
        schedule="cosine", warmup_steps=0, weight_decay=5e-4,
        grad_clip_norm=1.0),
    "sgd_plain": OptimConfig(
        name="sgd", learning_rate=0.1, momentum=0.0, schedule="constant",
        warmup_steps=0, weight_decay=0.0),
    "adamw_bf16_moments": OptimConfig(
        name="adamw", learning_rate=1e-3, schedule="constant",
        warmup_steps=0, weight_decay=0.01, moment_dtype="bfloat16"),
}


def _assert_trees_identical(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        x, y = jnp.asarray(x), jnp.asarray(y)
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _run_both(opt_cfg, sentinel=False, gate_pattern=None, steps=4):
    """Drive the optax chain and the fused epilogue over the same grad
    stream UNDER JIT (the deployment regime — both paths then lower
    through the same XLA pipeline, which is the bit-identity contract)
    and return both final (params, opt_state)."""
    tx, sched = make_optimizer(opt_cfg, total_steps=100,
                               sentinel_cooldown=sentinel)
    fe = make_fused_update(opt_cfg, sched, sentinel_cooldown=sentinel)
    params = _tree()
    state = tx.init(params)
    if sentinel:
        # nontrivial LR-cooldown leaf: the rewind path scaled it down
        from pytorch_distributed_train_tpu.sentinel.numeric import (
            scale_cooldown,
        )

        state = scale_cooldown(state, 0.5)

    @jax.jit
    def chain_step(p, s, g, finite):
        u, s2 = tx.update(g, s, p)
        p2 = optax.apply_updates(p, u)
        return jax.tree.map(lambda a, b: jnp.where(finite, a, b),
                            (p2, s2), (p, s))

    @jax.jit
    def fused_step(p, s, g, finite):
        p2, s2, _ = fe(g, s, p, finite=finite)
        return p2, s2

    rng = np.random.default_rng(7)
    p1 = p2 = params
    s1 = s2 = state
    for i in range(steps):
        grads = jax.tree.map(
            lambda p: jnp.asarray(rng.standard_normal(p.shape),
                                  jnp.float32), params)
        finite = jnp.bool_(
            True if gate_pattern is None else gate_pattern[i])
        p1, s1 = chain_step(p1, s1, grads, finite)
        p2, s2 = fused_step(p2, s2, grads, finite)
    return (p1, s1), (p2, s2)


@pytest.mark.parametrize("case", sorted(CASES))
def test_fused_epilogue_bit_identical_to_chain(case):
    (p1, s1), (p2, s2) = _run_both(CASES[case])
    _assert_trees_identical(p1, p2)
    _assert_trees_identical(s1, s2)


def test_fused_epilogue_gate_and_cooldown_leaf():
    """Gated steps (the sentinel/GradScaler skip) and the LR-cooldown
    chain link: fused == chain bit-for-bit including the skipped steps'
    untouched counters and the cooldown scale's effect on updates."""
    (p1, s1), (p2, s2) = _run_both(
        CASES["adamw_full"], sentinel=True,
        gate_pattern=[True, False, True, True])
    _assert_trees_identical(p1, p2)
    _assert_trees_identical(s1, s2)
    # the gate really skipped: counts advanced 3 times, not 4
    counts = [np.asarray(s) for s in jax.tree.leaves(s1)
              if np.asarray(s).dtype == np.int32]
    assert counts and all(int(c) == 3 for c in counts)


def test_fused_epilogue_in_train_step_matches_chain(devices8):
    """End-to-end: a jitted train step with the fused epilogue produces
    the SAME params as the chain path (same batch, same rng)."""
    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.config import MeshConfig
    from pytorch_distributed_train_tpu.losses import get_loss_fn
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh
    from pytorch_distributed_train_tpu.parallel.partition import (
        rules_for_model,
    )
    from pytorch_distributed_train_tpu.train_state import TrainState

    mesh = build_mesh(MeshConfig(data=8), devices8)
    model_cfg = ModelConfig(name="vit_b16", num_classes=10, image_size=8,
                            patch_size=4, hidden_size=32, num_layers=2,
                            num_heads=4, mlp_dim=64, dropout_rate=0.0)
    opt_cfg = CASES["adamw_full"]
    model = build_model(model_cfg, PrecisionConfig())
    tx, sched = make_optimizer(opt_cfg, total_steps=100)
    rules = rules_for_model("vit_b16")

    def init_state(rng):
        variables = model.init({"params": rng}, jnp.zeros((2, 8, 8, 3)),
                               train=False)
        return TrainState.create(params=variables["params"], tx=tx)

    shape = jax.eval_shape(init_state, jax.random.PRNGKey(0))
    sharding = steps_lib.state_shardings(mesh, rules, shape)
    rng = np.random.default_rng(0)
    batch = {"image": jnp.asarray(rng.standard_normal((16, 8, 8, 3)),
                                  jnp.float32),
             "label": jnp.asarray(rng.integers(0, 10, 16), jnp.int32)}
    results = {}
    for fused in (False, True):
        fe = make_fused_update(opt_cfg, sched) if fused else None
        step = steps_lib.jit_train_step(
            steps_lib.make_train_step(model, get_loss_fn("softmax_xent"),
                                      tx, fused_update=fe),
            mesh, sharding)
        state = jax.jit(init_state, out_shardings=sharding)(
            jax.random.PRNGKey(0))
        for _ in range(2):
            state, metrics = step(state, batch, jax.random.PRNGKey(1))
        results[fused] = (jax.device_get(state.params),
                          jax.device_get(state.opt_state))
    _assert_trees_identical(results[False][0], results[True][0])
    _assert_trees_identical(results[False][1], results[True][1])


def test_fused_unsupported_reasons():
    assert fused_update_unsupported_reason(
        OptimConfig(name="lamb")) is not None
    assert fused_update_unsupported_reason(
        OptimConfig(name="adafactor")) is not None
    assert fused_update_unsupported_reason(
        OptimConfig(name="adamw", plateau_factor=0.5)) is not None
    assert fused_update_unsupported_reason(
        OptimConfig(name="adamw", accum_steps=4)) is not None
    assert fused_update_unsupported_reason(
        OptimConfig(name="adamw", layer_lr_decay=0.9)) is not None
    assert fused_update_unsupported_reason(
        OptimConfig(name="adamw"), has_param_mask=True) is not None
    assert fused_update_unsupported_reason(
        OptimConfig(name="adamw", grad_clip_norm=1.0,
                    decay_exclude=r"bias$")) is None
    with pytest.raises(ValueError, match="fused_epilogue"):
        make_fused_update(OptimConfig(name="lamb"), lambda c: 1e-3)


# ---------------------------------------------------------------- models


def _model_outputs(name, fused, dtype="float32", **kw):
    cfg = ModelConfig(name=name, fused_epilogues=fused, **kw)
    model = build_model(cfg, PrecisionConfig(compute_dtype=dtype))
    rng = np.random.default_rng(3)
    if name.startswith("vit"):
        inputs = (jnp.asarray(rng.standard_normal((2, 16, 16, 3)),
                              jnp.float32),)
    else:
        inputs = (jnp.asarray(rng.integers(0, 50, (2, 12)), jnp.int32),
                  jnp.ones((2, 12), jnp.int32))
    variables = model.init({"params": jax.random.PRNGKey(0)}, *inputs,
                           train=False)
    return variables["params"], model.apply(variables, *inputs,
                                            train=False)


VIT_KW = dict(num_classes=10, image_size=16, patch_size=4, hidden_size=32,
              num_layers=2, num_heads=4, mlp_dim=64)
BERT_KW = dict(vocab_size=50, hidden_size=32, num_layers=2, num_heads=4,
               mlp_dim=64, max_seq_len=16)


@pytest.mark.parametrize("name,kw", [("vit_b16", VIT_KW),
                                     ("bert_base", BERT_KW)])
@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_fused_block_epilogues_bit_identical(name, kw, dtype):
    """model.fused_epilogues: same param tree (names, shapes, init
    bits), same outputs (bias+GELU and residual+LayerNorm replicate the
    nn.Dense/nn.LayerNorm math exactly)."""
    p_ref, out_ref = _model_outputs(name, False, dtype=dtype, **kw)
    p_fused, out_fused = _model_outputs(name, True, dtype=dtype, **kw)
    assert jax.tree_util.tree_structure(p_ref) == \
        jax.tree_util.tree_structure(p_fused)
    _assert_trees_identical(p_ref, p_fused)
    np.testing.assert_array_equal(np.asarray(out_ref),
                                  np.asarray(out_fused))


def test_no_fused_epilogue_remat_policy():
    """remat_policy='no_fused_epilogue' composes with the fused blocks
    (the tag is its handle) and leaves gradients equal to the unfused
    formulation's."""
    grads = {}
    for fused in (False, True):
        cfg = ModelConfig(name="bert_base", fused_epilogues=fused,
                          remat=True,
                          remat_policy="no_fused_epilogue" if fused
                          else "full", **BERT_KW)
        model = build_model(cfg, PrecisionConfig())
        rng = np.random.default_rng(3)
        ids = (jnp.asarray(rng.integers(0, 50, (2, 12)), jnp.int32),
               jnp.ones((2, 12), jnp.int32))
        variables = model.init({"params": jax.random.PRNGKey(0)}, *ids,
                               train=False)

        def loss(p):
            return jnp.sum(
                model.apply({"params": p}, *ids, train=False) ** 2)

        grads[fused] = jax.jit(jax.grad(loss))(variables["params"])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-5,
                                                atol=1e-5),
        jax.device_get(grads[False]), jax.device_get(grads[True]))


# ------------------------------------------------------------- CPU AOT A/B


def test_aot_epilogue_bytes_accessed():
    """Tier-1 CPU AOT smoke (ala AOT_AB.json): the fused-epilogue train
    step's cost_analysis bytes-accessed must not exceed the chain
    step's — the one-pass epilogue reads/writes the grad tree once."""
    from tools.aot_ab import _compile_epilogue_arm

    chain = _compile_epilogue_arm(True, False)
    fused = _compile_epilogue_arm(True, True)
    assert fused.get("ok", True) and chain.get("ok", True), (chain, fused)
    assert fused["gbytes_accessed"] <= chain["gbytes_accessed"], \
        (chain, fused)


def test_fused_momentum_zero_keeps_fp32_trace():
    """momentum=0.0 + moment_dtype: the chain's accumulator_dtype uses
    a TRUTHINESS check (0.0 -> fp32 trace) — the fused path must mirror
    it, not narrow the trace to bf16."""
    cfg = OptimConfig(name="momentum", learning_rate=0.1, momentum=0.0,
                      schedule="constant", warmup_steps=0,
                      weight_decay=0.0, moment_dtype="bfloat16")
    (p1, s1), (p2, s2) = _run_both(cfg)
    _assert_trees_identical(p1, p2)
    _assert_trees_identical(s1, s2)
