"""Unit tests for the unified observability layer (obs/): span nesting,
ring overflow, Chrome trace schema, Prometheus exposition format, goodput
bucket arithmetic, cross-host summarize, and the /metrics sidecar. All
CPU-only plain-python — no Trainer, no device work (the e2e wiring test
lives in test_observability.py)."""

import json
import threading
import urllib.request

import pytest

from pytorch_distributed_train_tpu.obs.cluster import summarize
from pytorch_distributed_train_tpu.obs.goodput import BUCKETS, GoodputTracker
from pytorch_distributed_train_tpu.obs.registry import (
    Histogram,
    MetricsRegistry,
    sanitize_name,
)
from pytorch_distributed_train_tpu.obs.spans import SpanRecorder


# ------------------------------------------------------------------ spans
def test_span_nesting_records_depth_and_thread():
    rec = SpanRecorder(capacity=16, feed_registry=False)
    with rec.span("outer"):
        assert rec.active() == ["outer"]
        with rec.span("inner", step=7):
            assert rec.active() == ["outer", "inner"]
    evs = rec.events()
    # completion order: inner closes before outer
    assert [s.name for s in evs] == ["inner", "outer"]
    inner, outer = evs
    assert inner.depth == 1 and outer.depth == 0
    assert inner.args == {"step": 7}
    assert inner.thread == threading.current_thread().name
    assert 0.0 <= inner.dur_s <= outer.dur_s


def test_span_ring_overflow_keeps_latest():
    rec = SpanRecorder(capacity=4, feed_registry=False)
    for i in range(10):
        with rec.span(f"s{i}"):
            pass
    evs = rec.events()
    assert len(evs) == 4
    assert [s.name for s in evs] == ["s6", "s7", "s8", "s9"]
    assert rec.n == 10


def test_span_exception_flagged_and_rering():
    rec = SpanRecorder(capacity=8, feed_registry=False)
    with pytest.raises(RuntimeError):
        with rec.span("boom"):
            raise RuntimeError("x")
    (sp,) = rec.events()
    assert sp.args.get("error") is True


def test_chrome_trace_schema(tmp_path):
    rec = SpanRecorder(capacity=8, feed_registry=False)
    with rec.span("a"):
        with rec.span("b", k="v"):
            pass
    path = rec.dump_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        trace = json.load(f)  # must be loadable JSON
    evs = trace["traceEvents"]
    assert len(evs) == 2
    for e in evs:
        assert e["ph"] == "X"
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert isinstance(e["pid"], int) and e["tid"]
    assert {e["name"] for e in evs} == {"a", "b"}


def test_spans_threadsafe_nesting():
    rec = SpanRecorder(capacity=64, feed_registry=False)
    errs = []

    def worker(tag):
        try:
            for _ in range(5):
                with rec.span(f"{tag}.outer"):
                    with rec.span(f"{tag}.inner"):
                        pass
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(f"t{i}",)) for i in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert not errs
    assert rec.n == 40
    # per-thread stacks: every inner span has depth 1, outer 0
    for s in rec.events():
        assert s.depth == (1 if s.name.endswith(".inner") else 0)


# --------------------------------------------------------------- registry
def _parse_prom(text: str) -> dict[str, float]:
    """Minimal Prometheus text-format parser: {series_line: value}."""
    out = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE ")), line
            continue
        name_labels, value = line.rsplit(" ", 1)
        assert " " not in name_labels.split("{")[0]
        out[name_labels] = float(value)
    return out


def test_registry_counter_gauge_render():
    reg = MetricsRegistry()
    reg.counter("requests_total", labels={"path": "/x"}).inc()
    reg.counter("requests_total", labels={"path": "/x"}).inc(2)
    reg.gauge("loss").set(1.5)
    series = _parse_prom(reg.render())
    assert series['requests_total{path="/x"}'] == 3.0
    assert series["loss"] == 1.5


def test_registry_histogram_exposition_semantics():
    reg = MetricsRegistry()
    h = reg.histogram("train_step_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 500.0):
        h.observe(v)
    series = _parse_prom(reg.render())
    # cumulative le buckets, +Inf == count, sum matches
    assert series['train_step_seconds_bucket{le="0.1"}'] == 1
    assert series['train_step_seconds_bucket{le="1.0"}'] == 3
    assert series['train_step_seconds_bucket{le="10.0"}'] == 4
    assert series['train_step_seconds_bucket{le="+Inf"}'] == 5
    assert series["train_step_seconds_count"] == 5
    assert series["train_step_seconds_sum"] == pytest.approx(506.05)


def test_registry_kind_conflict_and_sanitize():
    reg = MetricsRegistry()
    reg.counter("x_total")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total")
    assert sanitize_name("grad_norm/encoder.block-0") == \
        "grad_norm_encoder_block_0"
    reg.set_from_mapping({"a/b": 1.0, "text": "skip", "n": 2}, prefix="train")
    series = _parse_prom(reg.render())
    assert series["train_a_b"] == 1.0
    assert series["train_n"] == 2.0
    assert not any("text" in k for k in series)


# ---------------------------------------------------------------- goodput
def test_goodput_buckets_sum_to_wall():
    gp = GoodputTracker()
    gp.account("init", 0.5)
    gp.account("compile", 1.0)
    with gp.measure("step"):
        pass
    gp.account("step", 2.0)
    gp.account("ckpt", 0.25)
    snap = gp.snapshot(now=gp.t0 + 10.0)
    total = sum(v for k, v in snap.items() if k.startswith("goodput_s_"))
    assert total == pytest.approx(snap["goodput_wall_s"], rel=0.05)
    assert snap["goodput_pct"] == pytest.approx(100.0 * snap["goodput_s_step"]
                                                / 10.0, abs=0.1)
    assert set(f"goodput_s_{b}" for b in BUCKETS) <= set(snap)


def test_goodput_idle_never_negative_and_idle_unaccountable():
    gp = GoodputTracker()
    gp.account("step", 100.0)  # more than wall: clock skew must not crash
    snap = gp.snapshot(now=gp.t0 + 1.0)
    assert snap["goodput_s_idle"] == 0.0
    with pytest.raises(ValueError):
        gp.account("idle", 1.0)


# ---------------------------------------------------------------- cluster
def test_cluster_summarize_single_host_degenerate():
    out = summarize({"step_time_p50": 12.5, "input_stall_pct": 1.0},
                    process_index=0, process_count=1)
    assert out["step_time_p50_min"] == out["step_time_p50_max"] == 12.5
    assert out["step_time_p50_med"] == 12.5
    assert out["step_time_p50_max_host"] == 0
    assert out["input_stall_pct_max"] == 1.0
    # fixed schema: 4 keys per input key
    assert len(out) == 8


# --------------------------------------------------------------- watchdog
def test_flight_recorder_dump_includes_attached_spans():
    import io

    from pytorch_distributed_train_tpu.utils.watchdog import FlightRecorder

    fr = FlightRecorder(capacity=8)
    sp = SpanRecorder(capacity=8, feed_registry=False)
    fr.attach_spans(sp)
    with sp.span("checkpoint.save", step=3):
        pass
    fr.record("step", 3)
    out = io.StringIO()
    fr.dump(out)
    text = out.getvalue()
    assert "flight recorder" in text
    assert "trace spans" in text and "checkpoint.save" in text


# ------------------------------------------------------------- exposition
def test_metrics_server_scrape_parses():
    from pytorch_distributed_train_tpu.obs.exposition import MetricsServer
    from pytorch_distributed_train_tpu.obs.registry import get_registry

    get_registry().gauge("scrape_probe").set(42.0)
    srv = MetricsServer(-1)  # ephemeral port
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            body = r.read().decode()
        series = _parse_prom(body)
        assert series["scrape_probe"] == 42.0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=10) as r:
            assert json.load(r)["status"] == "ok"
    finally:
        srv.close()
