"""Checkpoint interop bridge (interop.py — SURVEY hard part #2).

Round-trip losslessness, torch-side legibility (safetensors.torch loads it
as a state_dict with Linear/Conv2d layouts), and cross-framework numerics:
weights exported from flax, loaded into an equivalent torch module, must
produce the same forward output.
"""

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_train_tpu.config import ModelConfig, PrecisionConfig
from pytorch_distributed_train_tpu.interop import (
    load_flax_safetensors,
    save_torch_safetensors,
)
from pytorch_distributed_train_tpu.models.registry import build_model

P32 = PrecisionConfig()


def _tree_equal(a, b):
    for (pa, la), (pb, lb) in zip(
        jax.tree_util.tree_leaves_with_path(a),
        jax.tree_util.tree_leaves_with_path(b),
    ):
        assert jax.tree_util.keystr(pa) == jax.tree_util.keystr(pb)
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb),
                                      err_msg=jax.tree_util.keystr(pa))


def test_roundtrip_resnet(tmp_path):
    cfg = ModelConfig(name="resnet18", num_classes=10, image_size=32)
    model = build_model(cfg, P32)
    v = model.init({"params": jax.random.PRNGKey(0)},
                   jnp.zeros((1, 32, 32, 3)), train=False)
    path = str(tmp_path / "resnet.safetensors")
    save_torch_safetensors(v["params"], path)
    restored = load_flax_safetensors(path, v["params"])
    _tree_equal(v["params"], restored)


def test_roundtrip_llama_with_template_shapes(tmp_path):
    cfg = ModelConfig(name="llama", vocab_size=64, hidden_size=32,
                      num_layers=2, num_heads=4, num_kv_heads=2, mlp_dim=64,
                      max_seq_len=16)
    model = build_model(cfg, P32)
    v = model.init({"params": jax.random.PRNGKey(1)},
                   jnp.zeros((1, 16), jnp.int32), train=False)
    path = str(tmp_path / "llama.safetensors")
    save_torch_safetensors(v["params"], path)
    template = jax.eval_shape(lambda: v["params"])  # ShapeDtypeStructs
    restored = load_flax_safetensors(path, template)
    _tree_equal(v["params"], restored)


def test_torch_reads_linear_and_conv_layouts(tmp_path):
    """The exported file must be a legible torch state_dict: names dotted,
    Linear (out,in), Conv2d OIHW."""
    from safetensors.torch import load_file

    cfg = ModelConfig(name="resnet18", num_classes=10, image_size=32)
    model = build_model(cfg, P32)
    v = model.init({"params": jax.random.PRNGKey(0)},
                   jnp.zeros((1, 32, 32, 3)), train=False)
    path = str(tmp_path / "m.safetensors")
    save_torch_safetensors(v["params"], path)
    sd = load_file(path)
    # stem conv OIHW: input channels (3, RGB) land in dim 1
    stem = sd["conv_stem.weight"]
    assert stem.ndim == 4 and stem.shape[1] == 3, tuple(stem.shape)
    assert stem.shape[2] == stem.shape[3]  # square kernel trailing (HW)
    # classifier: flax (512,10) → torch Linear (10,512)
    fc = [k for k, t in sd.items() if t.ndim == 2 and t.shape[0] == 10]
    assert fc and tuple(sd[fc[0]].shape) == (10, 512)
    assert all("." in k and "/" not in k for k in sd)


def test_cross_framework_forward_parity(tmp_path):
    """flax Dense stack → safetensors → torch.nn module: same outputs."""
    import flax.linen as nn
    import torch

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Dense(16, name="fc1")(x)
            x = nn.relu(x)
            return nn.Dense(4, name="fc2")(x)

    model = Tiny()
    x = np.random.default_rng(0).standard_normal((8, 12)).astype(np.float32)
    v = model.init(jax.random.PRNGKey(0), jnp.asarray(x))
    want = np.asarray(model.apply(v, jnp.asarray(x)))

    path = str(tmp_path / "tiny.safetensors")
    save_torch_safetensors(v["params"], path)

    tmodel = torch.nn.Sequential()
    tmodel.add_module("fc1", torch.nn.Linear(12, 16))
    tmodel.add_module("relu", torch.nn.ReLU())
    tmodel.add_module("fc2", torch.nn.Linear(16, 4))
    from safetensors.torch import load_file

    tmodel.load_state_dict(load_file(path))
    with torch.no_grad():
        got = tmodel(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, want, atol=1e-5)
