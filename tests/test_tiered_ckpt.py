"""Tiered async checkpointing plane (ckpt/; docs/checkpointing.md):
async-vs-sync restore equivalence, snapshot-only blocking, back-pressure
drain, kill-during-persist fallback to the newest sealed step, peer
fetch over a fake store, retention pins, sentinel rewind tier hits, and
the per-worker compile-cache satellite.

Late-alphabet on purpose: the tier-1 870s cap only reaches an
alphabetical prefix on this box, and early-alphabet files must stay
fast (CHANGES PR 2/3)."""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_train_tpu import faults as faults_lib
from pytorch_distributed_train_tpu.checkpoint import CheckpointManager
from pytorch_distributed_train_tpu.ckpt import (
    TieredCheckpointManager,
    build_checkpoint_manager,
)
from pytorch_distributed_train_tpu.ckpt import retention
from pytorch_distributed_train_tpu.ckpt import snapshot as snapshot_lib
from pytorch_distributed_train_tpu.config import CheckpointConfig, TrainConfig
from pytorch_distributed_train_tpu.faults.retry import (
    RetryPolicy,
    default_policy,
    set_default_policy,
)
from pytorch_distributed_train_tpu.obs.registry import get_registry
from pytorch_distributed_train_tpu.train_state import TrainState


@pytest.fixture(autouse=True)
def _clean_fault_schedule():
    """Each test owns the process-global fault schedule + retry policy."""
    prev_policy = default_policy()
    yield
    faults_lib.configure(())
    set_default_policy(prev_policy)


def _tiny_state(step: int = 0, seed: int = 0) -> TrainState:
    rng = np.random.default_rng(seed)
    params = {
        "dense": {"kernel": jnp.asarray(rng.standard_normal((8, 4)),
                                        jnp.float32),
                  "bias": jnp.asarray(rng.standard_normal(4), jnp.float32)},
    }
    state = TrainState.create(params=params, tx=optax.sgd(0.1, momentum=0.9),
                              batch_stats={})
    return state.replace(step=jnp.int32(step))


def _assert_trees_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


def _tier_hits(tier: str) -> float:
    return get_registry().get_value("ckpt_restore_tier_total",
                                    {"tier": tier}) or 0.0


class FakeStore:
    """Dict-backed stand-in for native/store.py StoreClient (the peer
    plane only needs set/get/delete)."""

    def __init__(self):
        self.kv: dict[str, bytes] = {}

    def set(self, key, value):
        self.kv[key] = bytes(value)

    def get(self, key, timeout_ms=0, max_len=0):
        if key not in self.kv:
            raise TimeoutError(key)
        return self.kv[key]

    def delete(self, key):
        self.kv.pop(key, None)

    def close(self):
        pass


# ------------------------------------------------------------- snapshot unit
def test_snapshot_seal_verify_and_wire_roundtrip():
    state = _tiny_state(step=5)
    from pytorch_distributed_train_tpu.checkpoint import _savable

    snap = snapshot_lib.take_snapshot(_savable(state), step=5, epoch=1)
    assert not snapshot_lib.verify(snap)  # unsealed never verifies
    snapshot_lib.seal(snap)
    assert snapshot_lib.verify(snap)
    # wire roundtrip: leaves + header CRC-verify, order preserved
    payload = snapshot_lib.serialize_leaves(snap)
    header = snapshot_lib.snapshot_meta(snap)
    assert snapshot_lib.verify_payload(payload, header)
    leaves = snapshot_lib.deserialize_leaves(payload)
    t_leaves = jax.tree_util.tree_leaves(snap.tree)
    assert snapshot_lib.leaves_match_template(leaves, t_leaves)
    for got, want in zip(leaves, t_leaves):
        np.testing.assert_array_equal(got, want)
    # corruption detected at both layers
    snap.tree["params"]["dense"]["bias"] = (
        snap.tree["params"]["dense"]["bias"] + 1.0)
    assert not snapshot_lib.verify(snap)
    assert not snapshot_lib.verify_payload(payload[:-8], header)


# ------------------------------------------------------------ retention unit
def test_retention_plan_keep_rules_and_pins():
    assert retention.plan_evictions([1, 2, 3, 4], keep_last=2) == [1, 2]
    assert retention.plan_evictions([10, 20, 30, 40], keep_last=1,
                                    keep_every=20) == [10, 30]
    assert retention.plan_evictions([], keep_last=2) == []
    # pins always survive, regardless of age
    assert retention.plan_evictions([1, 2, 3], keep_last=1,
                                    pinned=[1]) == [2]


def test_gc_never_deletes_newest_verified_step(tmp_path):
    """The acceptance property: however aggressive the keep policy, the
    newest verified step is pinned in both hot tiers."""
    cfg = CheckpointConfig(dir=str(tmp_path / "c"), tiered=True,
                           hot_keep=1, peer_fetch=False)
    tm = TieredCheckpointManager(cfg, "{}")
    for s in (1, 2, 3):
        assert tm.save(_tiny_state(step=s), epoch=0, step=s)
        tm.wait()
    # keep_last=1 would keep only step 3; the newest verified persistent
    # step IS 3 here, so older hot steps age out but 3 stays everywhere.
    tiers = tm.steps_by_tier()
    assert tiers["persistent"] == [1, 2, 3]  # Orbax max_to_keep=3 default
    assert tm.latest_good_step() == 3
    assert 3 in tiers["ram"] and 3 in tiers["disk"]
    assert tiers["ram"] == [3]  # keep_last=1 evicted 1, 2
    # and the planner itself refuses to evict a pinned newest-verified
    assert 3 not in retention.plan_evictions([1, 2, 3], keep_last=1,
                                             pinned=[3])
    tm.close()


# ------------------------------------------------- async save / equivalence
def test_async_restore_byte_identical_to_sync_and_blocking_small(tmp_path):
    state = _tiny_state(step=4, seed=7)
    # sync plane: the pre-existing Orbax path
    sync = CheckpointManager(
        CheckpointConfig(dir=str(tmp_path / "sync"), async_save=False), "{}")
    assert sync.save(state, epoch=2, step=4)
    sync.wait()
    # tiered plane, with an artificially slow persistent write so the
    # blocking/persist split is unambiguous even on a noisy CPU box
    cfg = CheckpointConfig(dir=str(tmp_path / "tiered"), tiered=True,
                           peer_fetch=False)
    tm = TieredCheckpointManager(cfg, "{}")
    orig_save = tm.persistent.save

    def slow_save(*a, **k):
        time.sleep(0.8)
        return orig_save(*a, **k)

    tm.persistent.save = slow_save
    assert tm.save(state, epoch=2, step=4)
    tm.wait()
    reg = get_registry()
    blocking_ms = reg.get_value("ckpt_last_blocking_ms")
    persist_ms = reg.get_value("ckpt_last_persist_ms")
    assert blocking_ms is not None and persist_ms is not None
    assert persist_ms >= 800.0
    # step-boundary blocking is snapshot-only: a small fraction of the
    # total persist pipeline
    assert blocking_ms < persist_ms * 0.5

    sync_restored, sync_meta = sync.restore(_tiny_state())
    # RAM-tier restore == sync restore, byte-identical params/opt_state
    ram_restored, ram_meta = tm.restore(_tiny_state())
    assert int(ram_restored.step) == 4 and ram_meta["epoch"] == 2
    _assert_trees_equal(jax.device_get(ram_restored.params),
                        jax.device_get(sync_restored.params))
    _assert_trees_equal(jax.device_get(ram_restored.opt_state),
                        jax.device_get(sync_restored.opt_state))
    assert sync_meta["epoch"] == ram_meta["epoch"]
    tm.close()
    # Orbax-tier restore of the async-written checkpoint (fresh manager,
    # hot tiers disabled) is byte-identical too
    cold = TieredCheckpointManager(
        CheckpointConfig(dir=str(tmp_path / "tiered"), tiered=True,
                         hot_disk=False, peer_fetch=False), "{}")
    before = _tier_hits("orbax")
    orbax_restored, _ = cold.restore(_tiny_state())
    assert _tier_hits("orbax") == before + 1
    _assert_trees_equal(jax.device_get(orbax_restored.params),
                        jax.device_get(sync_restored.params))
    _assert_trees_equal(jax.device_get(orbax_restored.opt_state),
                        jax.device_get(sync_restored.opt_state))
    cold.close()
    sync.close()


def test_backpressure_drain_accounted(tmp_path):
    """Second save boundary arriving mid-persist waits (single persist
    in flight) and the wait lands in the ckpt.drain goodput bucket."""
    from pytorch_distributed_train_tpu.obs.goodput import GoodputTracker

    gp = GoodputTracker()
    cfg = CheckpointConfig(dir=str(tmp_path / "c"), tiered=True,
                           peer_fetch=False)
    tm = TieredCheckpointManager(cfg, "{}", goodput=gp)
    orig_save = tm.persistent.save

    def slow_save(*a, **k):
        time.sleep(0.5)
        return orig_save(*a, **k)

    tm.persistent.save = slow_save
    with gp.measure("ckpt"):
        assert tm.save(_tiny_state(step=1), epoch=0, step=1)
    with gp.measure("ckpt"):
        assert tm.save(_tiny_state(step=2), epoch=0, step=2)  # drains 1
    tm.wait()
    assert gp.buckets.get("ckpt.drain", 0.0) > 0.1
    # reattribution preserves the bucket sum (ckpt gave what drain got)
    assert gp.buckets["ckpt"] >= 0.0
    tm.close()


# -------------------------------------------------- kill-during-persist path
def test_failed_persist_falls_back_to_newest_sealed_step(tmp_path):
    """Persist of step 2 dies after the hot seal+spill (the pipeline
    order guarantee): restores still land on step 2 from the disk tier;
    corrupting that spill falls back to step 1 (Orbax-verified)."""
    set_default_policy(RetryPolicy(max_attempts=2, base_delay_s=0.01,
                                   max_delay_s=0.02))
    cfg = CheckpointConfig(dir=str(tmp_path / "c"), tiered=True,
                           peer_fetch=False)
    tm = TieredCheckpointManager(cfg, "{}")
    assert tm.save(_tiny_state(step=1, seed=1), epoch=0, step=1)
    tm.wait()
    # every Orbax write for step >= 2 fails — the persister gives up
    faults_lib.configure(("ckpt.persist_io@step=2:count=99",))
    state2 = _tiny_state(step=2, seed=2)
    assert tm.save(state2, epoch=0, step=2)
    with pytest.raises(OSError):
        tm.wait()  # the terminal persist error escalates to the waiter
    tiers = tm.steps_by_tier()
    assert tiers["persistent"] == [1] and 2 in tiers["disk"]
    assert (get_registry().get_value("ckpt_persist_failures_total")
            or 0) >= 1
    tm.close()
    faults_lib.configure(())

    # fresh process: RAM gone, disk survives → newest SEALED step wins
    tm2 = TieredCheckpointManager(cfg, "{}")
    assert tm2.latest_good_step() == 2
    before = _tier_hits("disk")
    restored, _ = tm2.restore(_tiny_state())
    assert int(restored.step) == 2
    assert _tier_hits("disk") == before + 1
    _assert_trees_equal(jax.device_get(restored.params),
                        jax.device_get(state2.params))
    tm2.close()

    # truncate the spill of step 2 → verification fails → fall back to
    # the newest Orbax-verified step (1), counting the corruption
    npz = tmp_path / "c" / "hot" / "host_0" / "step_2" / "data.npz"
    npz.write_bytes(npz.read_bytes()[:64])
    tm3 = TieredCheckpointManager(cfg, "{}")
    before_corrupt = get_registry().get_value("ckpt_hot_corrupt_total") or 0
    restored3, _ = tm3.restore(_tiny_state())
    assert int(restored3.step) == 1
    assert (get_registry().get_value("ckpt_hot_corrupt_total")
            or 0) > before_corrupt
    tm3.close()


def test_foreign_hot_dir_snapshot_never_restored(tmp_path):
    """A node-local hot_dir outliving its run (config guidance: point it
    at scratch) must not hand a NEW experiment the old run's state just
    because shapes/dtypes match — run identity (the persistent dir) is
    stamped into every spill and checked on restore."""
    hot = str(tmp_path / "scratch")
    old_state = _tiny_state(step=9, seed=11)
    old = TieredCheckpointManager(
        CheckpointConfig(dir=str(tmp_path / "old_run"), tiered=True,
                         hot_dir=hot, peer_fetch=False), "{}")
    assert old.save(old_state, epoch=0, step=9)
    old.wait()
    old.close()
    # fresh experiment, same architecture, same scratch dir
    new = TieredCheckpointManager(
        CheckpointConfig(dir=str(tmp_path / "new_run"), tiered=True,
                         hot_dir=hot, peer_fetch=False), "{}")
    assert new.latest_good_step() is None  # foreign spills are not ours
    assert new.restore(_tiny_state()) is None
    assert new.restore(_tiny_state(), step=9) is None  # even explicitly
    new.close()
    # the old run itself still restores its own spill after a restart
    again = TieredCheckpointManager(
        CheckpointConfig(dir=str(tmp_path / "old_run"), tiered=True,
                         hot_dir=hot, peer_fetch=False), "{}")
    restored, _ = again.restore(_tiny_state())
    assert int(restored.step) == 9
    again.close()


def test_stale_persist_error_does_not_poison_later_wait(tmp_path):
    """A terminal persist failure surfaces at the NEXT drain/wait only;
    once a later persist has been submitted (and succeeded), wait() must
    not re-raise the hours-old error — a finished job whose final
    checkpoint landed must not fail on history."""
    set_default_policy(RetryPolicy(max_attempts=2, base_delay_s=0.01,
                                   max_delay_s=0.02))
    faults_lib.configure(("ckpt.persist_io@step=1:count=2",))  # step 1 only
    cfg = CheckpointConfig(dir=str(tmp_path / "c"), tiered=True,
                           peer_fetch=False)
    tm = TieredCheckpointManager(cfg, "{}")
    assert tm.save(_tiny_state(step=1), epoch=0, step=1)
    deadline = time.time() + 30
    while tm.persister.busy and time.time() < deadline:
        time.sleep(0.01)  # let the failing persist finish WITHOUT drain
    assert tm.save(_tiny_state(step=2), epoch=0, step=2)
    tm.wait()  # step 2 persisted fine — no stale step-1 error
    assert tm.steps_by_tier()["persistent"] == [2]
    assert tm.latest_good_step() == 2
    tm.close()


# ----------------------------------------------------------------- peer tier
def test_peer_fetch_restore_with_fake_store(tmp_path):
    store = FakeStore()
    state = _tiny_state(step=7, seed=3)
    # host 0 trains, seals, publishes
    h0 = TieredCheckpointManager(
        CheckpointConfig(dir=str(tmp_path / "h0"), tiered=True), "{}",
        store=store, host_id=0, peer_hosts=[0, 1])
    assert h0.save(state, epoch=2, step=7)
    h0.wait()
    assert any(k.startswith("ckptp/0/") for k in store.kv)
    h0.close()
    # host 1 restarts cold (own dir: no RAM, no disk, no Orbax) — with a
    # transient injected fetch fault absorbed by the retry policy
    set_default_policy(RetryPolicy(max_attempts=3, base_delay_s=0.01,
                                   max_delay_s=0.02))
    faults_lib.configure(("ckpt.peer_fetch@call=1:count=1",))
    h1 = TieredCheckpointManager(
        CheckpointConfig(dir=str(tmp_path / "h1"), tiered=True), "{}",
        store=store, host_id=1, peer_hosts=[0, 1])
    assert h1.latest_good_step() == 7  # advertised by the peer
    before = _tier_hits("peer")
    restored, meta = h1.restore(_tiny_state())
    assert int(restored.step) == 7 and meta["epoch"] == 2
    assert _tier_hits("peer") == before + 1
    _assert_trees_equal(jax.device_get(restored.params),
                        jax.device_get(state.params))
    retried = get_registry().get_value("retries_total",
                                       {"point": "ckpt.peer_fetch"})
    assert (retried or 0) >= 1
    h1.close()


# ----------------------------------------------------- sentinel rewind tiers
def _e2e_cfg(d: str) -> TrainConfig:
    cfg = TrainConfig()
    cfg.model.name = "resnet18"
    cfg.model.num_classes = 10
    cfg.model.image_size = 8
    cfg.data.dataset = "synthetic_images"
    cfg.data.synthetic_size = 256
    cfg.data.batch_size = 32
    cfg.data.num_workers = 1
    cfg.optim.name = "momentum"
    cfg.optim.learning_rate = 0.05
    cfg.optim.schedule = "constant"
    cfg.optim.warmup_steps = 0
    cfg.total_steps = 6
    cfg.checkpoint.dir = d
    cfg.checkpoint.save_every_steps = 2
    cfg.checkpoint.tiered = True
    cfg.checkpoint.peer_fetch = False
    cfg.obs.log_every_steps = 100
    cfg.sentinel.enabled = True
    cfg.sentinel.max_consecutive_bad = 1
    cfg.sentinel.spike_min_samples = 2
    return cfg


def test_sentinel_rewind_restores_from_ram_tier(tmp_path):
    """Auto-rewind under the tiered plane: the restore is served from
    host RAM (tier-hit metric), and the summary still records the
    rewind. The drain in _sentinel_rewind's ckpt.wait() guarantees the
    rewind target's persist committed first."""
    from pytorch_distributed_train_tpu.trainer import Trainer

    cfg = _e2e_cfg(str(tmp_path / "run"))
    cfg.faults.inject = ("step.loss_spike@step=5",)
    before = _tier_hits("ram")
    t = Trainer(cfg)
    t.fit()
    assert t._rewinds == 1
    assert _tier_hits("ram") >= before + 1
    t.close()
    recs = [json.loads(line)
            for line in open(os.path.join(cfg.checkpoint.dir,
                                          "metrics.jsonl"))]
    summary = [r for r in recs if r["tag"] == "summary"][-1]
    assert summary["rewinds"] == 1
    # blocking vs persist metric pair exists for the cadence saves
    assert get_registry().get_value("ckpt_last_blocking_ms") is not None
    assert get_registry().get_value("ckpt_last_persist_ms") is not None


def test_rewind_falls_back_to_orbax_when_hot_corrupt(tmp_path):
    """Hot tier cold/corrupt → the rewind path still lands on
    latest_good_step() via the persistent tier."""
    cfg = CheckpointConfig(dir=str(tmp_path / "c"), tiered=True,
                           hot_disk=False, peer_fetch=False)
    tm = TieredCheckpointManager(cfg, "{}")
    state = _tiny_state(step=3, seed=5)
    assert tm.save(state, epoch=1, step=3)
    tm.wait()
    # corrupt the RAM copy in place: CRC verification must catch it
    snap = tm.ram.get(3)
    snap.tree["params"]["dense"]["kernel"][...] += 1.0
    good = tm.latest_good_step()
    assert good == 3  # the persistent step verified via its manifest
    before_orbax = _tier_hits("orbax")
    before_corrupt = get_registry().get_value("ckpt_hot_corrupt_total") or 0
    restored, _ = tm.restore(_tiny_state(), step=good)
    assert int(restored.step) == 3
    assert _tier_hits("orbax") == before_orbax + 1
    assert (get_registry().get_value("ckpt_hot_corrupt_total")
            or 0) > before_corrupt
    # the Orbax copy predates the corruption: bytes match the original
    _assert_trees_equal(jax.device_get(restored.params),
                        jax.device_get(state.params))
    tm.close()


# --------------------------------------------- satellite: compile-cache dirs
def test_per_worker_compile_cache_dirs(tmp_path, monkeypatch):
    from pytorch_distributed_train_tpu import elastic

    base = str(tmp_path / "cc")
    assert elastic.worker_cache_dir(base, 0) != elastic.worker_cache_dir(
        base, 1)
    # _spawn hands each worker its own PDTT_COMPILE_CACHE_DIR
    envs = []

    class _FakeProc:
        pid = 0

        def poll(self):
            return 0

    def fake_popen(cmd, env=None):
        envs.append(env)
        return _FakeProc()

    monkeypatch.setattr(elastic.subprocess, "Popen", fake_popen)
    agent = elastic.ElasticAgent(
        elastic.LaunchConfig(nprocs=2, compile_cache_base=base), ["true"])
    agent.coord_port = 1
    agent.store_port = 2
    agent._spawn(0)
    dirs = [e["PDTT_COMPILE_CACHE_DIR"] for e in envs]
    assert len(dirs) == 2 and len(set(dirs)) == 2
    assert all(d.startswith(base) for d in dirs)
    # without a base, the env var is not set at all
    envs.clear()
    agent2 = elastic.ElasticAgent(elastic.LaunchConfig(nprocs=1), ["true"])
    agent2.coord_port = 1
    agent2.store_port = 2
    agent2._spawn(0)
    assert "PDTT_COMPILE_CACHE_DIR" not in envs[0]


# ------------------------------------------------- satellite: inspector tool
def test_ckpt_inspect_smoke(tmp_path, capsys):
    import tools.ckpt_inspect as inspect_tool

    cfg = CheckpointConfig(dir=str(tmp_path / "c"), tiered=True,
                           peer_fetch=False)
    tm = TieredCheckpointManager(cfg, "{}")
    for s in (1, 2):
        tm.save(_tiny_state(step=s), epoch=0, step=s)
        tm.wait()
    tm.close()
    assert inspect_tool.main(["--dir", cfg.dir]) == 0
    out = capsys.readouterr().out
    assert "persistent tier" in out and "hot disk tier" in out
    report = inspect_tool.inspect_dir(cfg.dir)
    assert report["restore_would_land_on"] == 2
    assert report["newest_verified_persistent"] == 2
    assert [r["step"] for r in report["persistent"]] == [1, 2]
    assert all(r["verdict"] == "verified" for r in report["persistent"])
    # a missing dir is a clean nonzero exit, not a traceback
    assert inspect_tool.main(["--dir", str(tmp_path / "nope")]) == 1


# --------------------------------------------- satellite: catalog stays sync
def test_new_fault_points_cataloged():
    from pytorch_distributed_train_tpu.faults.registry import POINTS
    from tools.check_fault_points import documented_points, main

    assert {"ckpt.persist_io", "ckpt.peer_fetch"} <= set(POINTS)
    assert {"ckpt.persist_io", "ckpt.peer_fetch"} <= documented_points()
    assert main() == 0
