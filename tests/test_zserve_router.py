"""Multi-replica router (serving_plane/router.py + tools/serve_router.py):
least-outstanding balancing, health-probe state flips, failover to a
survivor, session pinning, hedging of stragglers, rolling restart with
zero failed requests, store-based replica discovery, and the ISSUE-7
acceptance drill (subprocess replicas: injected slow decode → anomaly +
fake profiler capture + hedging; SIGTERM → drain → failover; deadline →
504 with slots reclaimed; timeline shows the chain). Late-alphabet file
per the tier-1 870s alphabetical-prefix constraint."""

import json
import os
import queue as queue_mod
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import serve_http  # noqa: E402
import serve_router as serve_router_tool  # noqa: E402

from pytorch_distributed_train_tpu.faults import (  # noqa: E402
    registry as fregistry,
)
from pytorch_distributed_train_tpu.obs import events as events_lib  # noqa: E402
from pytorch_distributed_train_tpu.obs.events import load_events  # noqa: E402
from pytorch_distributed_train_tpu.obs.registry import get_registry  # noqa: E402
from pytorch_distributed_train_tpu.serving_plane import (  # noqa: E402
    ReliabilityPlane,
)
from pytorch_distributed_train_tpu.serving_plane.router import (  # noqa: E402
    HealthProber,
    ReplicaSet,
    Router,
)
from pytorch_distributed_train_tpu.serving_plane.testing import (  # noqa: E402
    FakeByteTok,
    FakeTokenBatcher,
)


@pytest.fixture(autouse=True)
def _clean_planes():
    fregistry._reset_for_tests()
    yield
    fregistry._reset_for_tests()
    events_lib._reset_for_tests()


def _counter(name):
    return get_registry().get_value(name) or 0.0


def _make_replica(port=0, *, slots=4, step_delay_s=0.005,
                  drain_grace=10.0):
    batcher = FakeTokenBatcher(slots=slots, step_delay_s=step_delay_s)
    svc = serve_http.BatcherService(
        batcher, FakeByteTok(), plane=ReliabilityPlane(slots=slots),
        orphan_grace_s=0.5)
    httpd = ThreadingHTTPServer(("127.0.0.1", port), None)
    drain = serve_http.GracefulDrain(httpd, svc, grace_s=drain_grace)
    httpd.RequestHandlerClass = serve_http.make_handler(svc, drain)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return {"svc": svc, "httpd": httpd, "drain": drain,
            "batcher": batcher, "port": httpd.server_address[1],
            "addr": f"127.0.0.1:{httpd.server_address[1]}"}


def _kill_replica(rep):
    rep["httpd"].shutdown()
    rep["httpd"].server_close()
    rep["svc"].shutdown()


def _body(prompt="hello", max_tokens=4, **kw):
    d = {"prompt": prompt, "max_tokens": max_tokens, **kw}
    return json.dumps(d).encode(), d


# ----------------------------------------------------------------- units

def test_replicaset_pick_least_outstanding_and_states():
    rs = ReplicaSet(("a:1", "b:2"))
    assert rs.pick() == "a:1"  # tie → lexicographic
    rs.begin("a:1")
    assert rs.pick() == "b:2"  # least outstanding
    rs.mark("b:2", "draining")
    assert rs.pick() == "a:1"  # draining unroutable
    rs.mark("a:1", "down")
    assert rs.pick() is None
    rs.mark("a:1", "up")
    # a shedding replica ranks after a non-shedding one
    rs.mark("b:2", "up", healthz={"admission": "shedding"})
    rs.end("a:1")
    assert rs.pick() == "a:1"
    snap = {r["addr"]: r for r in rs.snapshot()}
    assert snap["b:2"]["admission"] == "shedding"


def test_prober_flips_states_and_journals(tmp_path):
    events_lib.configure(str(tmp_path))
    rs = ReplicaSet(("x:1",))
    answers = {"mode": "ok"}

    def fetch(addr):
        if answers["mode"] == "ok":
            return 200, {"status": "ok",
                         "reliability": {"admission": "ok",
                                         "queue_depth": 0}}
        if answers["mode"] == "draining":
            return 503, {"status": "draining"}
        raise OSError("connection refused")

    p = HealthProber(rs, down_after=2, fetch=fetch)
    p.probe_once()
    assert rs.get("x:1").state == "up"
    assert rs.get("x:1").healthz["admission"] == "ok"
    answers["mode"] = "draining"
    p.probe_once()
    assert rs.get("x:1").state == "draining"
    answers["mode"] = "dead"
    p.probe_once()  # one failed probe: debounced, still draining
    assert rs.get("x:1").state == "draining"
    p.probe_once()
    assert rs.get("x:1").state == "down"
    answers["mode"] = "ok"
    p.probe_once()
    assert rs.get("x:1").state == "up"
    names = [(e["category"], e["name"]) for e in load_events(str(tmp_path))]
    assert ("serve", "replica_down") in names
    assert ("serve", "replica_up") in names


def test_store_publish_and_discover_replicas():
    from pytorch_distributed_train_tpu.elastic import (
        discover_replicas,
        publish_replica,
    )
    from pytorch_distributed_train_tpu.native.store import (
        StoreClient,
        StoreServer,
    )

    with StoreServer() as srv:
        c = StoreClient("127.0.0.1", srv.port)
        assert discover_replicas(c) == []
        assert publish_replica(c, "127.0.0.1:8000") == 0
        assert publish_replica(c, "127.0.0.1:8001") == 1
        assert discover_replicas(c) == ["127.0.0.1:8000",
                                        "127.0.0.1:8001"]
        c.close()
    assert discover_replicas(None) == []


# ------------------------------------------------------------- failover

def test_router_fails_over_to_survivor(tmp_path):
    events_lib.configure(str(tmp_path), who="router")
    a, b = _make_replica(), _make_replica()
    rs = ReplicaSet((a["addr"], b["addr"]))
    prober = HealthProber(rs, interval_s=0.2)
    prober.probe_once()
    router = Router(rs, timeout_s=30.0)
    before = _counter("serve_failovers_total")
    try:
        _kill_replica(a)  # dead, but still marked up: the router's
        rs.begin(b["addr"])  # tiebreak must pick the corpse first
        raw, body = _body("failover me", 4)
        status, rbody = router.request("/v1/completions", raw, body)
        rs.end(b["addr"])
        assert status == 200, rbody
        assert json.loads(rbody)["finish_reason"] in ("length", "eos")
        assert _counter("serve_failovers_total") == before + 1
        names = [(e["category"], e["name"])
                 for e in load_events(str(tmp_path))]
        assert ("serve", "failover") in names
        # with A gone and probed, the set converges to B only
        prober.probe_once()
        prober.probe_once()
        assert rs.get(a["addr"]).state == "down"
        assert rs.pick() == b["addr"]
    finally:
        _kill_replica(b)


def test_session_pins_to_owning_replica():
    a, b = _make_replica(), _make_replica()
    rs = ReplicaSet((a["addr"], b["addr"]))
    HealthProber(rs).probe_once()
    router = Router(rs, timeout_s=30.0)
    try:
        raw, body = _body("turn one", 4, keep=True)
        status, rbody = router.request("/v1/completions", raw, body)
        assert status == 200
        sid = json.loads(rbody)["session"]
        assert sid is not None and router.sessions[sid] in (a["addr"],
                                                           b["addr"])
        # a resume routes HOME: the other replica would 400 it as an
        # unknown session, so a 200 proves the pin
        raw2, body2 = _body("turn two", 4, session=sid)
        status2, rbody2 = router.request("/v1/completions", raw2, body2)
        assert status2 == 200, rbody2
    finally:
        _kill_replica(a)
        _kill_replica(b)


def test_hedge_straggler_completes_on_second_replica(tmp_path):
    events_lib.configure(str(tmp_path), who="router")
    slow = _make_replica(step_delay_s=0.25)
    fast = _make_replica(step_delay_s=0.002)
    rs = ReplicaSet((slow["addr"], fast["addr"]))
    HealthProber(rs).probe_once()
    router = Router(rs, timeout_s=30.0, hedge_after_s=0.3)
    before = _counter("serve_hedges_total")
    try:
        rs.begin(fast["addr"])  # force the straggler to win the pick
        threading.Timer(0.1, rs.end, args=(fast["addr"],)).start()
        t0 = time.monotonic()
        raw, body = _body("straggling", 8)
        status, rbody = router.request("/v1/completions", raw, body)
        dt = time.monotonic() - t0
        assert status == 200
        # the slow replica would need >= 8 * 0.25 = 2s; the hedge won
        assert dt < 1.8, dt
        assert _counter("serve_hedges_total") == before + 1
        names = [(e["category"], e["name"])
                 for e in load_events(str(tmp_path))]
        assert ("serve", "hedge") in names
        assert ("serve", "hedge_win") in names
    finally:
        _kill_replica(slow)
        _kill_replica(fast)


# -------------------------------------------------- HTTP front (tool)

def test_router_tool_http_front_relays_and_streams():
    a, b = _make_replica(), _make_replica()
    rs = ReplicaSet((a["addr"], b["addr"]))
    prober = HealthProber(rs, interval_s=0.2)
    prober.probe_once()
    router = Router(rs, timeout_s=30.0)
    front = ThreadingHTTPServer(
        ("127.0.0.1", 0), serve_router_tool.make_handler(router, prober))
    threading.Thread(target=front.serve_forever, daemon=True).start()
    port = front.server_address[1]
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions",
            data=json.dumps({"prompt": "via the front",
                             "max_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            out = json.loads(r.read())
        assert out["finish_reason"] in ("length", "eos")
        # streamed passthrough ends with [DONE]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions",
            data=json.dumps({"prompt": "stream via front",
                             "max_tokens": 4,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            raw = r.read().decode()
        assert raw.rstrip().endswith("data: [DONE]")
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as r:
            health = json.loads(r.read())
        assert health["status"] == "ok" and health["up"] == 2
    finally:
        front.shutdown()
        front.server_close()
        _kill_replica(a)
        _kill_replica(b)


# ------------------------------------------------------ rolling restart

def test_rolling_restart_drains_with_zero_failed_requests(tmp_path):
    """Two supervised replicas, continuous traffic, rolling restart:
    every replica walks through the drain path, every request lands
    200 — the zero-failed-requests fleet restart."""
    events_lib.configure(str(tmp_path), who="router")
    boxes = [_make_replica(drain_grace=10.0), _make_replica(
        drain_grace=10.0)]
    stop = threading.Event()

    def supervise(box):
        # the "systemd" of this test: when the drain stops the service,
        # close the socket and bring a fresh replica up on the SAME port
        while not stop.is_set():
            if box["svc"]._stop:
                box["httpd"].server_close()
                time.sleep(1.0)  # let the router observe the death
                box.update(_make_replica(port=box["port"],
                                         drain_grace=10.0))
            time.sleep(0.05)

    sups = [threading.Thread(target=supervise, args=(b,), daemon=True)
            for b in boxes]
    for s in sups:
        s.start()
    rs = ReplicaSet(tuple(b["addr"] for b in boxes))
    prober = HealthProber(rs, interval_s=0.2)
    prober.start()
    router = Router(rs, timeout_s=30.0)
    statuses: list[int] = []
    lock = threading.Lock()

    def traffic():
        i = 0
        while not stop.is_set():
            raw, body = _body(f"rolling {i}", 3)
            status, _ = router.request("/v1/completions", raw, body)
            with lock:
                statuses.append(status)
            i += 1
            time.sleep(0.02)

    tthreads = [threading.Thread(target=traffic, daemon=True)
                for _ in range(2)]
    for t in tthreads:
        t.start()
    try:
        time.sleep(0.5)
        report = router.rolling_restart(down_timeout_s=20.0,
                                        wait_back_s=20.0)
        time.sleep(0.5)
    finally:
        stop.set()
        for t in tthreads:
            t.join(timeout=30)
        prober.stop()
    assert [e.get("drained") for e in report] == [True, True], report
    assert [e.get("back") for e in report] == [True, True], report
    assert statuses and all(s == 200 for s in statuses), (
        [s for s in statuses if s != 200][:5], len(statuses))
    names = [(e["category"], e["name"]) for e in load_events(str(tmp_path))]
    assert names.count(("serve", "rolling_drain")) == 2
    for b in boxes:
        _kill_replica(b)


# ----------------------------------------------------- acceptance drill

def _spawn_replica(tmp_path, name, *, faults="", extra_env=None,
                   extra_args=()):
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "PDTT_EVENTS_DIR": str(tmp_path / "events"),
           "PDTT_PROFILE_BACKEND": "fake",
           "PDTT_PROFILE_DIR": str(tmp_path / f"prof_{name}"),
           **(extra_env or {})}
    if faults:
        env["PDTT_FAULTS"] = faults
    env.pop("PDTT_TEST_DUMP_AFTER_S", None)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve_http.py"),
         "--fake-backend", "--fake-step-delay", "0.01", "--port", "0",
         "--slots", "4", "--profile-on-tail",
         "--tail-capture-seconds", "0.3", "--tail-cooldown", "5",
         "--drain-grace", "5", *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    q: queue_mod.Queue = queue_mod.Queue()

    def pump():
        for line in proc.stdout:
            q.put(line)

    threading.Thread(target=pump, daemon=True).start()
    deadline = time.monotonic() + 120.0
    port = None
    while time.monotonic() < deadline:
        try:
            line = q.get(timeout=max(0.1, deadline - time.monotonic()))
        except queue_mod.Empty:
            break
        m = re.search(r"serving on http://127\.0\.0\.1:(\d+)", line)
        if m:
            port = int(m.group(1))
            break
    assert port is not None, f"replica {name} never came up"
    return proc, f"127.0.0.1:{port}"


def test_e2e_drill_anomaly_hedge_drain_failover(tmp_path):
    """The ISSUE-7 acceptance drill: 2 replicas behind the router under
    continuous traffic; serve.slow_decode injected on replica A →
    tail-latency anomaly journaled + (fake-backend) profiler capture
    fires + hedged requests complete on B; SIGTERM A → drain → router
    fails over with zero failed requests; a deadline-expired request
    504s with its slot verifiably reclaimed; the merged journal +
    timeline_report show the anomaly→hedge→drain chain."""
    events_dir = tmp_path / "events"
    proc_a, addr_a = _spawn_replica(
        tmp_path, "a", faults="serve.slow_decode@call=30:count=25:"
                             "delay=0.4",
        extra_env={"PROCESS_ID": "1"})
    proc_b, addr_b = _spawn_replica(tmp_path, "b",
                                    extra_env={"PROCESS_ID": "2"})
    events_lib.configure(str(events_dir), who="router")
    rs = ReplicaSet((addr_a, addr_b))
    prober = HealthProber(rs, interval_s=0.5)
    prober.start()
    router = Router(rs, timeout_s=60.0, hedge_after_s=0.8)
    stop = threading.Event()
    failures: list[tuple[int, bytes]] = []
    lock = threading.Lock()

    def traffic(ci):
        i = 0
        while not stop.is_set():
            raw, body = _body(f"drill {ci}-{i}", 6)
            status, rbody = router.request("/v1/completions", raw, body)
            if status != 200:
                with lock:
                    failures.append((status, rbody[:200]))
            i += 1
            time.sleep(0.02)

    threads = [threading.Thread(target=traffic, args=(i,), daemon=True)
               for i in range(3)]
    for t in threads:
        t.start()
    try:
        # phase 1 — the slow-decode storm on A: wait until a hedge won
        # and A's anomaly journaled (both driven by the injected stalls)
        deadline = time.monotonic() + 60.0
        seen_hedge = seen_anomaly = False
        while time.monotonic() < deadline:
            names = [(e["category"], e["name"], e.get("host"))
                     for e in load_events(str(events_dir))]
            seen_hedge = any(n[:2] == ("serve", "hedge_win")
                             for n in names)
            seen_anomaly = any(
                n[0] == "anomaly" and n[2] == "host1"
                and n[1] in ("ttft_regression", "inter_token_regression")
                for n in names)
            if seen_hedge and seen_anomaly:
                break
            time.sleep(0.25)
        assert seen_anomaly, "no tail-latency anomaly journaled on A"
        assert seen_hedge, "no hedged completion won on B"
        # the anomaly fired the managed profiler (fake backend marker)
        cap_deadline = time.monotonic() + 20.0
        markers = []
        while time.monotonic() < cap_deadline and not markers:
            markers = [os.path.join(r, f)
                       for r, _d, fs in os.walk(tmp_path / "prof_a")
                       for f in fs if f == "FAKE_CAPTURE"]
            time.sleep(0.2)
        assert markers, "anomaly-triggered capture never materialized"
        # deadline-expired request → 504 through the router
        raw, body = _body("budget blown", 500, deadline_s=0.05)
        status, rbody = router.request("/v1/completions", raw, body)
        assert status == 504, (status, rbody)
        # phase 2 — SIGTERM A: graceful drain, router fails over
        proc_a.send_signal(signal.SIGTERM)
        assert proc_a.wait(timeout=60) == 0
        time.sleep(2.0)  # traffic keeps flowing through B
        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert not failures, failures[:5]
        # slots verifiably reclaimed on the survivor: no leaks, all free
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with urllib.request.urlopen(f"http://{addr_b}/healthz",
                                        timeout=10) as r:
                health = json.loads(r.read())
            slots = health["reliability"]["slots"]
            if slots["active"] == 0 and slots["queued"] == 0:
                break
            time.sleep(0.2)
        assert slots["active"] == 0 and slots["queued"] == 0
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        prober.stop()
        for p in (proc_a, proc_b):
            if p.poll() is None:
                p.kill()
                p.wait(timeout=30)
    events = load_events(str(events_dir))
    names = [(e["category"], e["name"]) for e in events]
    assert ("serve", "tail_latency") in names
    assert ("serve", "drain_begin") in names
    assert (("serve", "failover") in names
            or ("serve", "replica_down") in names)
    assert ("fault", "serve.slow_decode") in names  # the injection record
    # the cross-host timeline tells the story in one read
    import timeline_report

    text = "\n".join(timeline_report.timeline_lines(events, width=60))
    assert "tail_latency" in text and "drain_begin" in text
    chains = "\n".join(timeline_report.causal_chains(events))
    assert "-> capture" in chains, chains
