"""CI-style collection guard (ADVICE round 5, high): a single module
with an import-time error aborts the ENTIRE pytest run ("Interrupted: 1
error during collection" — 547 tests never ran because of one missing
``import functools``). This test collects the suite in a subprocess and
fails loudly on any collection error, so the next such typo costs one
red test instead of the whole round's signal."""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_suite_collects_cleanly():
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": ""}
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-p", "no:cacheprovider", "tests/"],
        capture_output=True, text=True, timeout=600, env=env, cwd=ROOT)
    assert r.returncode == 0, (
        "pytest collection failed:\n" + r.stdout[-3000:] + r.stderr[-2000:])
