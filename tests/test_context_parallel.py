"""Context parallelism: ring attention + Ulysses vs the XLA reference.

SURVEY §5.7 — the behavioral spec is torch's ring attention
(torch:distributed/tensor/experimental/_context_parallel/_attention.py:317
forward, :488 backward); here both are validated against full attention on a
(data=2, context=4) mesh of 8 fake CPU devices, including gradients (the
backward ring is autodiff-derived, so this exercises the reverse ppermute
path), GQA head expansion, padding masks (Ulysses), and an end-to-end Llama
train step where CP must reproduce the non-CP loss exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from pytorch_distributed_train_tpu.ops.attention import (
    ContextParallelConfig,
    dot_product_attention,
)
from pytorch_distributed_train_tpu.ops.ring_attention import ring_attention
from pytorch_distributed_train_tpu.ops.ulysses import ulysses_attention


@pytest.fixture(scope="module")
def cp_mesh():
    devs = np.array(jax.devices("cpu")[:8]).reshape(2, 1, 1, 4)
    return Mesh(devs, ("data", "fsdp", "tensor", "context"))


def _qkv(B=4, S=128, H=8, Hkv=None, D=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda h: jnp.asarray(  # noqa: E731
        rng.normal(size=(B, S, h, D)), jnp.float32
    )
    return mk(H), mk(Hkv or H), mk(Hkv or H)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(cp_mesh, causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal, impl="xla")
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh=cp_mesh, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full_attention(cp_mesh, causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal, impl="xla")
    out = jax.jit(
        lambda a, b, c: ulysses_attention(a, b, c, mesh=cp_mesh,
                                          causal=causal, impl="xla")
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_gqa(cp_mesh):
    q, k, v = _qkv(H=8, Hkv=2)
    ref = dot_product_attention(q, k, v, causal=True, impl="xla")
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh=cp_mesh, causal=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_gqa(cp_mesh):
    """GQA both ways: Hkv=4 divides context=4 (late expansion, KV crosses the
    wire un-expanded) and Hkv=2 doesn't (pre-expansion fallback)."""
    for hkv in (4, 2):
        q, k, v = _qkv(H=8, Hkv=hkv)
        ref = dot_product_attention(q, k, v, causal=True, impl="xla")
        out = jax.jit(
            lambda a, b, c: ulysses_attention(a, b, c, mesh=cp_mesh,
                                              causal=True, impl="xla")
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_ulysses_pad_mask(cp_mesh):
    q, k, v = _qkv(B=4, S=128)
    lengths = np.array([128, 96, 64, 32])
    mask = jnp.asarray(
        (np.arange(128)[None, :] < lengths[:, None])[:, None, None, :]
    )  # (B, 1, 1, S)
    ref = dot_product_attention(q, k, v, mask=mask, impl="xla")
    out = jax.jit(
        lambda a, b, c, m: ulysses_attention(a, b, c, mask=m, mesh=cp_mesh,
                                             impl="xla")
    )(q, k, v, mask)
    # compare only unpadded query rows (padded rows attend uniformly; both
    # paths agree there too but carry no meaning)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [0, 48])
def test_ring_windowed_matches_full_attention(cp_mesh, window):
    """Sliding window across the ring: out-of-band hops are skipped, the
    diagonal hop masks the band — must equal the single-device banded
    reference (VERDICT r1 item 6: windowed fast paths)."""
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=True, impl="xla",
                                window=window)
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh=cp_mesh, causal=True,
                                       window=window, impl="xla")
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ulysses_windowed_matches_full_attention(cp_mesh):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=True, impl="xla", window=48)
    out = jax.jit(
        lambda a, b, c: ulysses_attention(a, b, c, mesh=cp_mesh, causal=True,
                                          window=48, impl="xla")
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def _qkv_flash(B=2, S=512, H=4, Hkv=None, D=64, seed=31):
    """Flash-chunk-compatible shapes: D=64 lane-aligned, S_local=128."""
    rng = np.random.default_rng(seed)
    mk = lambda h: jnp.asarray(  # noqa: E731
        rng.normal(size=(B, S, h, D)) * 0.5, jnp.float32
    )
    return mk(H), mk(Hkv or H), mk(Hkv or H)


@pytest.mark.parametrize("window", [0, 100])
def test_ring_pallas_chunks_match_full_attention(cp_mesh, window):
    """Ring with the Pallas flash inner kernel (interpret mode on CPU) —
    the SURVEY §5.7 design: the ring's per-hop attention IS the flash
    kernel, not a dense einsum (VERDICT r1 weak item 3)."""
    q, k, v = _qkv_flash()
    ref = dot_product_attention(q, k, v, causal=True, impl="xla",
                                window=window)
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh=cp_mesh, causal=True,
                                       window=window, impl="pallas")
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_pallas_gradients_match(cp_mesh):
    """The flash-chunk custom VJP (lse-cotangent folded into delta) through
    the full ring: grads must equal the single-device reference."""
    q, k, v = _qkv_flash(B=1)

    g_ring = jax.jit(jax.grad(
        lambda a, b, c: jnp.sum(jnp.square(ring_attention(
            a, b, c, mesh=cp_mesh, causal=True, impl="pallas"))),
        argnums=(0, 1, 2),
    ))(q, k, v)
    g_ref = jax.grad(
        lambda a, b, c: jnp.sum(jnp.square(dot_product_attention(
            a, b, c, causal=True, impl="xla"))),
        argnums=(0, 1, 2),
    )(q, k, v)
    for g1, g2, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=5e-4, rtol=5e-3,
                                   err_msg=f"d{name} mismatch")


def test_ring_pallas_gqa(cp_mesh):
    q, k, v = _qkv_flash(H=8, Hkv=2)
    ref = dot_product_attention(q, k, v, causal=True, impl="xla")
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh=cp_mesh, causal=True,
                                       impl="pallas")
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_ring_zigzag_matches_full_attention(cp_mesh, impl):
    """Causal load-balanced layout (SURVEY §5.7; torch _load_balancer.py):
    the zigzag permutation must be EXACT — attention is permutation-
    equivariant and the masks are position-based."""
    q, k, v = _qkv_flash()
    ref = dot_product_attention(q, k, v, causal=True, impl="xla")
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh=cp_mesh, causal=True,
                                       layout="zigzag", impl=impl)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_ring_zigzag_windowed_and_grads(cp_mesh):
    q, k, v = _qkv_flash(B=1)
    ref = dot_product_attention(q, k, v, causal=True, impl="xla", window=100)
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh=cp_mesh, causal=True,
                                       window=100, layout="zigzag",
                                       impl="pallas")
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    g_z = jax.jit(jax.grad(
        lambda a, b, c: jnp.sum(jnp.square(ring_attention(
            a, b, c, mesh=cp_mesh, causal=True, layout="zigzag",
            impl="pallas"))),
        argnums=(0, 1, 2),
    ))(q, k, v)
    g_ref = jax.grad(
        lambda a, b, c: jnp.sum(jnp.square(dot_product_attention(
            a, b, c, causal=True, impl="xla"))),
        argnums=(0, 1, 2),
    )(q, k, v)
    for g1, g2, name in zip(g_z, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=5e-4, rtol=5e-3,
                                   err_msg=f"d{name} mismatch")


def test_zigzag_perm_properties():
    """zigzag_perm is a permutation pairing chunk i with 2n−1−i, and
    non-causal / indivisible calls ignore the layout knob."""
    from pytorch_distributed_train_tpu.ops.ring_attention import zigzag_perm

    S, n = 64, 4
    p = zigzag_perm(S, n)
    assert sorted(p.tolist()) == list(range(S))
    h = S // (2 * n)
    for i in range(n):
        dev = p[i * 2 * h:(i + 1) * 2 * h]
        assert dev[0] == i * h  # low chunk start
        assert dev[h] == (2 * n - 1 - i) * h  # paired high chunk start


def test_ring_gradients_match(cp_mesh):
    """Backward ring (autodiff-transposed ppermutes) vs full-attention grads."""
    q, k, v = _qkv(B=2, S=128, H=4, D=16)

    def loss(fn):
        return lambda a, b, c: jnp.sum(jnp.square(fn(a, b, c)))

    g_ring = jax.jit(jax.grad(
        loss(lambda a, b, c: ring_attention(a, b, c, mesh=cp_mesh, causal=True)),
        argnums=(0, 1, 2),
    ))(q, k, v)
    g_ref = jax.grad(
        loss(lambda a, b, c: dot_product_attention(a, b, c, causal=True,
                                                   impl="xla")),
        argnums=(0, 1, 2),
    )(q, k, v)
    for g1, g2 in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("impl,layout", [("ring", "contiguous"),
                                         ("ring", "zigzag"),
                                         ("ulysses", "contiguous")])
def test_llama_train_step_cp_matches_dp(impl, layout):
    """End-to-end: one train step of a tiny Llama under CP == without CP."""
    from pytorch_distributed_train_tpu import steps as steps_lib
    from pytorch_distributed_train_tpu.config import (
        MeshConfig, ModelConfig, OptimConfig, PrecisionConfig,
    )
    from pytorch_distributed_train_tpu.losses import get_loss_fn
    from pytorch_distributed_train_tpu.models.registry import build_model
    from pytorch_distributed_train_tpu.optim import make_optimizer
    from pytorch_distributed_train_tpu.parallel.mesh import build_mesh
    from pytorch_distributed_train_tpu.parallel.partition import rules_for_model
    from pytorch_distributed_train_tpu.train_state import TrainState

    model_cfg = ModelConfig(
        name="llama", hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=4, mlp_dim=64, vocab_size=64, max_seq_len=64, remat=False,
    )
    prec = PrecisionConfig()
    tx, _ = make_optimizer(OptimConfig(name="adamw", learning_rate=1e-2), 10)
    loss_fn = get_loss_fn("causal_lm_xent")
    rules = rules_for_model("llama")
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 64, size=(8, 64)), jnp.int32)
    batch = {"input_ids": ids}
    init_rng = jax.random.PRNGKey(7)
    step_rng = jax.random.PRNGKey(11)

    def run(mesh_cfg):
        devs = jax.devices("cpu")[:8]
        mesh = build_mesh(mesh_cfg, devs)
        model = build_model(model_cfg, prec, mesh=mesh, mesh_cfg=mesh_cfg)

        def init(r):
            variables = model.init({"params": r}, ids[:1], train=False)
            return TrainState.create(params=variables["params"], tx=tx)

        state_shape = jax.eval_shape(init, init_rng)
        sharding = steps_lib.state_shardings(mesh, rules, state_shape)
        with mesh:
            state = jax.jit(init, out_shardings=sharding)(init_rng)
            train_step = steps_lib.jit_train_step(
                steps_lib.make_train_step(model, loss_fn, tx), mesh, sharding,
                ("data", "fsdp"),
            )
            new_state, metrics = train_step(state, batch, step_rng)
        leaf = jax.tree_util.tree_leaves(new_state.params)[0]
        return float(metrics["loss"]), np.asarray(leaf)

    loss_dp, leaf_dp = run(MeshConfig(data=8, fsdp=1, tensor=1, context=1))
    loss_cp, leaf_cp = run(
        MeshConfig(data=2, fsdp=1, tensor=1, context=4, context_impl=impl,
                   context_layout=layout)
    )
    assert abs(loss_dp - loss_cp) < 1e-4, (loss_dp, loss_cp)
    np.testing.assert_allclose(leaf_cp, leaf_dp, atol=1e-4, rtol=1e-4)
